"""tpusan golden fixture: malformed / stale suppressions.

Expected findings: bad-suppression at the reason-less and unknown-rule
comments, unused-suppression at the one matching nothing — and the
underlying lock-blocking-call still fires because neither bad comment
suppresses it.
"""

import time


class Sloppy:
    def hold(self):
        with self.mu:
            # tpusan: ok(lock-blocking-call)
            time.sleep(0.01)

    def wrong_rule(self):
        with self.mu:
            # tpusan: ok(no-such-rule) — confidently wrong
            time.sleep(0.01)

    def stale(self):
        # tpusan: ok(lock-nested-loop) — nothing here trips that rule
        return 1
