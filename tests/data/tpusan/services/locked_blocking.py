"""tpusan golden fixture: blocking calls under a lock region.

Expected findings: lock-blocking-call at the sleep, the socket recv,
and the device readback.  Never imported — linted by tests/test_analysis.py.
"""

import time

import jax


class Server:
    def slow_path(self, sock):
        with self._lock:
            time.sleep(0.5)            # finding: sleep under the lock
            data = sock.recv(4096)     # finding: socket read under the lock
            return data

    def readback_locked(self):
        # *_locked suffix: runs under the lock by convention.
        mirror = jax.device_get(self._state)  # finding: device readback
        return mirror
