"""Golden: host dict walks on the decided path (host-walk-in-decided-path).

Three canonical shapes the rule must catch in an RSM apply/drain body:
a direct `self.kv[op.key]` walk, a local-alias walk (`kv = self.kv`),
and a bound-verb alias walk (`kv_get = kv.get`).  The cid-keyed dup
probe must stay clean — the rule keys on the op's `.key`, not on every
dict access.
"""


class Server:
    def __init__(self):
        self.kv = {}
        self.dup = {}
        self.applied = -1

    def evict(self, key):
        # Trim path so the store does not also trip unbounded-host-state
        # (this golden isolates the decided-walk rule).
        self.kv.pop(key, None)
        self.dup.pop(key, None)

    def _apply(self, op):
        seen = self.dup.get(op.cid, -1)  # cid-keyed: NOT a walk finding
        if op.cseq <= seen:
            return None
        if op.kind == "get":
            return self.kv.get(op.key, "")
        self.kv[op.key] = self.kv.get(op.key, "") + op.value
        return ""

    def _apply_batch_locked(self, vals):
        kv = self.kv
        kv_get = kv.get
        for v in vals:
            kv[v.key] = kv_get(v.key, "") + v.value

    def drain_decided(self, runs):
        for run in runs:
            for op in run:
                key = op.key
                self.kv[key] = op.value
