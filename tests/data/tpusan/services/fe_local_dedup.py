"""tpusan golden: frontend-local-dedup — a frontend class keeping its
own at-most-once table.  Both stores below answer retries from memory
only THIS frontend holds; a clerk whose retry migrated to a peer
frontend after a kill would double-apply (or read a stale reply) because
the peer never saw these entries."""


class BadClerkFrontend:
    def __init__(self):
        self._dup_replies = {}
        self._seen = set()

    def handle(self, op):
        if (op.cid, op.cseq) in self._dup_replies:        # local dup hit
            return self._dup_replies[(op.cid, op.cseq)]   # FLAG (subscript)
        self._seen.add((op.cid, op.cseq))                 # FLAG (add)
        reply = self._submit(op)
        self._dup_replies[(op.cid, op.cseq)] = reply
        return reply

    def _submit(self, op):
        return ("OK", op)


class GoodServer:
    """NOT a *Frontend* class: the replicated RSM's dup table is exactly
    where at-most-once belongs — must stay clean."""

    def __init__(self):
        self.dup = {}

    def apply(self, op):
        self.dup[op.cid] = (op.cseq, "OK")
        return "OK"
