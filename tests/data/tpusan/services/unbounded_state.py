"""Golden for unbounded-host-state (ISSUE 14): an RSM apply path that
grows self-attribute stores with no trim/GC/snapshot path anywhere in
the class — every decided op grows host memory forever.  Expected
findings: 2 (the audit log list and the results dict; `self.kv` is
exempt because `_install` rebinds it — the snapshot-replace path)."""


class LeakyServer:
    def __init__(self):
        self.kv = {}
        self.results = {}
        self.audit = []
        self.pending = {}

    def _apply(self, op):
        self.kv[op.key] = op.value          # exempt: _install rebinds it
        self.results[op.cid] = (op.cseq, "ok")   # finding: never trimmed
        self.audit.append((op.cid, op.key))      # finding: never trimmed
        self.pending[op.cid] = op                # exempt: popped below
        return "ok"

    def _resolve(self, cid):
        self.pending.pop(cid, None)

    def _install(self, blob):
        self.kv = dict(blob["kv"])
