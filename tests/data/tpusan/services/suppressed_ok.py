"""tpusan golden fixture: a correctly-justified suppression.

Expected: ZERO active findings — the sleep under the lock is suppressed
with a rule name and a reason, which is the shipped suppression format.
"""

import time


class Cold:
    def drain(self):
        with self._lock:
            # tpusan: ok(lock-blocking-call) — boot-time drain before any
            # client can contend for this lock; pacing is the point.
            time.sleep(0.01)
