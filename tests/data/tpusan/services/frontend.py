"""tpusan golden: blocking-in-eventloop — a frontend event-loop callback
that sleeps, waits on a lock, and makes blocking calls.  Callbacks run ON
the epoll loop (or the driver's notify sweep): decode/enqueue/wake only."""

import time


class BadFrontend:
    def _on_batch(self, conn_id, args, wctx):
        time.sleep(0.001)                 # finding: sleep in callback
        self.big_lock.acquire()           # finding: lock wait
        reply = self.net.call(args)       # finding: blocking RPC leg
        self.ready.wait(0.1)              # finding: event wait
        self.pending.append((conn_id, reply))

    def reply_cb(self, fut):
        with self.mu:                     # finding: `with` on a lock
            self.done.append(fut)

    def _engine_pass(self):
        # NOT a callback (no _on_* / *_cb name): the engine thread may
        # block on the submit handoff — no findings here.
        time.sleep(0.001)
