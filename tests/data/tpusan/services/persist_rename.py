"""tpusan golden fixture: hand-rolled write-then-rename persistence.

Expected findings: durable-write-discipline at BOTH write-opens — each
function reimplements the atomic-persist pattern outside the durafs
seam (no tmp fsync, no dir fsync, no fault injection).
"""

import os
import pickle


def save_meta(path, meta):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:   # finding: bypasses durafs.atomic_write
        f.write(pickle.dumps(meta))
    os.replace(tmp, path)


def save_report(path, text):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:    # finding: same pattern, text mode
        f.write(text)
    os.rename(tmp, path)


def plain_log(path, line):
    # No rename in sight: an append-only log is not the atomic-persist
    # pattern, so this function must NOT trip the rule.
    with open(path, "a") as f:
        f.write(line)
