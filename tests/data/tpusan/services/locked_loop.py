"""tpusan golden fixture: per-cell Python loop under the lock.

Expected findings: lock-nested-loop at the inner loop — the TUNING
round-7 regression shape (per-cell fan-out under the fabric lock).
"""


class Fanout:
    def deliver(self, cells):
        with self.mu:
            for g in range(self.G):
                for i in range(self.I):   # finding: nested loop under lock
                    self.queues[g].append(cells[g][i])
