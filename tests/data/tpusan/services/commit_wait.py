"""Golden: blocking-commit-wait — waiting on a cross-group RPC/future
while holding the server mutex or inside the apply path (the classic
2PC deadlock shape: A's apply blocks on B, B's on A, both logs jam)."""

import threading


class TwoPCServer:
    def __init__(self, peers):
        self.mu = threading.Lock()
        self.peers = peers
        self.prepared = {}

    def _apply_commit(self, op):
        # FINDING: consulting the coordinator group from INSIDE the
        # apply path — the replica can't drain its log past this op
        # until another group answers.
        peer = self.peers[0]
        decision = peer.txn_status(op.tid)
        return decision

    def commit(self, fut, op):
        with self.mu:
            # FINDING: parking on a cross-group future under mu — every
            # clerk op on this server now queues behind a remote group.
            fut.wait(1.0)
            self.prepared.pop(op.tid, None)
