"""tpusan golden fixture: host-state writes inside jit-traced functions.

Expected findings: tracer-leak at the self-attribute write, the closure
container append, and the global statement.
"""

import functools

import jax

TRACE_LOG = []


class Stepper:
    @functools.partial(jax.jit, static_argnums=0)
    def step(self, state):
        out = state + 1
        self.last = out          # finding: tracer into host attribute
        TRACE_LOG.append(out)    # finding: tracer into closure/global list
        return out


def make_step():
    def body(carry, x):
        global _steps            # finding: global write while tracing
        _steps += 1
        return carry + x, x

    return jax.lax.scan(body, 0, None)
