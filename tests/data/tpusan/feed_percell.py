"""tpusan golden fixture: decided-feed consumer bypassing the columnar
contract.

Expected findings: feed-columnar at the private-queue access AND the
module-level "subscribes but never drains columnar" finding.
"""


class Replica:
    def __init__(self, fabric, g, p):
        self.sub = fabric.subscribe_decided(g, p)

    def apply_loop(self):
        while True:
            while self.sub._q:               # finding: private queue
                seqs, vals = self.sub._q.popleft()   # finding: again
                for s, v in zip(seqs, vals):
                    self.apply(s, v)
