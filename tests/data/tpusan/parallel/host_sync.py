"""Golden: host-sync-in-sharded-step — host synchronization inside the
sharded execution path (three findings: np.asarray in a sharded step,
.block_until_ready in a dispatch helper, jax.device_get in a drain)."""

import jax
import numpy as np


def sharded_step_host(state, link):
    out = step(state, link)
    # BAD: materializing the sharded result on the host serializes the
    # whole mesh behind one device round-trip.
    done = np.asarray(out.done)
    return out, done


def _dispatch_done(out):
    # BAD: a barrier inside the per-shard dispatch path.
    out.done.block_until_ready()
    return out.done


def drain_shard(out, shard):
    # BAD: full-array readback inside the drain loop.
    cols = jax.device_get(out.cols)
    return cols[shard]


def sharded_step_clean(state, link):
    # OK: a nested closure handed to jit traces on the device — the
    # host-sync filter must not reach into it.
    def _inner(s, l):
        return np.asarray([1], dtype=np.int32)  # traced as a constant

    return jax.jit(_inner)(state, link)
