"""tpusan golden fixture: nondeterminism in a schedule-deterministic path.

(The filename matters: it puts this fixture in the analyzer's
deterministic-path scope.)  Expected findings: nondet-clock at the wall
clock read and at both process-global RNG draws.
"""

import random
import time


def generate_schedule(duration):
    t0 = time.time()                    # finding: wall clock, not monotonic
    events = []
    while time.monotonic() - t0 < duration:   # monotonic itself is fine
        action = random.choice(["kill", "heal"])   # finding: global RNG
        events.append((random.random(), action))   # finding: global RNG
    return events


def seeded_ok(seed):
    rng = random.Random(seed)  # constructing a seeded RNG is the fix
    return rng.random()
