"""Golden: readback-in-step — a device readback added to the fused step
path (this file's `core/fabric.py` suffix puts it in the step-path lint
scope).  The kernelscope contract is ONE summary readback per dispatch;
each of these adds a host round-trip per step.
"""
import jax


class NotTheFabric:
    def _step_once(self, io, touched_acc, msgs_acc):
        # A second fetch next to the sanctioned summary readback: the
        # exact regression the rule exists to catch.
        decided = jax.device_get(io.decided)          # finding 1
        proto = jax.device_get(io.proto)              # finding 2
        return decided, proto

    def _wait_for_dispatch(self, handle):
        # Blocking on the device future inside the step path stalls the
        # clock thread for the whole dispatch instead of overlapping it.
        handle.block_until_ready()                    # finding 3
        return handle
