"""Golden fixture for the unbounded-retry rule: retry loops with no
deadline/budget/backoff/timeout bound and no pacing sleep (2 findings),
plus bounded shapes that must stay quiet."""

import time

from tpu6824.services.common import Backoff
from tpu6824.utils.errors import RPCError


def call(addr, name, *args):
    raise RPCError("stub")


def spin_retry_no_bound(addr):
    # FINDING: while-True catching RPCError, nothing bounds or paces it.
    while True:
        try:
            return call(addr, "get", "k")
        except RPCError:
            continue


def rotate_retry_no_bound(addrs):
    # FINDING: rotation is not a bound — every endpoint refusing spins
    # this loop at CPU speed.
    i = 0
    while True:
        addr = addrs[i % len(addrs)]
        i += 1
        try:
            return call(addr, "put", "k", "v")
        except RPCError:
            pass


def retry_with_deadline(addr, deadline):
    # quiet: bounded by a deadline check.
    while True:
        try:
            return call(addr, "get", "k")
        except RPCError:
            if time.monotonic() >= deadline:
                raise


def retry_with_backoff(addr):
    # quiet: paced by the budgeted Backoff.
    bo = Backoff()
    while True:
        try:
            return call(addr, "get", "k")
        except RPCError:
            bo.sleep()


def serve_loop(conn):
    # quiet: catches-and-re-raises is not a retry loop.
    while True:
        try:
            conn.recv()
        except RPCError:
            raise
