"""tpusan golden: python-decode-in-native-path — a frontend event-loop
callback decoding frame bytes per op in Python.  Decode belongs to the
native ingest layer (rpcserver.cpp); a Python per-op unpack loop on the
callback thread re-creates the GIL-bound ingest wall (ISSUE 11)."""

import pickle
import struct

_OP = struct.Struct("<BQqHI")


class BadNativeFrontend:
    def _on_batch(self, conn_id, payload, wctx):
        off = 8
        nops = struct.unpack_from("<H", payload, 6)[0]  # header read: ok
        ops = []
        for _ in range(nops):
            kind, cid, cseq, klen, vlen = _OP.unpack_from(payload, off)
            # finding ^: per-op struct unpack in the callback loop
            off += _OP.size
            cseq2 = int.from_bytes(payload[off:off + 8], "little")
            # finding ^: per-op int.from_bytes
            ops.append((kind, cid, cseq, cseq2))
            off += klen + vlen
        self.pending.append((conn_id, ops))

    def reply_cb(self, conn_id, raw):
        out = []
        while raw:
            rep = pickle.loads(raw)   # finding: per-op pickle in a loop
            out.append(rep)
            raw = raw[1:]
        self.done.append((conn_id, out))

    def _engine_pass(self, payload):
        # NOT a callback: the engine thread may decode (it is the
        # fallback decoder's home) — no findings here.
        for _ in range(4):
            struct.unpack_from("<H", payload, 0)
