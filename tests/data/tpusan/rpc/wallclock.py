"""Golden fixture for the wallclock-duration rule (ISSUE 15): durations
computed from the WALL clock in rpc/services/core scope.  Expected: two
active findings (the direct delta and the carried-name delta), the
monotonic function and the bare human-facing timestamp stay clean, and
the justified suppression registers without counting."""

import time


def work():
    pass


class LatencyProbe:
    def op_latency(self):
        t0 = time.time()
        work()
        return time.time() - t0  # finding: wall-clock duration

    def remaining(self, started):
        started = time.time()
        budget = 5.0
        left = budget - (started - 1.0)  # finding: carried wall name
        return left

    def op_latency_monotonic(self):
        t0 = time.monotonic()
        work()
        return time.monotonic() - t0  # clean: monotonic duration

    def stamp(self):
        return time.time()  # clean: a human-facing timestamp, no delta

    def nested_scopes_are_separate(self):
        def _helper():
            t0 = time.time()  # clean: an inner-scope stamp
            return t0

        t0 = time.monotonic()
        _helper()
        # clean: the NESTED def's wall name must not contaminate this
        # scope's monotonic duration (review-caught false positive).
        return time.monotonic() - t0

    def suppressed_delta(self):
        t0 = time.time()
        work()
        # tpusan: ok(wallclock-duration) — golden exemplar of a
        # justified suppression (e.g. diffing two wall timestamps a
        # remote artifact recorded; no monotonic base exists for them)
        return time.time() - t0
