"""Golden: unbounded-obs-buffer — telemetry buffers without a cap.

An obs-layer series that appends forever: the ring deque has no maxlen
and the raw points list grows for the process lifetime.  Pollers
serialize these whole, so the leak lands exactly when observability
matters (long soaks).  3 findings: the uncapped deque construction, the
list append, and the list extend.
"""

from collections import deque


class LeakySeries:
    def __init__(self):
        self.points = []                  # uncapped accumulation target
        self.ring = deque()               # FINDING: deque without maxlen
        self.bounded = deque(maxlen=64)   # fine: capped ring

    def sample(self, t, v):
        self.points.append((t, v))        # FINDING: append, no cap
        self.bounded.append(v)            # fine: ring is capped

    def backfill(self, more):
        self.points.extend(more)          # FINDING: extend, no cap

    def snapshot(self):
        local = []                        # fine: locals are per-call
        local.extend(self.points)
        return local
