"""Golden: blocking-io-in-telemetry-path — disk IO on a telemetry clock.

An obs-layer sampler that opens a file inside its pulse-observer
callback and fsyncs two calls below its fold body.  Both run on clocks
shared with the serving path, so one slow disk turns the observability
plane into the outage.  2 findings: the direct open in the `_on_*`
callback, and the os.fsync reached through the fold's helper chain.
The `sync` method is the sanctioned blackbox cadence seam — its msync
is never flagged — and the drain body's dict store is the compliant
producer shape.
"""

import os


class DiskySampler:
    def __init__(self, mm):
        self._mm = mm
        self.stamps = {}

    def _on_sample(self, pulse, now):
        with open("/tmp/telem.json", "w") as f:   # FINDING: IO in observer
            f.write("{}")

    def fold(self, cids):
        self._spill(cids)

    def _spill(self, cids):
        os.fsync(3)                               # FINDING: via fold->_spill

    def sync(self):
        self._mm.flush()                          # fine: THE cadence seam

    def drain_pass(self, counts):
        self.stamps["n"] = len(counts)            # fine: memory store only

    def sample_rss(self):
        # tpusan: ok(blocking-io-in-telemetry-path) — golden: a tiny
        # procfs read per tick, measured and documented (pulse's RSS
        # gauge shape); procfs never blocks on storage
        with open("/proc/self/statm") as f:
            return f.read()
