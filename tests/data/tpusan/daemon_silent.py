"""tpusan golden fixture: daemon threads dying silently.

Expected findings: daemon-crash-sink at both Thread() spawns (neither
target routes exceptions to the crash sink) and daemon-bare-except at
the swallow-everything handler inside the run loop.
"""

import threading


class Service:
    def start(self):
        t = threading.Thread(target=self._loop, daemon=True)  # finding
        t.start()
        threading.Thread(target=_orphan, daemon=True).start()  # finding

    def _loop(self):
        while not self.dead:
            try:
                self.tick()
            except Exception:   # finding: swallowed, nothing recorded
                pass


def _orphan():
    while True:
        pass
