"""GOLDEN (consan): a named hot lock missing from the canonical
manifest.  Naming a lock via utils.locks is a claim that it is part of
the enforced hierarchy — a name absent from MANIFEST has no rank, so
neither the static pass nor the runtime lockdep can order it.
"""

from tpu6824.utils.locks import new_lock


class RogueService:
    def __init__(self):
        self._state_mu = new_lock("rogue.state_mu")
        self.rows = 0

    def bump(self):
        with self._state_mu:
            self.rows += 1
