"""GOLDEN (consan): lock-protection inconsistency across thread
classes.  `rows` is written under mu on the spawned ticker thread but
read lock-free from the RPC surface — the devapply mirror race shape
(PR 15): correct under the GIL by accident, a real race without it.
"""

import threading

from tpu6824.utils.locks import new_lock


class MixedTraffic:
    def __init__(self, srv):
        self.mu = new_lock("kvpaxos.mu")
        self.rows = 0
        self._ticker = threading.Thread(target=self._loop, daemon=True)
        srv.register("Rows", self.rows_view)

    def _loop(self):
        while True:
            with self.mu:
                self.rows += 1

    def rows_view(self):
        return self.rows
