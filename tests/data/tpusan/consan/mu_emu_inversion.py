"""GOLDEN (consan): seeded mu→emu lock-order inversion.

The PR 15/16 nightmare shape: the sanctioned order is server mutex →
engine leaf (kvpaxos.mu → devapply.emu), but `backward()` takes the
engine leaf first and then re-enters the server mutex through a helper
— an interprocedural AB/BA cycle no single function shows.

This golden is double-duty: consan must find the cycle STATICALLY
(lock-order-cycle, plus lock-manifest-order for the backward edge), and
the runtime test imports it under lockwatch and drives both paths so
the SAME inversion is caught live (graph cycle + manifest order
violation).  One seeded bug, both halves of the sanitizer.
"""

from tpu6824.utils.locks import new_rlock


class InvertedServer:
    def __init__(self):
        self.mu = new_rlock("kvpaxos.mu")
        self.emu = new_rlock("devapply.emu")
        self.applied = 0

    def forward(self):
        # The sanctioned order: server mutex, then engine leaf.
        with self.mu:
            self._drain()

    def _drain(self):
        with self.emu:
            self.applied += 1

    def backward(self):
        # The seeded inversion: engine leaf first, then the helper
        # re-enters the server mutex.
        with self.emu:
            self._publish()

    def _publish(self):
        with self.mu:
            self.applied += 1
