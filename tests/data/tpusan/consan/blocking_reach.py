"""GOLDEN (consan): blocking call reachable under a held server mutex.
The sleep is two calls away from the lock region — lexically invisible
to the per-file lock-blocking-call rule, only the interprocedural reach
analysis connects `apply`'s held mu to `_backoff`'s sleep.
"""

import time

from tpu6824.utils.locks import new_lock


class SlowServer:
    def __init__(self):
        self.mu = new_lock("kvpaxos.mu")
        self.applied = 0

    def apply(self):
        with self.mu:
            self._settle()

    def _settle(self):
        self._backoff()

    def _backoff(self):
        time.sleep(0.05)
