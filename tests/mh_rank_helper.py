"""One rank of the multi-OS-process mesh validation (invoked by
tests/test_multihost_process.py as a subprocess per rank; 2- and
4-process meshes).

Usage: python tests/mh_rank_helper.py <rank> <nproc> <coordinator_port>
"""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")


def main():
    rank, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    from tpu6824.parallel.multihost import init_multihost

    init_multihost(coordinator_address=f"127.0.0.1:{port}",
                   num_processes=nproc, process_id=rank)

    import numpy as np
    import jax.numpy as jnp

    from tpu6824.core.kernel import apply_starts, init_state
    from tpu6824.parallel.mesh import place_state, sharded_step
    from tpu6824.parallel.multihost import dcn_safe, make_multihost_mesh

    devs = jax.devices()
    assert len(devs) == 4 * nproc, len(devs)
    assert len(jax.local_devices()) == 4

    mesh = make_multihost_mesh(devs)
    assert dcn_safe(mesh), dict(mesh.shape)

    G, I, P = 16, 4, 4
    state = init_state(G, I, P)
    sa = np.zeros((G, I, P), bool)
    sv = np.full((G, I, P), -1, np.int32)
    sa[:, :, 0] = True
    sv[:, :, 0] = np.arange(G * I).reshape(G, I) + 1
    state = apply_starts(state, jnp.zeros((G, I), bool), jnp.asarray(sa),
                         jnp.asarray(sv))
    state = place_state(state, mesh)
    link = jnp.ones((G, P, P), bool)
    done = jnp.full((G, P), -1, jnp.int32)
    dr = jnp.zeros((G, P, P), jnp.float32)

    step = sharded_step(mesh)
    state, io = step(state, link, done, jax.random.key(0), dr, dr)
    # The global array spans both processes; verify this rank's shards.
    for shard in state.decided.addressable_shards:
        assert (np.asarray(shard.data) >= 0).all(), \
            "multi-process sharded step failed to decide (local shard)"
    print(f"RANK-OK {rank} mesh={dict(mesh.shape)} msgs={int(io.msgs)}",
          flush=True)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
