"""Decentralized consensus backend plumbing shared by the services.

`StructOpPeer` adapts a `core.hostpeer.HostPaxosPeer` (per-message gob RPC
consensus) to the PaxosPeer contract the services program against, shipping
each service's NamedTuple ops as registered gob structs — the exact shape of
the reference's `gob.Register(Op{})` calls that let Op values ride the
`interface{}` fields of the Paxos wire (`paxos/rpc.go:61,67,79`).

A service adds a wire schema + two converters and gains one-replica-per-
OS-process deployment with no shared fabric (see `kvpaxos.make_host_replica`
and `shardmaster.make_host_cluster`)."""

from __future__ import annotations

from tpu6824.shim.gob import Struct, complete


class StructOpPeer:
    """PaxosPeer contract over a HostPaxosPeer with typed struct values.

    `to_wire(op) -> dict` and `from_wire(dict) -> op` must round-trip
    exactly (the RSM layers compare decided ops to proposed ops for
    ownership, e.g. kvpaxos/server.go:69-113's "mine?" check)."""

    def __init__(self, host_peer, name: str, schema: Struct,
                 to_wire, from_wire):
        self.hp = host_peer
        self.name = name
        self.schema = schema
        self.to_wire = to_wire
        self.from_wire = from_wire

    def start(self, seq: int, op) -> None:
        self.hp.start(seq, (self.name, self.to_wire(op)))

    def status(self, seq: int):
        fate, wrapped = self.hp.status_wrapped(seq)
        if wrapped is None:
            return fate, None
        name, v = wrapped
        if name != self.name:
            raise TypeError(
                f"value of type {name!r} in this group's log — this adapter "
                f"only shares a log with {self.name!r} proposers")
        # gob omits zero-valued fields on the wire; restore before decoding.
        return fate, self.from_wire(complete(self.schema, v))

    def done(self, seq: int) -> None:
        self.hp.done(seq)

    def min(self) -> int:
        return self.hp.min()

    def max(self) -> int:
        return self.hp.max()

    def set_participation_floor(self, seq: int, force: bool = False) -> None:
        """Amnesiac-rejoin guard passthrough (HostPaxosPeer docstring)."""
        self.hp.set_participation_floor(seq, force=force)

    def participation_floor(self) -> int:
        return self.hp.participation_floor()

    def kill(self) -> None:
        self.hp.kill()


def make_host_replica(sockdir: str, prefix: str, name: str, schema: Struct,
                      make_server, nservers: int, me: int,
                      seed: int | None = None,
                      persist_dir: str | None = None,
                      **peer_kw):
    """One decentralized replica: a gob Paxos peer endpoint at
    `{sockdir}/{prefix}-{me}` plus the service RSM built by
    `make_server(host_op_peer)`.  With `persist_dir` the peer's consensus
    state is crash-durable (see HostPaxosPeer).  Extra keywords (pooled=,
    parallel_fanout=, ...) pass through to HostPaxosPeer, so services can
    run on the optimized connection profiles.  Returns (host_peer,
    server)."""
    from tpu6824.core.hostpeer import HostPaxosPeer
    from tpu6824.shim.wire import default_registry

    registry = default_registry().register(name, schema)
    addrs = [f"{sockdir}/{prefix}-{i}" for i in range(nservers)]
    peer = HostPaxosPeer(addrs, me, registry=registry, seed=seed,
                         persist_dir=persist_dir, **peer_kw)
    return peer, make_server(peer)


def make_host_cluster(sockdir: str, prefix: str, name: str, schema: Struct,
                      make_server, nservers: int, seed: int | None = None,
                      **peer_kw):
    """All replicas in one process (tests); one-per-process deployments call
    make_host_replica directly."""
    pairs = [
        make_host_replica(sockdir, prefix, name, schema, make_server,
                          nservers, i,
                          seed=None if seed is None else seed + i, **peer_kw)
        for i in range(nservers)
    ]
    return [p for p, _ in pairs], [s for _, s in pairs]
