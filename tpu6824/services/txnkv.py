"""txnkv — cross-group atomic transactions: 2PC over Paxos groups
(ISSUE 13, ROADMAP item 5; design shape per arxiv 1906.01365,
*Reconfigurable Atomic Transaction Commit*).

The widest workload class shardkv cannot serve alone is a multi-key
operation SPANNING groups — a cross-shard transfer, a multi-key CAS.
This module layers classic two-phase commit on the per-group Paxos logs
so 2PC state is replicated and crash-recoverable for free:

  - **Participants**: `txn_prepare` / `txn_commit` / `txn_abort` are
    ordinary shardkv log entries (plain `Op`s whose kind is one of
    `TXN_KINDS` and whose value carries a JSON payload), applied
    deterministically by every replica of the group.  A prepare locks
    its keys IN THE APPLY PATH — conflicting ordinary ops (and
    conflicting prepares) answer `ErrTxnLocked` and retry through the
    existing clerk `Backoff` budget; the vote (yes + read values, or a
    deterministic `ErrTxnAbort` on a failed CAS expectation) is itself
    the replicated log entry's reply, so a replica crash forgets
    nothing.
  - **Coordinator record — the single commit point**: `txn_coord
    {tid, decision}` is a log entry in the COORDINATOR group whose
    apply is first-writer-wins: whichever decision reaches that group's
    Paxos log first IS the transaction's fate, forever.  The clerk
    proposes `commit` after a full prepare quorum; a participant's
    recovery path proposes `abort` for a transaction whose coordinator
    record never appeared — the race is settled by log order, so a
    clerk crash between prepare-quorum and commit-record can never
    yield a half-applied transaction.
  - **Reconfiguration safety** (the hard part and the point): a shard
    migrating mid-commit carries its prepared-lock table inside
    `XState.txn` (`transfer_state`), and the new owner installs the
    inherited prepares — the keys stay locked — then resolves them by
    consulting the coordinator record (`_txn_resolve_pass` on the
    shardkv ticker) before the keys can serve conflicting ops.
    Kill-mid-commit + `reconfig` + dirty-disk reboot converge to the
    coordinator's decision from any interleaving.

Two clerk surfaces:

  - `TxnClerk` — in-process (directory + shardmaster config), the
    harness/bench surface: `txn(ops)`, `multi_cas`, `transfer`.
  - `TxnFrontendClerk` — the WIRE surface: phases ride the
    ClerkFrontend's existing multi-group `route=` machinery as new
    frame kinds (`txn_*`, caps-gated behind the `fe_txn` capability —
    old clerks/servers interop unchanged in both directions; see
    rpc/wire.py).

Payloads are JSON (text-safe on every wire path, incl. the binary fe
frame's utf-8 value field).  The decentralized gob host backend does
NOT speak txn ops (guarded loudly in shardkv's wire codec).

Pinned tradeoffs (ROADMAP item-5 successor list):
  - coordinator decision records (`txn_decisions`) are bounded by
    RESOLUTION-TIED GC (ISSUE 14, closing successor item 5e): every
    participant portion acks at finish-apply (`txn_ack`, origin gids
    carried through reconfiguration in XState.txn), the last ack
    stamps a resolved watermark, and a replicated `compact` entry
    trims the row only after resolution + DECISION_LINGER_OPS more
    applied ops (DECISION_MAX_OPS is the fallback for records that can
    never be fully acked).  The trim-safety invariant stands: no
    trimmed decision is ever consulted — counted by
    `txn.trimmed_decision_consults`, asserted zero under the
    kill_mid_commit + lag_revive soaks;
  - `ErrTxnLocked` is a NEW error on the shared plain-op surface:
    clerks from this PR on retry it (same cseq, Backoff-paced), but a
    pre-txn clerk sees it as a terminal error for the lock window —
    deployments running transactions should run upgraded clerks.
"""

from __future__ import annotations

import json
import threading
import time

from tpu6824.obs import metrics as _metrics
from tpu6824.obs import tracing as _tracing
from tpu6824.ops.hashing import key2shard
from tpu6824.services import shardmaster
from tpu6824.services.common import Backoff, FlakyNet, fresh_cid
from tpu6824.utils import crashsink
from tpu6824.utils.locks import new_lock
from tpu6824.utils.errors import (
    OK,
    ErrTxnAbort,
    ErrTxnLocked,
    ErrWrongGroup,
    RPCError,
)

# The transactional kinds a shardkv log may carry (ISSUE 13; `txn_ack`
# added by ISSUE 14's resolution-tied decision GC).  The first four are
# also the caps-gated fe wire kind extension — see rpc/wire.py
# TXN_KINDS; `txn_ack` is participant→coordinator plumbing that never
# rides a clerk frontend (resolvers propose it via the directory).
TXN_KINDS = frozenset(
    ("txn_prepare", "txn_commit", "txn_abort", "txn_coord", "txn_ack"))

# Sub-op kinds inside a prepare payload: read (lock + report value),
# put/append (lock + buffered write), cas (lock + expectation check +
# buffered write).
TXN_OP_KINDS = ("read", "put", "append", "cas")

COMMIT = "commit"
ABORT = "abort"

# Participant-side recovery pacing (liveness only — SAFETY rests on the
# coordinator record): how old a prepared entry must be before the
# ticker consults the coordinator, and before a decision-less entry may
# be ABORTED at the coordinator (first-writer-wins vs the clerk's
# commit).  Inherited entries consult promptly — a migrated-in prepare
# blocks its keys until resolved.
import os as _os

RESOLVE_AFTER = float(_os.environ.get("TPU6824_TXN_RESOLVE_AFTER", 0.5))
ABORT_AFTER = float(_os.environ.get("TPU6824_TXN_ABORT_AFTER", 2.0))

# Decision/record GC horizons (ISSUE 14, horizon) — all in APPLIED OPS
# of the owning group's log, applied only at replicated `compact`
# entries so every replica trims identically.  The trim-safety
# invariant: a `txn_decisions` row may go ONLY once no unresolved
# prepare can ever consult it — every participant portion acked
# (`txn_ack`, tracked per decision in `txn_decision_waits`) AND
# `DECISION_LINGER_OPS` more ops applied (covers a split-portion ack
# racing its sibling's finish).  `DECISION_MAX_OPS` is the fallback for
# decisions that can never be fully acked (an abort recorded before
# some participant ever prepared) — far beyond any clerk retry window.
# `DONE_LINGER_OPS` replaces PR 12's naive `txn_done` size cap: rows
# now retire on the same log-progress watermark (stamped at recording
# seq), so a slow clerk's outcome poll can't find its row evicted by a
# burst of unrelated transactions — eviction needs the log to advance
# `DONE_LINGER_OPS` past the row, not merely 4096 newer txns.
DECISION_LINGER_OPS = int(
    _os.environ.get("TPU6824_TXN_DECISION_LINGER_OPS", 1024))
DECISION_MAX_OPS = int(
    _os.environ.get("TPU6824_TXN_DECISION_MAX_OPS", 65536))
DONE_LINGER_OPS = int(
    _os.environ.get("TPU6824_TXN_DONE_LINGER_OPS", 8192))
# Legacy `txn_done` bound for deployments running WITHOUT the horizon
# machinery (no compact entries → the linger watermark never advances):
# _record_done falls back to this deterministic apply-order cap.
DONE_CAP = int(_os.environ.get("TPU6824_TXN_DONE_CAP", 4096))

# tpuscope metrics (module scope per the metric-unregistered rule).
_M_BEGIN = _metrics.counter("txn.begin")
_M_COMMIT = _metrics.counter("txn.commit")
_M_ABORT = _metrics.counter("txn.abort")
_M_LOCK_CONFLICTS = _metrics.counter("txn.lock_conflicts")
_M_INHERITED = _metrics.counter("txn.inherited_prepares")
_G_INFLIGHT = _metrics.gauge("txn.inflight")
# horizon decision GC (ISSUE 14)
_M_ACKS = _metrics.counter("txn.acks")
_M_DECISIONS_TRIMMED = _metrics.counter("txn.decisions_trimmed")
_M_DONE_TRIMMED = _metrics.counter("txn.done_trimmed")
# The trim-safety sentinel: a consult (txn_status / local decision
# read) for a tid whose decision row was TRIMMED.  Nonzero means the
# resolution-tied GC un-decided a transaction's record while someone
# still needed it — the soaks assert this stays zero.
_M_TRIMMED_CONSULTS = _metrics.counter("txn.trimmed_decision_consults")

_inflight_mu = new_lock("txnkv.inflight_mu")
_inflight_n = 0


def _inflight_add(d: int) -> None:
    global _inflight_n
    with _inflight_mu:
        _inflight_n += d
        _G_INFLIGHT.set(_inflight_n)


class TxnAborted(Exception):
    """The transaction's coordinator decision is ABORT (CAS expectation
    failed, lock-wait budget exhausted, or a recovery abort won the
    commit-point race).  The caller may safely retry with a fresh
    transaction."""


class TxnAbandoned(RPCError):
    """Raised by an armed mid-commit kill hook: the clerk dies between
    prepare-quorum and commit-record, leaving the transaction's fate to
    the participant resolvers + the coordinator log."""


# ------------------------------------------------------------- payloads
# JSON in Op.value: text-safe on the pickled frame, the binary fe frame
# (utf-8 value bytes), and in-process calls alike.


def encode_prepare(tid: str, coord: int, coord_srv, tops,
                   gids=None) -> str:
    """tops: iterable of (key, kind, value, expect) sub-ops.  `gids`
    (ISSUE 14): the FULL participant gid list, so any participant's
    resolver can tell the coordinator who must ack before the decision
    record may ever be trimmed."""
    d = {"tid": tid, "coord": int(coord),
         "coord_srv": list(coord_srv),
         "ops": [list(t) for t in tops]}
    if gids is not None:
        d["gids"] = [int(g) for g in gids]
    return json.dumps(d, separators=(",", ":"))


def encode_finish(tid: str) -> str:
    return json.dumps({"tid": tid}, separators=(",", ":"))


def encode_coord(tid: str, decision: str, gids=None) -> str:
    """`gids` (ISSUE 14): the participant gids whose acks resolve this
    decision — ALL participants for a commit, the PREPARED set for a
    clerk abort.  Absent (old writers / resolver without the list) the
    decision is never fast-trimmed; only the DECISION_MAX_OPS fallback
    horizon reaps it."""
    d = {"tid": tid, "decision": decision}
    if gids is not None:
        d["gids"] = [int(g) for g in gids]
    return json.dumps(d, separators=(",", ":"))


def encode_ack(tid: str, gid: int) -> str:
    return json.dumps({"tid": tid, "gid": int(gid)},
                      separators=(",", ":"))


def decode_payload(value: str) -> dict:
    return json.loads(value)


# ------------------------------------------------------- the RSM logic
# Called from ShardKVServer._apply under the server mutex — pure state
# transition, deterministic across replicas, no I/O, no clock reads in
# anything that decides an outcome (the monotonic stamp below only paces
# the resolver, never picks a fate).


def apply_txn(srv, op) -> tuple[tuple, bool]:
    """Apply one decided transactional op to `srv` (a ShardKVServer).
    Returns (reply, record): `record` is False for the retryable
    outcomes (`ErrTxnLocked`, `ErrWrongGroup`) that must NOT enter the
    dup filter — the clerk re-sends the same cseq after backoff."""
    p = decode_payload(op.value)
    tid = p["tid"]
    seq = srv.applied + 1  # the seq this op applies at (caller bumps after)
    if op.kind == "txn_coord":
        # The single commit point: first decision to reach this group's
        # log wins; every later proposal reads the recorded fate.
        d = srv.txn_decisions.get(tid)
        if d is None:
            d = p["decision"]
            srv.txn_decisions[tid] = d
            srv.txn_decision_seq[tid] = seq
            gids = p.get("gids")
            if gids:
                # Resolution tracking (ISSUE 14): the decision row may
                # be trimmed only once every one of these participant
                # gids has acked its finish-apply (+ linger).  Without
                # the list, only the MAX_OPS fallback ever reaps it.
                srv.txn_decision_waits[tid] = {int(g) for g in gids}
        return (OK, d), True

    if op.kind == "txn_ack":
        # A participant portion finished applying the decision: discard
        # it from the decision's wait set; the last ack stamps the
        # resolution watermark the compact-entry GC trims against.
        gid = int(p["gid"])
        waits = srv.txn_decision_waits.get(tid)
        if waits is not None:
            waits.discard(gid)
            if not waits:
                del srv.txn_decision_waits[tid]
                srv.txn_resolved[tid] = seq
        return (OK, ""), True

    if op.kind == "txn_prepare":
        tops = tuple(tuple(t) for t in p["ops"])
        ent = srv.txn_prepared.get(tid)
        if ent is not None and tops == ent["ops"]:
            # True replay (re-proposed / retried, identical sub-ops):
            # idempotent, return the recorded reads.
            return (OK, json.dumps(ent["reads"])), True
        # NOTE a same-tid prepare with DIFFERENT sub-ops is NOT a
        # replay: a stale route can land another group's portion here
        # (reads for the wrong keys would silently alias — the partial-
        # read bug the pallas soak caught), and a clerk whose config
        # lags can legitimately send two portions to one group that
        # owns both.  Fall through: the incoming portion passes the
        # SAME ownership/lock/CAS gauntlet and merges into the entry.
        done = srv.txn_done.get(tid)
        if done is not None:  # terminal: the txn already finished here
            return ((OK, "{}") if done == COMMIT
                    else (ErrTxnAbort, "")), True
        for key, _k, _v, _e in tops:
            if not srv._owns(key):
                # Not recorded: the clerk re-queries the config and
                # retries the whole transaction (shardkv's contract).
                return (ErrWrongGroup, ""), False
        for key, _k, _v, _e in tops:
            holder = srv.txn_locks.get(key)
            if holder is not None and holder != tid:
                _M_LOCK_CONFLICTS.inc()
                return (ErrTxnLocked, ""), False
        reads: dict[str, str] = {}
        for key, k, _v, exp in tops:
            cur = srv.kv.get(key, "")
            if k == "cas" and cur != exp:
                # Deterministic vote NO — recorded, the txn aborts.
                return (ErrTxnAbort, key), True
            if k in ("read", "cas"):
                reads[key] = cur
        for key, _k, _v, _e in tops:
            srv.txn_locks[key] = tid
        if ent is not None:  # second portion at the true owner: merge
            ent["ops"] = tuple(dict.fromkeys(ent["ops"] + tops))
            ent["reads"].update(reads)
        else:
            srv.txn_prepared[tid] = {
                "coord": int(p["coord"]),
                "coord_srv": tuple(p.get("coord_srv", ())),
                "ops": tops, "reads": reads,
                "t": time.monotonic(), "inherited": False,
                # ISSUE 14: the full participant list (resolver→coord
                # recovery payloads carry it) and this portion's ORIGIN
                # gid(s) — what the coordinator's decision-GC wait set
                # expects the finish-apply ack to name, carried through
                # reconfiguration in XState.txn.
                "gids": tuple(int(g) for g in p.get("gids", ())) or None,
                "origins": {srv.gid},
            }
        return (OK, json.dumps(reads)), True

    # txn_commit / txn_abort — applies wherever the tid is prepared and
    # is a decision RECORD everywhere else: a commit landing at a new
    # shard owner BEFORE the migrated prepare arrives must not be lost
    # (the reconf apply replays it against the inherited entry), and a
    # commit landing at the pre-reconfig donor applies to its stale copy
    # harmlessly.  NO ownership check — the fix-en-route semantics
    # (ISSUE 13): prepared transactions outlive the shard map.
    decision = COMMIT if op.kind == "txn_commit" else ABORT
    ent = srv.txn_prepared.pop(tid, None)
    if ent is not None:
        _release_locks(srv, tid, ent)
        # _test_partial_commit: the PR 3-style atomicity fault hook — a
        # committing group drops its writes, manufacturing exactly the
        # half-applied transaction the checker must catch.
        if decision == COMMIT \
                and not getattr(srv, "_test_partial_commit", False):
            _apply_writes(srv, ent["ops"])
        # Participant ack at finish-apply (ISSUE 14): this portion will
        # never again consult the coordinator decision — owe an ack per
        # origin gid (volatile send-queue, drained by the ticker's
        # ack_pass; the coordinator's dup filter makes resends free).
        for origin in (ent.get("origins") or (srv.gid,)):
            srv._txn_acks_owed[(tid, int(origin))] = (
                ent["coord"], tuple(ent["coord_srv"]))
    prior = srv.txn_done.get(tid)
    if prior is None:
        _record_done(srv, tid, decision, seq)
        prior = decision
    return (OK, prior), True


def _release_locks(srv, tid: str, ent: dict) -> None:
    for key, _k, _v, _e in ent["ops"]:
        if srv.txn_locks.get(key) == tid:
            del srv.txn_locks[key]


def _apply_writes(srv, tops) -> None:
    for key, k, val, _e in tops:
        if k in ("put", "cas"):
            srv.kv[key] = val
        elif k == "append":
            srv.kv[key] = srv.kv.get(key, "") + val


def _record_done(srv, tid: str, decision: str, seq: int) -> None:
    # ISSUE 14: no size cap on the horizon path (PR 12's naive
    # `txn_done` cap could evict a row a slow clerk's outcome poll
    # still needed under a burst of unrelated transactions) — rows are
    # stamped with their recording seq and retired by the compact
    # entry's DONE_LINGER_OPS log-progress watermark instead,
    # deterministically on every replica.
    srv.txn_done[tid] = decision
    srv.txn_done_seq[tid] = seq
    hz = getattr(srv, "horizon", None)
    if hz is None or not hz.enabled():
        # Compaction OFF (no snapshot cadence → no compact entries →
        # the watermark never advances): keep the legacy deterministic
        # cap as the memory bound, trimmed in apply order exactly as
        # PR 12 did.  Horizon config must be uniform across a group
        # (like every other replicated knob) for trims to stay
        # log-deterministic.
        while len(srv.txn_done) > DONE_CAP:
            old = next(iter(srv.txn_done))
            del srv.txn_done[old]
            srv.txn_done_seq.pop(old, None)


def prune_for_import(srv, imported_shards) -> None:
    """Reconf-apply prelude (review hardening, ISSUE 13): when shards
    are IMPORTED, the incoming XState.txn is the AUTHORITATIVE set of
    surviving prepares for them — any LOCAL prepared portion covering
    those shards is a stale leftover from a previous ownership stint
    (the shard migrated away, its 2PC state was resolved elsewhere,
    and it migrated back).  Without this prune, the stale entry's
    resolver would later read the eternal coordinator COMMIT and
    re-apply old buffered writes over newer committed state (a lost
    update; a double-apply for appends).  Deterministic: pure function
    of RSM state, applied in log order on every replica."""
    if not srv.txn_prepared:
        return
    dead_tids = []
    for tid, ent in srv.txn_prepared.items():
        kept = tuple(t for t in ent["ops"]
                     if key2shard(t[0]) not in imported_shards)
        if len(kept) == len(ent["ops"]):
            continue
        for key, _k, _v, _e in ent["ops"]:
            if key2shard(key) in imported_shards \
                    and srv.txn_locks.get(key) == tid:
                del srv.txn_locks[key]
        if kept:
            ent["ops"] = kept
            ent["reads"] = {k: v for k, v in ent["reads"].items()
                            if key2shard(k) not in imported_shards}
        else:
            dead_tids.append(tid)
    for tid in dead_tids:
        del srv.txn_prepared[tid]


def _row_origins(row, default) -> set:
    """Origin gid set of an XState.txn row: 5-tuples carry it (int or
    tuple — ISSUE 14's resolved-watermark plumbing); legacy 4-tuples
    default to the installer's own gid (the fallback horizon covers
    the un-matchable ack)."""
    if len(row) > 4:
        o = row[4]
        return {int(x) for x in (o if isinstance(o, (tuple, list))
                                 else (o,))}
    return {int(default)}


def install_inherited(srv, txn_entries) -> None:
    """Reconf-apply half of reconfiguration safety: install the
    prepared entries that traveled with the shard state (`XState.txn`).
    Keys re-lock under the new owner; a decision that arrived BEFORE
    the migration (recorded in txn_done) replays against the inherited
    writes immediately."""
    for row in txn_entries:
        tid, coord, coord_srv, tops = row[0], row[1], row[2], row[3]
        origins = _row_origins(row, srv.gid)
        tops = tuple(tuple(t) for t in tops)
        done = srv.txn_done.get(tid)
        if done is not None:
            if done == COMMIT:
                _apply_writes(srv, tops)
            # The migrated portion is already finished here: it still
            # owes the coordinator its origin's ack (the resolved
            # watermark travels WITH the shard — ISSUE 14).
            for origin in origins:
                srv._txn_acks_owed[(tid, origin)] = (
                    int(coord), tuple(coord_srv))
            continue
        ent = srv.txn_prepared.get(tid)
        if ent is not None:
            # A second donor's portion of the same transaction: merge.
            merged = tuple(dict.fromkeys(ent["ops"] + tops))
            ent["ops"] = merged
            ent["origins"] = set(ent.get("origins") or ()) | origins
            for key, _k, _v, _e in tops:
                srv.txn_locks[key] = tid
            continue
        for key, _k, _v, _e in tops:
            srv.txn_locks[key] = tid
        srv.txn_prepared[tid] = {
            "coord": int(coord), "coord_srv": tuple(coord_srv),
            "ops": tops, "reads": {},
            "t": time.monotonic(), "inherited": True,
            "gids": None, "origins": origins,
        }
        _M_INHERITED.inc()


def export_prepared(srv, shards_list) -> tuple:
    """Donor half (`transfer_state`): the prepared-lock-table rows whose
    keys fall in the migrating shards, in XState.txn shape —
    (tid, coord_gid, coord_srv, sub-ops, origin-gids).  The origin
    column is the per-group resolved watermark's identity: whoever
    finally applies this portion's finish acks THESE gids at the
    coordinator, however many migrations later."""
    out = []
    for tid, ent in sorted(srv.txn_prepared.items()):
        tops = tuple(t for t in ent["ops"]
                     if key2shard(t[0]) in shards_list)
        if tops:
            out.append((tid, ent["coord"], tuple(ent["coord_srv"]), tops,
                        tuple(sorted(ent.get("origins") or (srv.gid,)))))
    return tuple(out)


# --------------------------------------------------------- the resolver
# Runs on the shardkv ticker thread, NEVER under the server mutex and
# never inside _apply (the tpusan `blocking-commit-wait` shape): consult
# the coordinator, then drive the outcome through this group's OWN log.


def resolve_pass(srv, limit: int = 4) -> int:
    """One recovery pass over srv's aged/inherited prepared entries.
    Returns the number of transactions resolved."""
    now = time.monotonic()
    with srv.mu:
        if srv.dead or not srv.txn_prepared:
            return 0
        cands = []
        for tid, ent in srv.txn_prepared.items():
            age_floor = (getattr(srv, "txn_resolve_inherited", 0.05)
                         if ent["inherited"]
                         else getattr(srv, "txn_resolve_after",
                                      RESOLVE_AFTER))
            if now - ent["t"] >= age_floor:
                cands.append((tid, dict(ent)))
            if len(cands) >= limit:
                break
    resolved = 0
    for tid, ent in cands:
        d = consult_coordinator(srv, ent, tid)
        if d is None:
            if now - ent["t"] < getattr(srv, "txn_abort_after",
                                        ABORT_AFTER):
                continue
            # No decision anywhere and the clerk is presumed dead:
            # race an ABORT into the coordinator log.  First writer
            # wins — if the clerk's commit got there first, we read
            # COMMIT back and apply it.
            d = decide_at_coordinator(srv, ent, tid, ABORT)
        if d is None:
            continue
        kind = "txn_commit" if d == COMMIT else "txn_abort"
        from tpu6824.services.shardkv import Op as _SOp
        op = _SOp(kind, "", encode_finish(tid), f"txr-{tid}", 1, None)
        try:
            with srv.mu:
                if tid not in srv.txn_prepared:
                    continue  # another path finished it meanwhile
                srv._sync(op)
            resolved += 1
        except RPCError:
            continue
    return resolved


def _coord_servers(srv, ent):
    names = ent["coord_srv"]
    if not names:
        # Fallback: shardkv servers self-register as "g<gid>-<me>".
        pfx = f"g{ent['coord']}-"
        names = tuple(sorted(n for n in srv.directory if n.startswith(pfx)))
    return names


def consult_coordinator(srv, ent, tid: str):
    """The coordinator record's decision for tid, or None (no decision
    yet / coordinator unreachable).  Decisions are write-once, so a
    stale read can only under-report — never lie."""
    if ent["coord"] == srv.gid:
        d = srv.txn_decisions.get(tid)  # lock-free: write-once value
        if d is None and tid in srv._trimmed_tids:
            _M_TRIMMED_CONSULTS.inc()  # the trim-safety sentinel
        return d
    for name in _coord_servers(srv, ent):
        peer = srv.directory.get(name)
        if peer is None or peer is srv:
            continue
        try:
            d = peer.txn_status(tid)
        except Exception:  # noqa: BLE001 — dead/partitioned peer: next
            continue
        if d is not None:
            return d
    return None


def decide_at_coordinator(srv, ent, tid: str, decision: str):
    """Propose `decision` into the coordinator group's log (first
    writer wins); returns the ACTUAL recorded decision, or None.  The
    prepare-payload participant list rides along so the decision's ack
    wait set is complete even for recovery-raced records."""
    payload = encode_coord(tid, decision, gids=ent.get("gids"))
    cid = f"txr-{srv.gid}-{tid}"
    from tpu6824.services.shardkv import Op as _SOp
    if ent["coord"] == srv.gid:
        op = _SOp("txn_coord", "", payload, cid, 1, None)
        try:
            with srv.mu:
                err, d = srv._sync(op)
        except RPCError:
            return None
        return d if err == OK else None
    for name in _coord_servers(srv, ent):
        peer = srv.directory.get(name)
        if peer is None:
            continue
        try:
            err, d = peer.txn_op("txn_coord", "", payload, cid, 1)
        except Exception:  # noqa: BLE001 — try the next replica
            continue
        if err == OK:
            return d
    return None


def ack_pass(srv, limit: int = 8) -> int:
    """Drain this server's owed participant acks (ISSUE 14): for each
    (tid, origin) finished locally, propose `txn_ack` into the
    coordinator group's log.  Runs on the shardkv TICKER, never under
    mu and never in _apply (the blocking-commit-wait rule); a
    coordinator that is unreachable keeps the entry owed — resends are
    dup-filtered there, so retry is free.  Returns acks landed."""
    with srv.mu:
        if srv.dead or not srv._txn_acks_owed:
            return 0
        pend = list(srv._txn_acks_owed.items())[:limit]
    landed = 0
    for (tid, origin), (coord, coord_srv) in pend:
        payload = encode_ack(tid, origin)
        cid = f"txa-{srv.gid}-{origin}-{tid}"
        ent = {"coord": coord, "coord_srv": coord_srv}
        ok = False
        from tpu6824.services.shardkv import Op as _SOp
        if coord == srv.gid:
            op = _SOp("txn_ack", "", payload, cid, 1, None)
            try:
                with srv.mu:
                    if not srv.dead:
                        err, _ = srv._sync(op)
                        ok = err == OK
            except RPCError:
                ok = False
        else:
            for name in _coord_servers(srv, ent):
                peer = srv.directory.get(name)
                if peer is None:
                    continue
                try:
                    err, _ = peer.txn_op("txn_ack", "", payload, cid, 1)
                except Exception:  # noqa: BLE001 — next replica
                    continue
                if err == OK:
                    ok = True
                    break
        if ok:
            landed += 1
            _M_ACKS.inc()
            with srv.mu:
                srv._txn_acks_owed.pop((tid, origin), None)
    return landed


# ------------------------------------------------ compaction (horizon)
# Applied ONLY from the replicated `compact` log entry (shardkv._apply)
# — pure function of (seq, RSM state), identical on every replica.


def _note_trimmed(srv, tid: str) -> None:
    """Bounded observability ring of trimmed decision tids, consulted
    by the trim-safety sentinel counter (volatile, never RSM state)."""
    srv._trimmed_tids[tid] = True
    while len(srv._trimmed_tids) > 4096:
        srv._trimmed_tids.pop(next(iter(srv._trimmed_tids)))


def apply_compact(srv, seq: int) -> None:
    """One replicated compact entry at `seq`: retire dup rows idle past
    the dup horizon, txn_done rows past DONE_LINGER_OPS, and — the
    trim-safety invariant — decision records that are FULLY RESOLVED
    (every participant acked) plus DECISION_LINGER_OPS of linger, with
    DECISION_MAX_OPS as the fallback for never-fully-ackable records.
    A decision whose tid is still locally prepared is NEVER trimmed."""
    retire = getattr(srv, "dup_retire_ops", 0)
    if retire > 0:
        floor = seq - retire
        if floor > 0:
            dup_seq = srv.dup_seq
            stale = [cid for cid, s in dup_seq.items() if s < floor]
            for cid in stale:
                srv.dup.pop(cid, None)
                del dup_seq[cid]
            if stale:
                from tpu6824.services import horizon as _hz
                _hz.note_dup_retired(len(stale))
    floor = seq - DONE_LINGER_OPS
    if floor > 0:
        stale = [tid for tid, s in srv.txn_done_seq.items() if s < floor]
        for tid in stale:
            srv.txn_done.pop(tid, None)
            del srv.txn_done_seq[tid]
        if stale:
            _M_DONE_TRIMMED.inc(len(stale))
    trimmed = []
    floor = seq - DECISION_LINGER_OPS
    if floor > 0:
        for tid, s in list(srv.txn_resolved.items()):
            if s < floor and tid not in srv.txn_prepared:
                trimmed.append(tid)
                del srv.txn_resolved[tid]
    floor = seq - DECISION_MAX_OPS
    if floor > 0:
        # Fallback horizon: decisions that can never be fully acked
        # (e.g. an abort recorded before some participant prepared) —
        # far beyond any clerk retry/replay window by construction.
        for tid, s in list(srv.txn_decision_seq.items()):
            if s < floor and tid in srv.txn_decisions \
                    and tid not in srv.txn_prepared \
                    and tid not in trimmed:
                trimmed.append(tid)
                srv.txn_decision_waits.pop(tid, None)
                srv.txn_resolved.pop(tid, None)
    for tid in trimmed:
        srv.txn_decisions.pop(tid, None)
        srv.txn_decision_seq.pop(tid, None)
        _note_trimmed(srv, tid)
    if trimmed:
        _M_DECISIONS_TRIMMED.inc(len(trimmed))


# -------------------------------------------------- mid-commit killing


class MidCommitKiller:
    """One-shot kill-between-prepare-quorum-and-commit-record, armed by
    the nemesis `kill_mid_commit {disk}` action (TxnKillTarget).
    Install as `clerk.mid_commit_hook` on every clerk under test; the
    next transaction that reaches its commit point fires `crash_fn(disk)`
    (e.g. kill a coordinator-group replica, with the disk disposition
    recorded for durafault deployments) and dies via `TxnAbandoned` —
    the fate of that transaction is then entirely the resolvers' +
    coordinator log's problem, which is the scenario's point."""

    def __init__(self, crash_fn=None):
        self.crash_fn = crash_fn
        self._mu = threading.Lock()
        self._armed: str | None = None
        self.fired: list[tuple[str, str]] = []  # (tid, disk)

    def arm(self, disk: str = "keep") -> None:
        with self._mu:
            self._armed = disk

    def disarm(self) -> None:
        with self._mu:
            self._armed = None

    def __call__(self, tid: str, coord_gid: int) -> None:
        with self._mu:
            disk, self._armed = self._armed, None
        if disk is None:
            return
        self.fired.append((tid, disk))
        if self.crash_fn is not None:
            try:
                self.crash_fn(coord_gid, disk)
            except Exception as e:  # noqa: BLE001 — the kill must land
                crashsink.record("mid-commit-kill", e, fatal=False)
        raise TxnAbandoned(f"killed mid-commit (tid={tid}, disk={disk})")


# ------------------------------------------------------------- history


class TxnHistory:
    """Thread-safe transactional history recorder (the txn analog of
    harness.linearize.History) — consumed by harness/txn_check.py."""

    def __init__(self):
        self._mu = threading.Lock()
        self._recs: list = []
        self.t0 = time.monotonic()

    def now(self) -> float:
        return time.monotonic() - self.t0

    def record(self, rec) -> None:
        with self._mu:
            self._recs.append(rec)

    def records(self) -> list:
        with self._mu:
            return list(self._recs)

    def __len__(self) -> int:
        with self._mu:
            return len(self._recs)


# ------------------------------------------------------------ the clerk


class _TxnClerkBase:
    """The 2PC driver shared by the in-process and wire clerks; the
    transport-specific half is `_phase_call` + `_config`."""

    #: prepare attempts per group before giving up on a lock (the
    #: distributed-deadlock breaker: abort + fresh transaction).
    LOCK_RETRIES = 24
    #: config-snapshot TTL: a shardmaster Query is a LOGGED Paxos op —
    #: per-attempt re-queries from a fleet of clerks would saturate the
    #: sm log exactly like the shardkv poller problem
    #: (shardkv.py::_tick_loop docstring).  Both clerks cache through
    #: `_cached_cfg`.
    CFG_TTL = 0.05

    def __init__(self, history: TxnHistory | None = None,
                 lock_retries: int | None = None):
        self.history = history
        self.lock_retries = (self.LOCK_RETRIES if lock_retries is None
                             else lock_retries)
        self.mid_commit_hook = None  # nemesis/test seam
        self._backoff = Backoff()
        self.cid = f"txn-{fresh_cid():x}"
        self._cseq = 0
        self._cseq_mu = new_lock("txnkv.cseq_mu")
        self._cfg_at = -float("inf")
        self._cfg = None

    def _cached_cfg(self):
        now = time.monotonic()
        if self._cfg is None or now - self._cfg_at >= self.CFG_TTL:
            self._cfg = self.smck.query(-1, timeout=5.0)
            self._cfg_at = now
        return self._cfg

    def _next(self) -> int:
        with self._cseq_mu:
            self._cseq += 1
            return self._cseq

    # transport half -----------------------------------------------------
    def _config(self):
        raise NotImplementedError

    def _phase_call(self, gid, kind, routing_key, payload, cseq,
                    deadline, retry_locked=False):
        """One phase op against group `gid` → (err, val).  Transport
        retries until `deadline`; with retry_locked, ErrTxnLocked also
        retries (same cseq) until the deadline."""
        raise NotImplementedError

    # the protocol -------------------------------------------------------
    def txn(self, ops, timeout: float = 20.0):
        """Run `ops` — (key, kind, value[, expect]) sub-ops, kinds from
        TXN_OP_KINDS — as ONE atomic cross-group transaction.

        Returns (status, reads): status 'committed' | 'aborted';
        `reads` maps key → value observed at the commit point for
        read/cas sub-ops (None when aborted).  Raises TxnAbandoned if a
        mid-commit kill hook fired (fate unknown — resolvers own it)
        and RPCError when the coordinator was unreachable (fate
        unknown).  Every outcome is recorded into `self.history`."""
        ops = [self._norm(t) for t in ops]
        call_t = self.history.now() if self.history is not None else 0.0
        root = _tracing.span("txn.op", comp="txn",
                             nops=len(ops)) if _tracing.enabled() else None
        try:
            status, reads = self._txn_inner(ops, timeout, root)
            self._record(ops, call_t, status, reads)
            return status, reads
        except TxnAbandoned:
            self._record(ops, call_t, "unknown", None)
            raise
        except RPCError:
            self._record(ops, call_t, "unknown", None)
            raise
        finally:
            if root is not None:
                root.end()

    @staticmethod
    def _norm(t):
        key, kind, value = t[0], t[1], t[2]
        expect = t[3] if len(t) > 3 else ""
        if kind not in TXN_OP_KINDS:
            raise ValueError(f"unknown txn sub-op kind {kind!r}")
        return (key, kind, value, expect)

    def _record(self, ops, call_t, status, reads) -> None:
        if self.history is None:
            return
        from tpu6824.harness.txn_check import TxnRecord
        rec_ops = []
        for k, kind, v, exp in ops:
            if kind == "read":
                rec_ops.append(("r", k, (reads or {}).get(k, "")))
            elif kind == "cas":
                rec_ops.append(("r", k, exp))
                rec_ops.append(("w", k, v))
            elif kind == "append":
                rec_ops.append(("a", k, v))
            else:
                rec_ops.append(("w", k, v))
        self.history.record(TxnRecord(
            client=self.cid, ops=tuple(rec_ops), call=call_t,
            ret=self.history.now() if status != "unknown" else None,
            status=status))

    def _txn_inner(self, ops, timeout, root):
        deadline = time.monotonic() + timeout
        self._backoff.reset()
        while True:
            out = self._attempt(ops, deadline, root)
            if out is not None:
                return out
            if time.monotonic() >= deadline:
                raise RPCError("txn timeout (config churn?)")
            self._backoff.sleep(deadline - time.monotonic())

    def _attempt(self, ops, deadline, root):
        """One transaction attempt.  None = config raced us
        (ErrWrongGroup after re-route) — the caller retries with a
        fresh config and a fresh tid."""
        cfg_view = self._config()
        parts: dict[int, list] = {}
        for t in ops:
            gid = cfg_view.gid_of(t[0])
            if gid is None:
                return None  # unassigned shard: config still settling
            parts.setdefault(gid, []).append(t)
        gids = sorted(parts)
        coord = gids[0]
        all_real = [cfg_view.real_gid(g) for g in gids]
        tid = f"t{fresh_cid():x}"
        _M_BEGIN.inc()
        _inflight_add(1)
        rctx = root.ctx if root is not None else None
        try:
            decision = COMMIT
            reads: dict[str, str] = {}
            prepared: list[int] = []
            unknown_phase = False  # a prepare whose fate we can't see
            unknown_gids: list[int] = []  # those groups, specifically
            sp = _tracing.child("txn.begin", parent=rctx, comp="txn",
                                tid=tid)
            if sp is not None:
                sp.end()
            for gid in gids:
                payload = encode_prepare(
                    tid, cfg_view.real_gid(coord),
                    cfg_view.server_names(coord), parts[gid],
                    gids=all_real)
                psp = _tracing.child("txn.prepare", parent=rctx,
                                     comp="txn", gid=gid)
                try:
                    with _tracing.use_ctx(psp.ctx if psp is not None
                                          else None):
                        err, val = self._phase_call(
                            gid, "txn_prepare", parts[gid][0][0],
                            payload, self._next(),
                            min(deadline, time.monotonic() + 4.0),
                            retry_locked=True)
                except RPCError:
                    err, val = None, None  # fate at gid unknown
                    unknown_phase = True
                    unknown_gids.append(cfg_view.real_gid(gid))
                finally:
                    if psp is not None:
                        psp.end()
                if err == OK:
                    prepared.append(gid)
                    reads.update(json.loads(val) if val else {})
                    continue
                decision = ABORT
                abort_reason = (val if err == ErrTxnAbort
                                else err or "unreachable")
                break
            if decision == ABORT and not prepared and not unknown_phase:
                # Nothing is held under this tid ANYWHERE (every
                # refusal was a definitive no-lock reply: ErrTxnLocked
                # budget, CAS-fail vote, wrong group) — a coordinator
                # record would be a pure-overhead Paxos round plus an
                # eternal decision row no resolver can ever consult
                # (review hardening: at contention-level abort rates
                # that roughly doubles coordinator log traffic).
                _M_ABORT.inc()
                return None if abort_reason == ErrWrongGroup \
                    else ("aborted", None)
            if decision == COMMIT and self.mid_commit_hook is not None:
                self.mid_commit_hook(tid, coord)
            csp = _tracing.child("txn.commit", parent=rctx, comp="txn",
                                 tid=tid, decision=decision)
            try:
                with _tracing.use_ctx(csp.ctx if csp is not None
                                      else None):
                    err, actual = self._phase_call(
                        coord, "txn_coord", cfg_view.coord_key(coord),
                        encode_coord(
                            tid, decision,
                            # Commit awaits every participant's ack; an
                            # abort awaits the groups that hold locks —
                            # including UNKNOWN-fate prepares (a timed-
                            # out RPC whose op still landed holds locks
                            # and WILL consult this record; omitting it
                            # from the wait set would let the linger
                            # trim un-decide the abort under load).  A
                            # never-landed unknown simply never acks and
                            # the MAX_OPS fallback reaps the row.
                            gids=(all_real if decision == COMMIT else
                                  [cfg_view.real_gid(g)
                                   for g in prepared] + unknown_gids)),
                        self._next(), deadline)
            except RPCError:
                err, actual = None, None
            if err != OK or actual not in (COMMIT, ABORT):
                # The commit point itself is unreachable: the fate is
                # genuinely unknown — resolvers will settle it.
                if csp is not None:
                    csp.end()
                raise RPCError(f"txn {tid}: coordinator unreachable, "
                               "fate unknown")
            for gid in prepared:
                fk = "txn_commit" if actual == COMMIT else "txn_abort"
                try:
                    self._phase_call(gid, fk, parts[gid][0][0],
                                     encode_finish(tid), self._next(),
                                     deadline)
                except RPCError:
                    pass  # the resolver finishes stragglers
            if csp is not None:
                rsp = _tracing.child("txn.reply", parent=csp.ctx,
                                     comp="txn", tid=tid)
                if rsp is not None:
                    rsp.end()
                csp.end()
            if actual == COMMIT:
                _M_COMMIT.inc()
                return ("committed", reads)
            _M_ABORT.inc()
            if decision == COMMIT:
                # We asked for commit but a recovery abort won the
                # race: aborted, retryable.
                return ("aborted", None)
            if abort_reason == ErrWrongGroup:
                return None  # re-route with a fresh config
            return ("aborted", None)
        finally:
            _inflight_add(-1)

    # convenience surface ------------------------------------------------
    def multi_cas(self, triples, timeout: float = 20.0) -> bool:
        """Atomically set every key whose current value matches its
        expectation: triples = (key, expect, new).  True on commit."""
        status, _ = self.txn([(k, "cas", new, exp)
                              for k, exp, new in triples], timeout=timeout)
        return status == "committed"

    def read(self, keys, timeout: float = 20.0) -> dict:
        """One atomic multi-key snapshot (a read-only transaction).
        An aborted attempt (a lock window, a lost commit-point race)
        is retried within the deadline — a read-only txn is always
        safely retryable; TxnAborted surfaces only at exhaustion."""
        deadline = time.monotonic() + timeout
        bo = Backoff()
        ops = [(k, "read", "", "") for k in keys]
        while True:
            status, reads = self.txn(
                ops, timeout=max(0.5, deadline - time.monotonic()))
            if status == "committed":
                return reads
            if time.monotonic() >= deadline:
                raise TxnAborted("read-only txn aborted")
            bo.sleep(deadline - time.monotonic())

    def transfer(self, src: str, dst: str, amount: int,
                 timeout: float = 30.0) -> bool:
        """Cross-shard transfer: atomically move `amount` from src to
        dst (integer balances, missing key = 0), conserving the sum.
        Optimistic CAS loop: snapshot, compute, multi_cas, retry on
        expectation failure."""
        deadline = time.monotonic() + timeout
        bo = Backoff()
        while True:
            try:
                snap = self.read(
                    [src, dst],
                    timeout=max(0.5, deadline - time.monotonic()))
            except TxnAborted:
                # The snapshot's read-only txn lost a commit-point race
                # (a resolver's recovery abort) — retryable like any
                # CAS miss.
                if time.monotonic() >= deadline:
                    return False
                bo.sleep(deadline - time.monotonic())
                continue
            a = int(snap.get(src) or 0)
            b = int(snap.get(dst) or 0)
            if self.multi_cas(
                    [(src, snap.get(src, ""), str(a - amount)),
                     (dst, snap.get(dst, ""), str(b + amount))],
                    timeout=max(0.5, deadline - time.monotonic())):
                return True
            if time.monotonic() >= deadline:
                return False
            bo.sleep(deadline - time.monotonic())


class _ConfigView:
    """One attempt's routing snapshot: key → gid, gid → server names,
    and the coordinator routing token for the wire path.  Wire clerks
    work in FRONTEND GROUP-INDEX space (gid_to_idx given); payloads
    always carry the REAL gid (`real_gid`) so participant resolvers can
    find the coordinator group in the directory."""

    def __init__(self, cfg, gid_to_idx=None):
        self.cfg = cfg
        self._g2i = gid_to_idx
        self._i2g = (None if gid_to_idx is None
                     else {i: g for g, i in gid_to_idx.items()})

    def gid_of(self, key: str):
        gid = self.cfg.shards[key2shard(key)]
        if gid == shardmaster.UNASSIGNED:
            return None
        if self._g2i is not None:
            return self._g2i.get(gid)
        return gid

    def real_gid(self, gid):
        return self._i2g[gid] if self._i2g is not None else gid

    def server_names(self, gid) -> tuple:
        return tuple(self.cfg.groups_dict().get(self.real_gid(gid), ()))

    def coord_key(self, gid) -> str:
        # In-process clerks route by gid directly; the wire clerk's
        # coordinator op routes via the NUL-prefixed token its route fn
        # understands (frontend_route below — collision-proof against
        # user keys).
        return _coord_token(gid) if self._g2i is not None else ""


class TxnClerk(_TxnClerkBase):
    """In-process transactional clerk over a shardkv deployment: routes
    by shardmaster config, talks to ShardKVServer.txn_op through the
    lossy FlakyNet leg like every other in-process clerk."""

    def __init__(self, sm_servers, directory: dict,
                 net: FlakyNet | None = None,
                 history: TxnHistory | None = None, **kw):
        super().__init__(history=history, **kw)
        self.smck = shardmaster.Clerk(sm_servers)
        self.directory = directory
        self.net = net or FlakyNet()

    def _config(self):
        return _ConfigView(self._cached_cfg())

    def _phase_call(self, gid, kind, routing_key, payload, cseq,
                    deadline, retry_locked=False):
        cfg = self._cached_cfg()
        names = cfg.groups_dict().get(gid, ())
        if not names:
            # Group left the config (still serving): directory fallback.
            pfx = f"g{gid}-"
            names = tuple(sorted(n for n in self.directory
                                 if n.startswith(pfx)))
        bo = Backoff()
        attempts = 0
        while True:
            for name in names:
                srv = self.directory.get(name)
                if srv is None:
                    continue
                try:
                    err, val = self.net.call(
                        srv, srv.txn_op, kind, routing_key, payload,
                        self.cid, cseq)
                except RPCError:
                    continue
                if err == ErrTxnLocked and retry_locked:
                    attempts += 1
                    if attempts >= self.lock_retries \
                            or time.monotonic() >= deadline:
                        return err, val  # give up: caller aborts
                    bo.sleep(max(0.0, deadline - time.monotonic()))
                    break  # re-send same cseq from the head
                return err, val
            else:
                if time.monotonic() >= deadline:
                    raise RPCError(f"txn phase {kind}@g{gid}: no live "
                                   "replica within deadline")
                bo.sleep(max(0.0, deadline - time.monotonic()))


class TxnFrontendClerk(_TxnClerkBase):
    """The WIRE transactional clerk: every phase op is one frame op
    through a multi-group ClerkFrontend — (kind, routing_key, payload,
    cid, cseq) tuples with the caps-gated txn frame kinds.  `gids`
    fixes the frontend's group order (index space); `sm_servers` feeds
    the routing snapshot.  An endpoint whose fe_caps does not advertise
    `fe_txn` refuses transactions loudly (old servers interop unchanged
    for every pre-txn op)."""

    def __init__(self, addrs, sm_servers, gids, timeout: float = 10.0,
                 history: TxnHistory | None = None, wire_format="auto",
                 **kw):
        super().__init__(history=history, **kw)
        from tpu6824.services.frontend import FrontendClerk
        self._fc = FrontendClerk(addrs, timeout=timeout,
                                 wire_format=wire_format)
        self.smck = shardmaster.Clerk(sm_servers)
        self.gids = list(gids)
        self._g2i = {g: i for i, g in enumerate(self.gids)}
        self.cid = self._fc.cid  # one wire identity, one dup-filter row

    def _config(self):
        return _ConfigView(self._cached_cfg(), gid_to_idx=self._g2i)

    def _phase_call(self, gid, kind, routing_key, payload, cseq,
                    deadline, retry_locked=False):
        bo = Backoff()
        attempts = 0
        while True:
            budget = max(0.2, deadline - time.monotonic())
            err, val = self._fc.txn_call(
                (kind, routing_key, payload, self.cid, cseq),
                timeout=budget)
            if err == ErrTxnLocked and retry_locked:
                attempts += 1
                if attempts >= self.lock_retries \
                        or time.monotonic() >= deadline:
                    return err, val
                bo.sleep(max(0.0, deadline - time.monotonic()))
                continue
            return err, val

    def close(self) -> None:
        self._fc.close()


# Coordinator routing token ("\x00g<idx>!"): leads with a NUL byte so
# it cannot collide with any printable user key, and the route falls
# through to the shard map on anything that does not match the exact
# shape (a user key merely STARTING with the prefix is still routed,
# never rejected).  Produced only by _ConfigView.coord_key, consumed
# only by frontend_route; keys beginning with NUL are reserved.
_COORD_TOKEN_PREFIX = "\x00g"


def _coord_token(idx: int) -> str:
    return f"{_COORD_TOKEN_PREFIX}{idx}!"


def _parse_coord_token(key: str):
    """Group index for an exact coordinator token, else None."""
    if not key.startswith(_COORD_TOKEN_PREFIX):
        return None
    bang = key.find("!")
    if bang <= len(_COORD_TOKEN_PREFIX):
        return None
    digits = key[len(_COORD_TOKEN_PREFIX):bang]
    return int(digits) if digits.isdigit() else None


def frontend_route(gids, cfg_cell):
    """The route= closure for a txn-capable multi-group ClerkFrontend:
    ordinary keys follow the CURRENT shard map (cfg_cell is a 1-slot
    mutable holding the latest Config — see ConfigRouter), and the
    coordinator token routes straight to that group index (the
    txn_coord op's apply never checks ownership)."""
    g2i = {g: i for i, g in enumerate(gids)}
    ng = len(gids)

    def route(key: str) -> int:
        idx = _parse_coord_token(key)
        if idx is not None and 0 <= idx < ng:
            return idx
        gid = cfg_cell[0].shards[key2shard(key)]
        return g2i.get(gid, 0)

    return route


class ConfigRouter:
    """Keeps a frontend route's config snapshot fresh: a daemon poller
    queries the shardmaster every `interval` and writes the 1-slot cell
    `frontend_route` reads — the engine thread never blocks on a config
    Query."""

    def __init__(self, sm_servers, gids, interval: float = 0.05):
        self.smck = shardmaster.Clerk(sm_servers)
        self.cell = [self.smck.query(-1, timeout=5.0)]
        self.route = frontend_route(gids, self.cell)
        self.interval = interval
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=crashsink.guarded(self._loop, "txn-config-router"),
            daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.cell[0] = self.smck.query(-1, timeout=2.0)
            except RPCError:
                continue  # sm group busy/partitioned: keep the old map

    def stop(self):
        self._stop.set()
