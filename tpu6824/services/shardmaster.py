"""shardmaster — Paxos-replicated shard configuration service.

Capability parity with the reference Lab 4A (`shardmaster/server.go`,
`shardmaster/client.go`): Join/Leave/Move/Query produce a numbered sequence of
`Config{num, shards[NSHARDS]→gid, groups{gid→servers}}`; rebalancing moves as
few shards as possible and keeps the spread ≤ 1.

Fixes a reference defect on purpose: the reference's `Move()` handler logs the
op with type Leave (`shardmaster/server.go:82`), so replicas replaying the log
apply a Leave instead of a Move.  Here Move is logged and applied as Move.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import NamedTuple

from tpu6824.core.fabric import PaxosFabric, WindowFullError
from tpu6824.core.peer import Fate, PaxosPeer
from tpu6824.obs import tracing as _tracing
from tpu6824.ops.hashing import NSHARDS
from tpu6824.ops.rebalance import UNASSIGNED, rebalance_host
from tpu6824.services.common import FlakyNet, fresh_cid
from tpu6824.utils.errors import RPCError
from tpu6824.utils import crashsink
from tpu6824.utils.locks import new_rlock
from tpu6824.utils.trace import dprintf


@dataclass(frozen=True)
class Config:
    """shardmaster/common.go:35-41 — one numbered configuration."""

    num: int
    shards: tuple  # len NSHARDS, shard index -> gid (UNASSIGNED if none)
    groups: tuple  # sorted tuple of (gid, tuple(servers))

    def groups_dict(self) -> dict[int, tuple]:
        return dict(self.groups)

    @staticmethod
    def initial() -> "Config":
        return Config(0, (UNASSIGNED,) * NSHARDS, ())


class Op(NamedTuple):
    kind: str  # 'join' | 'leave' | 'move' | 'query'
    gid: int
    servers: tuple
    shard: int
    cid: int
    cseq: int
    # tpuscope trace metadata (see kvpaxos.Op.tc): the submitting leg's
    # (trace_id, span_id) when tracing is enabled, else None; never part
    # of op identity.
    tc: tuple | None = None


class ShardMasterServer:
    RPC_METHODS = ["join", "leave", "move", "query"]  # wire surface (rpc.Server)

    def __init__(self, fabric: PaxosFabric | None, g: int, me: int,
                 op_timeout: float = 8.0, px=None):
        """`px` overrides the consensus backend (PaxosPeer contract) — the
        batched fabric by default, or the decentralized wire backend via
        `make_host_cluster`."""
        if fabric is None and px is None:
            raise ValueError(
                "ShardMasterServer needs a fabric or an explicit px")
        self.px = px if px is not None else PaxosPeer(fabric, g, me)
        self.me = me
        # Budget contract: the RSM handler legitimately rides mu across
        # a full paxos agreement (see _sync), so the hold bound is the
        # op deadline plus drain slack — not the leaf-lock default.
        self.mu = new_rlock("shardmaster.mu",
                            hold_budget_s=op_timeout + 2.0)
        self.configs: list[Config] = [Config.initial()]
        self.applied = -1
        self.dup: dict[int, tuple[int, object]] = {}
        self.op_timeout = op_timeout
        self.dead = False
        self._ticker = threading.Thread(
            target=crashsink.guarded(self._tick_loop, "shardmaster-ticker"),
            daemon=True)
        self._ticker.start()

    # ----------------------------------------------------------- RSM apply

    def _apply(self, op: Op):
        seen, reply = self.dup.get(op.cid, (-1, None))
        if op.cseq <= seen:
            return reply
        if op.kind == "join":
            reply = self._do_join(op.gid, op.servers)
        elif op.kind == "leave":
            reply = self._do_leave(op.gid)
        elif op.kind == "move":
            reply = self._do_move(op.shard, op.gid)
        elif op.kind == "query":
            reply = None  # resolved read-side after apply
        # tpusan: ok(unbounded-host-state) — one dup row per ADMIN
        # clerk (join/leave/move issuers + config pollers), a
        # population bounded by deployment size, not by traffic; the
        # config history itself is the replicated data of this service
        self.dup[op.cid] = (op.cseq, reply)
        if op.kind != "query":
            dprintf("shardmaster", "s%d applied %s gid=%d shard=%d -> "
                    "config %d", self.me, op.kind, op.gid, op.shard,
                    len(self.configs) - 1)
        if op.tc is not None:  # tpuscope: apply-side span for traced ops
            _tracing.complete("service.apply", op.tc[0], op.tc[1],
                              time.monotonic_ns(), comp="shardmaster",
                              me=self.me, kind=op.kind)
        return reply

    def _next_config(self) -> tuple[list, dict]:
        """Copy-on-write of the latest config
        (prepareNextConfig, shardmaster/server.go:185-193)."""
        cur = self.configs[-1]
        return list(cur.shards), dict(cur.groups)

    def _push(self, shards: list, groups: dict):
        self.configs.append(
            Config(
                num=len(self.configs),
                shards=tuple(shards),
                groups=tuple(sorted(groups.items())),
            )
        )

    def _do_join(self, gid: int, servers: tuple):
        shards, groups = self._next_config()
        if gid in groups:
            # Rejoin with new server list still makes a new config.
            groups[gid] = tuple(servers)
        else:
            groups[gid] = tuple(servers)
        shards = rebalance_host(shards, list(groups.keys()))
        self._push(shards, groups)

    def _do_leave(self, gid: int):
        shards, groups = self._next_config()
        groups.pop(gid, None)
        shards = rebalance_host(shards, list(groups.keys()))
        self._push(shards, groups)

    def _do_move(self, shard: int, gid: int):
        # Correct Move semantics (reference logs it as Leave — §2.4.4).
        shards, groups = self._next_config()
        shards[shard] = gid
        self._push(shards, groups)

    # ----------------------------------------------------------- log driver

    def _tick_loop(self):
        while not self.dead:
            time.sleep(0.02)
            try:
                with self.mu:
                    if self.dead:
                        return
                    self._drain_decided()
            except RPCError:
                # Transient backend outage (e.g. a fabricd restarting from
                # a checkpoint behind a remote_fabric handle): keep the
                # drain ticker alive and retry.
                continue

    def _drain_decided(self):
        while True:
            fate, v = self.px.status(self.applied + 1)
            if fate == Fate.DECIDED:
                self._apply(v)
                self.applied += 1
                self.px.done(self.applied)
            elif fate == Fate.FORGOTTEN:
                self.applied += 1
            else:
                return

    def _sync(self, want: Op):
        deadline = time.monotonic() + self.op_timeout
        started = False
        while True:
            if self.dead:
                raise RPCError("server killed")
            seq = self.applied + 1
            fate, v = self.px.status(seq)
            if fate == Fate.DECIDED:
                reply = self._apply(v)
                self.applied = seq
                self.px.done(seq)
                if isinstance(v, Op) and v.cid == want.cid and v.cseq == want.cseq:
                    return reply
                started = False
                continue
            if not started:
                try:
                    self.px.start(seq, want)
                    started = True
                except WindowFullError:
                    pass
            if time.monotonic() >= deadline:
                raise RPCError("op timeout (no majority?)")
            # tpusan: ok(lock-blocking-reachable) — the RSM handler
            # holds mu across paxos agreement by design (ops serialize
            # on the server mutex, reference lab semantics); the 2ms
            # nap paces the decide poll, bounded by the deadline above.
            time.sleep(0.002)

    # ----------------------------------------------------------- RPC surface

    def join(self, gid: int, servers, cid: int, cseq: int):
        with self.mu:
            self._check()
            self._dedup_or_sync(Op("join", gid, tuple(servers), -1, cid, cseq))
            return True

    def leave(self, gid: int, cid: int, cseq: int):
        with self.mu:
            self._check()
            self._dedup_or_sync(Op("leave", gid, (), -1, cid, cseq))
            return True

    def move(self, shard: int, gid: int, cid: int, cseq: int):
        with self.mu:
            self._check()
            self._dedup_or_sync(Op("move", gid, (), shard, cid, cseq))
            return True

    def query(self, num: int, cid: int, cseq: int) -> Config:
        with self.mu:
            self._check()
            self._dedup_or_sync(Op("query", -1, (), -1, cid, cseq))
            if num == -1 or num >= len(self.configs):
                return self.configs[-1]
            return self.configs[num]

    def _check(self):
        if self.dead:
            raise RPCError("dead")

    def _dedup_or_sync(self, op: Op):
        seen, _ = self.dup.get(op.cid, (-1, None))
        if op.cseq <= seen:
            return
        # tpuscope: stamp the caller's trace context into the proposed
        # value (the rpc leg made it current) so the apply span joins
        # the clerk's causal chain.
        if _tracing.enabled():
            sp = _tracing.child("service.submit", comp="shardmaster",
                                kind=op.kind)
            if sp is not None:
                op = op._replace(tc=(sp.trace_id, sp.span_id))
                sp.end()
        self._sync(op)

    def kill(self):
        with self.mu:
            self.dead = True
        self.px.kill()


class Clerk:
    """shardmaster/client.go:56-120."""

    def __init__(self, servers: list[ShardMasterServer], net: FlakyNet | None = None):
        self.servers = servers
        self.net = net or FlakyNet()
        self.cid = fresh_cid()
        self.cseq = 0
        self.mu = threading.Lock()

    def _next(self):
        with self.mu:
            self.cseq += 1
            return self.cseq

    def _loop(self, fn_name, *args, timeout=None):
        cseq = self._next()
        deadline = time.monotonic() + timeout if timeout else None
        i = 0
        while True:
            srv = self.servers[i % len(self.servers)]
            i += 1
            try:
                return self.net.call(srv, getattr(srv, fn_name), *args, self.cid, cseq)
            except RPCError:
                pass
            if deadline and time.monotonic() >= deadline:
                raise RPCError("clerk timeout")
            time.sleep(0.01)

    def join(self, gid: int, servers, timeout=None):
        self._loop("join", gid, tuple(servers), timeout=timeout)

    def leave(self, gid: int, timeout=None):
        self._loop("leave", gid, timeout=timeout)

    def move(self, shard: int, gid: int, timeout=None):
        self._loop("move", shard, gid, timeout=timeout)

    def query(self, num: int = -1, timeout=None) -> Config:
        return self._loop("query", num, timeout=timeout)


def make_cluster(nservers=3, ninstances=32, fabric=None, g=0, **kw):
    if fabric is None:
        fabric = PaxosFabric(ngroups=1, npeers=nservers, ninstances=ninstances,
                             auto_step=True)
    servers = [ShardMasterServer(fabric, g, p, **kw) for p in range(nservers)]
    return fabric, servers


# ---------------------------------------------------------------------------
# Decentralized backend (cf. kvpaxos.make_host_cluster): the config service
# one-replica-per-process, consensus over per-message gob RPC.

from tpu6824.services.host_backend import StructOpPeer
from tpu6824.shim.gob import INT, STRING, Slice, Struct

SMOP_WIRE = Struct("SMOp", [
    ("Kind", STRING), ("GID", INT), ("Servers", Slice(STRING)),
    ("Shard", INT), ("CID", INT), ("Seq", INT),
])
SMOP_NAME = "tpu6824.SMOp"


def HostOpPeer(host_peer) -> StructOpPeer:
    return StructOpPeer(
        host_peer, SMOP_NAME, SMOP_WIRE,
        to_wire=lambda op: {"Kind": op.kind, "GID": op.gid,
                            "Servers": list(op.servers), "Shard": op.shard,
                            "CID": op.cid, "Seq": op.cseq},
        from_wire=lambda d: Op(d["Kind"], d["GID"], tuple(d["Servers"]),
                               d["Shard"], d["CID"], d["Seq"]),
    )


def make_host_replica(sockdir: str, nservers: int, me: int,
                      seed: int | None = None,
                      peer_kw: dict | None = None, **kw):
    """One decentralized shardmaster replica (peer endpoint + RSM);
    `peer_kw` goes to HostPaxosPeer (pooled=, parallel_fanout=, ...)."""
    from tpu6824.services.host_backend import make_host_replica as _mk

    return _mk(sockdir, "smpx", SMOP_NAME, SMOP_WIRE,
               lambda p: ShardMasterServer(None, 0, p.me, px=HostOpPeer(p),
                                           **kw),
               nservers, me, seed=seed, **(peer_kw or {}))


def make_host_cluster(sockdir: str, nservers: int = 3,
                      seed: int | None = None,
                      peer_kw: dict | None = None, **kw):
    from tpu6824.services.host_backend import make_host_cluster as _mk

    return _mk(sockdir, "smpx", SMOP_NAME, SMOP_WIRE,
               lambda p: ShardMasterServer(None, 0, p.me, px=HostOpPeer(p),
                                           **kw),
               nservers, seed=seed, **(peer_kw or {}))
