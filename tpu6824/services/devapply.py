"""devapply — the host half of the device-resident columnar apply (ISSUE 16).

`core/devapply_kernel.py` owns the device state and the jitted step;
this module owns everything the device must never see: string→id
interning, the per-drain column build, reply resolution from chain
nodes back to interned strings, the lazily-synced host mirror, the
snapshot cut, and capacity management (rebase).

The decided-path contract (the tpusan `host-walk-in-decided-path` rule
polices its other half in kvpaxos):

  - Per op, the host does ONE key-intern probe (which memoizes the
    key's table slot) plus O(1) integer bookkeeping — chain-node
    allocation is a counter bump, same-drain read-after-write is a dict
    lookup — and list appends.  No store-dict walk, no string
    concatenation, no per-op device call.
  - The jitted device step runs per FLUSH, not per drain: get-free
    drains accumulate columns (padded to a `core.jitshape` bucket;
    oversized batches chunk through the top rung) and flush on the next
    drain with gets, on the size cap, or on a snapshot/mirror/rebase
    boundary.  The flush's pre-node readback serves get replies and the
    host chain shadow alike — and stays IN FLIGHT when no get needs it,
    so the driver never blocks on the device between drains.
  - Get replies resolve node→string through a memo: a single-node chain
    returns the interned value string itself (zero new bytes), an
    append chain concatenates ONCE and memoizes.  `DevVal` carries the
    encoded bytes with the reply so the native reply ring pushes value
    ids' bytes without re-encoding per reply.
  - The mirror (the old `self.kv` dict, demoted) syncs from a device
    readback on cadence, on snapshot cut, and on demand — never on the
    decided path.

Capacity: the chain store fills as writes accumulate and the intern
tables grow with unique strings; a rebase (readback → resolve → rebuild
with single-node chains and a GC'd intern set) bounds both.  The
`devapply.table_load_frac` gauge names a near-full table before the
hard ceiling raises (the watchdog queue-growth rule watches it).
"""

from __future__ import annotations

import functools
import os
import time

import numpy as np

from tpu6824.core import devapply_kernel as _dk
from tpu6824.core.devapply_kernel import (
    C_KID, C_KIND, C_NC, C_NODE, C_PREV, C_SLOT, C_TMASK, C_VID,
    K_APPEND, K_GET, K_PUT, DevKVState, col_fills, host_insert,
    make_state,
)
from tpu6824.core.jitshape import bucket_for, bucket_ladder
from tpu6824.obs import metrics as _metrics
from tpu6824.utils.errors import OK, ErrNoKey
from tpu6824.utils.locks import new_rlock

# Registry wiring (ISSUE 16 observability satellite): counters/gauges at
# module scope per the metric-unregistered rule; pulse samples them with
# the rest of the registry, watchdog watches the load gauge.
_M_APPLIED = _metrics.counter("devapply.applied_ops")
_M_SYNCS = _metrics.counter("devapply.mirror_syncs")
_M_READBACK = _metrics.counter("devapply.readback_us")
_M_REBASES = _metrics.counter("devapply.rebases")
_M_LOAD = _metrics.gauge("devapply.table_load_frac")

_KIND_CODE = {"get": K_GET, "put": K_PUT, "append": K_APPEND}

# Rebase when the intern/key population would cross this fraction of the
# table — past it, open-addressed probes cluster and a full table is a
# liveness bug (the kernel's probe bound).
_LOAD_MAX = 0.85


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


class DevVal(str):
    """A resolved get-reply value: a plain `str` everywhere (clerks,
    dup table, history checkers compare it as one), plus the encoded
    bytes memoized for the native reply ring — the ring pushes a value
    id's bytes once per NODE, not once per reply."""

    __slots__ = ("_b",)

    def bytes(self) -> bytes:
        b = getattr(self, "_b", None)
        if b is None:
            b = str.encode(self)
            self._b = b
        return b

    def __reduce__(self):  # snapshots/wire pickle as the plain value
        return (str, (str(self),))


# Jit warmup memo: one compile pass per (slots, chain) shape per
# process — every engine with the same env shares the executables.
_WARMED: set = set()


def _locked(fn):
    """Serialize a public engine entry point on `self.emu`.  The lock
    is reentrant because entry points nest (snapshot_resolve→resolve,
    batch_reset→_rebase→load_from_dict→_flush) and a leaf: nothing
    under it calls back out of the engine, so the server's `mu`→`emu`
    order can never invert."""
    @functools.wraps(fn)
    def inner(self, *args, **kwargs):
        with self.emu:
            return fn(self, *args, **kwargs)
    return inner


class DevApplyEngine:
    """One replica's device-resident KV apply state.

    Thread contract: the decided path (batch_*) runs only on the
    server's driver thread, but mirror/snapshot entry points are called
    both OFF the server mutex (the driver's cadence sync, by design —
    it must not hold `mu` through a readback) and UNDER it (kv_view,
    set_devapply, snapshot install), and since the accumulate/flush
    redesign those paths mutate shared column/flush state.  Every
    public method therefore takes the engine's own reentrant leaf lock
    `emu` (order: `mu` → `emu`, never inverted — the engine calls
    nothing that takes `mu`).  `mirror` is still swapped whole, so
    lock-free debug reads of the previous dict stay consistent.
    """

    def __init__(self, slots: int | None = None, chain: int | None = None,
                 sync_every: int | None = None):
        S = _pow2(max(64, slots if slots is not None
                      else _env_int("TPU6824_DEVAPPLY_SLOTS", 1 << 15)))
        C = max(256, chain if chain is not None
                else _env_int("TPU6824_DEVAPPLY_CHAIN", 4 * S))
        self.slots = S
        self.chain = C
        self._kcap = int(S * _LOAD_MAX)
        self.sync_every = (sync_every if sync_every is not None
                           else _env_int("TPU6824_DEVAPPLY_SYNC", 8192))
        # The top rung doubles as the accumulate cap (get-free drains
        # pile columns until it trips), so it sets the flush cadence:
        # every flush is a device dispatch, and under a thread-heavy
        # host each dispatch's GIL round-trip can eat a scheduler
        # quantum — fewer, fatter steps win.  16384 ops ≈ 512KB packed
        # matrix, still one cheap transfer.
        self._ladder = bucket_ladder(
            8, _env_int("TPU6824_DEVAPPLY_BUCKET", 16384))
        # Row fills for the packed op-column matrix: each device step
        # ships ONE freshly-built (8, bucket) matrix — per-column
        # transfers cost 2× the step itself, and a fresh buffer per
        # chunk is what lets the CPU backend zero-copy-alias it (the
        # engine never mutates a buffer after handing it to the step).
        self._fills = col_fills(S)
        self._state: DevKVState = make_state(S, C)
        # Host interners.  Values skip the dedup dict on purpose: hot
        # workloads append mostly-unique payloads, so a per-op
        # val->id probe would buy nothing — the rebase GC reclaims
        # dead ids either way.  vid 0 is reserved as the get column's
        # inert fill.
        self._k2i: dict[str, int] = {}
        self._i2k: list[str] = []
        self._i2v: list[str] = [""]
        # Slot-assignment authority: the host shadow of the device key
        # table (probed by `host_insert`, slots memoized per kid) — the
        # device consumes resolved slots and never probes.
        self._htbl = np.full(S + 1, -1, np.int32)
        self._kslot: list[int] = []
        # Host chain shadow: the append-log the host itself emitted
        # (vid per node) plus prev links from the per-drain readback —
        # what get replies and mirror syncs resolve against.
        self._cvid = np.zeros(C, np.int32)
        self._cprev = np.full(C, -1, np.int32)
        self._nc = 0
        self._nnext = 0
        self._node_val: dict[int, DevVal] = {}
        self.last_applied = -1
        self.mirror: dict[str, str] = {}
        self.mirror_applied = -1
        # Accumulated column build state (carries across get-free
        # drains until a flush).  `_blastw` (kid → its latest write's
        # chain node since the last flush) is how read-after-write
        # stays host-known: the device table is allowed to lag the
        # watermark, so any op whose key was written since the last
        # flush carries its predecessor in the `prevs` column.
        self._bkinds: list[int] = []
        self._bslots: list[int] = []
        self._bkids: list[int] = []
        self._bvids: list[int] = []
        self._bnodes: list[int] = []
        self._bprevs: list[int] = []
        self._bwvid: list[int] = []
        self._bwapp: list[bool] = []
        self._bgets: list[int] = []
        self._blastw: dict[int, int] = {}
        self._bj = 0
        self._jbase = 0
        # Deferred chain-shadow fills: a get-free drain dispatches its
        # device step and returns WITHOUT blocking on the readback (the
        # decided path stays async); the prev links land here and any
        # shadow reader flushes via `_drain_shadow` first.
        self._pending: list = []
        # Engine leaf lock (see the class docstring's thread contract):
        # serializes the driver's off-`mu` cadence sync against
        # under-`mu` engine users.  Reentrant because public entry
        # points nest.
        self.emu = new_rlock("devapply.emu")
        self.warmup()

    # ------------------------------------------------------------ jit warmup

    def warmup(self) -> None:
        """Compile every bucket rung once (throwaway state, identical
        shapes).  The signature set is finite by construction, so after
        this pass steady state is zero-recompile (jitguard contract);
        the jit cache is process-global, so only the first engine with
        a given (slots, chain) pays."""
        key = (self.slots, self.chain, self._ladder)
        if key in _WARMED:
            return
        st = make_state(self.slots, self.chain)
        for b in self._ladder:
            # Chain the returned state: the step donates its input.
            st, _ = _dk.apply_step(st, np.repeat(self._fills, b, axis=1))
        _WARMED.add(key)

    # ------------------------------------------------------- batch building

    @_locked
    def batch_reset(self, expected_ops: int) -> None:
        """Start a drain's column build; rebases first if the drain
        could overrun the chain store or the key-table load ceiling
        (conservative: every op counted as a potential new key/node,
        so mid-batch capacity never trips).  Accumulated columns from
        earlier get-free drains persist — they flush on the next get,
        size cap, snapshot cut, or mirror sync, not per drain."""
        if (self._nnext + expected_ops > self.chain
                or len(self._i2k) + expected_ops > self._kcap):
            self._rebase()
            if (self._nnext + expected_ops > self.chain
                    or len(self._i2k) + expected_ops > self._kcap):
                raise RuntimeError(
                    f"devapply table full past rebase (keys="
                    f"{len(self._i2k)}, slots={self.slots}): raise "
                    "TPU6824_DEVAPPLY_SLOTS / TPU6824_DEVAPPLY_CHAIN")
        self._bj = 0
        self._jbase = 0
        del self._bgets[:]

    def batch_op(self, code: int, key: str, value: str) -> int:
        """Append one decided op to the drain's columns; returns its
        drain-local index `j` (stable across mid-drain commits).  The
        whole per-op host cost of the decided path lives here: one
        intern probe (slot memoized) and integer appends — chain nodes
        are a counter bump, the predecessor is a dict lookup.

        Deliberately NOT `_locked`: it runs only between a drain's
        `batch_reset`/`batch_commit` on the driver thread under the
        server's `mu`, so every `emu` holder that touches its state is
        already serialized against it (off-`mu` engine calls happen
        only on the driver thread itself) — and a per-op lock acquire
        is real money on the one per-op path this module has."""
        kid = self._k2i.get(key)
        if kid is None:
            kid = len(self._i2k)
            self._k2i[key] = kid
            self._i2k.append(key)
            self._kslot.append(host_insert(self._htbl, self.slots, kid))
        prev = self._blastw.get(kid, -1)
        if code == K_GET:
            vid = 0
            node = -1
            # (drain-local index, accumulated-column index): the former
            # names the reply, the latter its lane in the flush's pre.
            self._bgets.append((self._bj, len(self._bkinds)))
        else:
            i2v = self._i2v
            vid = len(i2v)
            i2v.append(value)
            node = self._nnext
            self._nnext = node + 1
            self._blastw[kid] = node
            self._bwvid.append(vid)
            self._bwapp.append(code == K_APPEND)
        self._bkinds.append(code)
        self._bslots.append(self._kslot[kid])
        self._bkids.append(kid)
        self._bvids.append(vid)
        self._bnodes.append(node)
        self._bprevs.append(prev)
        j = self._bj
        self._bj = j + 1
        return j

    @_locked
    def batch_commit(self, applied_seq: int):
        """End a drain's column build; returns [(j, pre_node)] for the
        drain's gets.  Always advances `last_applied` to `applied_seq`
        — the snapshot cut asserts against it.

        The device step does NOT run here unless it must: a get-free
        drain is pure integer bookkeeping (the columns carry over), and
        the accumulated batch flushes on the next drain WITH gets, on
        the size cap (one top-rung chunk), or on a snapshot/mirror/
        rebase boundary.  Every flush is a device dispatch the driver
        thread pays a scheduler round-trip for — amortizing it across
        drains is most of the decided-path win on a contended host."""
        self.last_applied = applied_seq
        nops = self._bj - self._jbase
        self._jbase = self._bj
        if nops:
            _M_APPLIED.inc(nops)
        if self._bgets:
            jco = list(self._bgets)
            del self._bgets[:]
            pre = self._flush(need_pre=True)
            return [(j, int(pre[c])) for j, c in jco]
        if len(self._bkinds) >= self._ladder[-1]:
            self._flush()
        return ()

    def _flush(self, need_pre: bool = False):
        """Apply the accumulated columns through the jitted device step
        (oversized batches chunk through the top bucket).  With
        `need_pre` the per-op pre-node column is read back and returned
        (blocking); otherwise the readback stays in flight and only the
        chain-shadow fill is deferred to `_drain_shadow`."""
        n = len(self._bkinds)
        if n == 0:
            if need_pre:
                self._drain_shadow()
            return None
        t0 = time.perf_counter_ns()
        kinds_np = np.asarray(self._bkinds, np.int32)
        slots_np = np.asarray(self._bslots, np.int32)
        kids_np = np.asarray(self._bkids, np.int32)
        vids_np = np.asarray(self._bvids, np.int32)
        nodes_np = np.asarray(self._bnodes, np.int32)
        prevs_np = np.asarray(self._bprevs, np.int32)
        # tmask: each key's LAST write in this commit is the one that
        # scatters into the device table (unique live slot indices).
        # np.unique on the reversed write-kid column finds it without a
        # python loop over ops.
        tmask_np = np.zeros(n, np.int32)
        wpos = np.flatnonzero(nodes_np >= 0)
        nw = len(wpos)
        if nw:
            _, first = np.unique(kids_np[wpos][::-1], return_index=True)
            tmask_np[wpos[nw - 1 - first]] = 1
        wcum = np.cumsum(nodes_np >= 0)
        state = self._state
        top = self._ladder[-1]
        pres = []
        off = 0
        while off < n:
            seg = min(n - off, top)
            b = bucket_for(seg, self._ladder)
            end = off + seg
            buf = np.repeat(self._fills, b, axis=1)
            buf[C_KIND, :seg] = kinds_np[off:end]
            buf[C_SLOT, :seg] = slots_np[off:end]
            buf[C_KID, :seg] = kids_np[off:end]
            buf[C_VID, :seg] = vids_np[off:end]
            buf[C_NODE, :seg] = nodes_np[off:end]
            buf[C_PREV, :seg] = prevs_np[off:end]
            buf[C_TMASK, :seg] = tmask_np[off:end]
            buf[C_NC, 0] = self._nc + int(wcum[end - 1])
            state, pre = _dk.apply_step(state, buf)
            pres.append((pre, seg))  # device future; not yet read back
            off = end
        self._state = state
        nc0 = self._nc
        if nw:
            # Host half of the chain-shadow update: nodes are allocated
            # sequentially at column-build time, so node ids are
            # nc0..nc0+nw-1 in column order and the vids are host data;
            # only an append's prev link waits on the readback.
            self._cvid[nc0:nc0 + nw] = self._bwvid
            self._nc = nc0 + nw
        pre = None
        if need_pre:
            # Pre-nodes wanted NOW (get replies), so this flush pays
            # the blocking readback; deferred shadow fills from earlier
            # flushes complete alongside.
            self._drain_shadow()
            pre = (np.asarray(pres[0][0])[:pres[0][1]] if len(pres) == 1
                   else np.concatenate(
                       [np.asarray(p)[:s] for p, s in pres]))
            if nw:
                self._cprev[nc0:nc0 + nw] = np.where(
                    np.asarray(self._bwapp), pre[wpos], -1)
        elif nw:
            # Leave the readback in flight: the driver thread moves
            # straight on to notify/reply instead of donating its
            # scheduler quantum to a blocking wait.
            self._pending.append(
                (pres, wpos, nc0, np.asarray(self._bwapp)))
        # The columns are on the device now: the host probe memo stays,
        # the batch-local read-after-write memo resets (the table has
        # caught up).
        self._blastw.clear()
        del self._bkinds[:], self._bslots[:], self._bkids[:]
        del self._bvids[:], self._bnodes[:], self._bprevs[:]
        del self._bwvid[:], self._bwapp[:]
        _M_READBACK.inc((time.perf_counter_ns() - t0) // 1000)
        _M_LOAD.set(len(self._i2k) / self.slots)
        return pre

    @_locked
    def note_applied(self, applied_seq: int) -> None:
        """Advance the log watermark past entries with no KV effect
        (gaps, foreign entries, FORGOTTEN fast-forwards): the snapshot
        cut asserts the engine watermark equals the service's, and those
        entries are applied by definition."""
        if applied_seq > self.last_applied:
            self.last_applied = applied_seq

    @_locked
    def get_reply(self, node: int):
        """A flushed get's reply tuple from its pre-node."""
        if node < 0:
            return (ErrNoKey, "")
        return (OK, self.resolve(node))

    @_locked
    def apply_one(self, kind: str, key: str, value: str,
                  applied_seq: int):
        """Scalar fallback (feedless backends drain per op): the same
        device state machine, batch of one."""
        code = _KIND_CODE[kind]
        self.batch_reset(1)
        self.batch_op(code, key, value)
        gres = self.batch_commit(applied_seq)
        if code == K_GET:
            return self.get_reply(gres[0][1])
        return (OK, "")

    # --------------------------------------------------- value resolution

    def _drain_shadow(self) -> None:
        """Materialize deferred chain-prev links from in-flight device
        readbacks (get-free drains skip the blocking wait on the
        decided path; every shadow reader flushes here first)."""
        if not self._pending:
            return
        t0 = time.perf_counter_ns()
        for pres, wpos, nc0, wapp in self._pending:
            pre = (np.asarray(pres[0][0])[:pres[0][1]] if len(pres) == 1
                   else np.concatenate(
                       [np.asarray(p)[:s] for p, s in pres]))
            nw = len(wpos)
            self._cprev[nc0:nc0 + nw] = np.where(wapp, pre[wpos], -1)
        del self._pending[:]
        _M_READBACK.inc((time.perf_counter_ns() - t0) // 1000)

    @_locked
    def resolve(self, node: int) -> DevVal:
        """Chain node → value string, memoized per node: a single-node
        chain hands back the interned string (no new bytes); an append
        chain concatenates once, and any memoized ancestor
        short-circuits the walk."""
        cache = self._node_val
        v = cache.get(node)
        if v is not None:
            return v
        if self._pending:
            self._drain_shadow()
        cvid, cprev, i2v = self._cvid, self._cprev, self._i2v
        parts = []
        cur = node
        while cur >= 0:
            hit = cache.get(cur)
            if hit is not None:
                parts.append(hit)
                break
            parts.append(i2v[cvid[cur]])
            cur = int(cprev[cur])
        if len(parts) == 1:
            s = parts[0]
        else:
            parts.reverse()
            s = "".join(parts)
        v = s if type(s) is DevVal else DevVal(s)
        cache[node] = v
        return v

    # ------------------------------------------------- mirror and snapshots

    @_locked
    def snapshot_cut(self):
        """The under-mutex half of a snapshot: copy the two table
        columns out (the step donates-and-overwrites them in place, so
        a ref capture would not survive the next drain).  Cost is the
        FIXED table capacity — independent of live store size, unlike
        the old path's whole-host-dict copy under `mu`; the copy also
        fences any still-in-flight drain (device ops are ordered), so
        the cut observes exactly the state at `last_applied`."""
        self._flush()  # the device catches up to the watermark first
        st = self._state
        S = self.slots
        return (np.asarray(st.tbl_kid)[:S], np.asarray(st.tbl_node)[:S],
                self.last_applied)

    @_locked
    def snapshot_resolve(self, cut) -> dict:
        """Materialize a cut into the blob's kv dict (the off-mutex
        half).  Safe against later drains on the cutting thread: the
        cut's table columns are host copies, and the chain shadow
        slots and intern ids they reference are append-only history.
        When the cut is still current the result doubles as a mirror
        sync."""
        kid_np, node_np, applied = cut
        t0 = time.perf_counter_ns()
        occ = np.flatnonzero(kid_np >= 0)
        i2k = self._i2k
        res = self.resolve
        d = {}
        for s in occ.tolist():
            d[i2k[kid_np[s]]] = res(int(node_np[s]))
        _M_READBACK.inc((time.perf_counter_ns() - t0) // 1000)
        if applied == self.last_applied:
            self.mirror = d
            self.mirror_applied = applied
            _M_SYNCS.inc()
        return d

    @_locked
    def sync_mirror(self) -> dict:
        """Readback → resolved dict → swap the mirror (cadence / on
        demand / snapshot cut — never the decided path)."""
        return self.snapshot_resolve(self.snapshot_cut())

    def mirror_due(self, applied: int) -> bool:
        return applied - self.mirror_applied >= self.sync_every

    # ------------------------------------------------------ load and rebase

    @_locked
    def load_from_dict(self, kv: dict, applied: int) -> None:
        """Rebuild the device state from a resolved dict (snapshot
        install, runtime enable, rebase): fresh intern tables, host-
        probed key table (bit-identical to device probing — same hash),
        single-node chains."""
        # Complete accumulated columns and in-flight shadow fills
        # against the OLD layout before its arrays are replaced.
        self._flush(need_pre=True)
        S, C = self.slots, self.chain
        if len(kv) > self._kcap or len(kv) > C:
            raise RuntimeError(
                f"devapply cannot hold {len(kv)} keys (slots={S}, "
                f"chain={C}): raise TPU6824_DEVAPPLY_SLOTS")
        k2i: dict[str, int] = {}
        i2k: list[str] = []
        i2v: list[str] = [""]
        kslot: list[int] = []
        tbl = np.full(S + 1, -1, np.int32)
        tnode = np.full(S + 1, -1, np.int32)
        cvid = np.zeros(C, np.int32)
        cprev = np.full(C, -1, np.int32)
        nc = 0
        for k, v in kv.items():
            kid = len(i2k)
            k2i[k] = kid
            i2k.append(k)
            vid = len(i2v)
            i2v.append(v)
            s = host_insert(tbl, S, kid)
            kslot.append(s)
            tnode[s] = nc
            cvid[nc] = vid
            nc += 1
        import jax.numpy as jnp

        dev_cvid = np.zeros(C + 1, np.int32)
        dev_cvid[:C] = cvid
        dev_cprev = np.full(C + 1, -1, np.int32)
        dev_cprev[:C] = cprev
        self._state = DevKVState(
            tbl_kid=jnp.asarray(tbl), tbl_node=jnp.asarray(tnode),
            chain_vid=jnp.asarray(dev_cvid),
            chain_prev=jnp.asarray(dev_cprev),
            n_chain=jnp.int32(nc))
        self._k2i, self._i2k, self._i2v = k2i, i2k, i2v
        # `jnp.asarray` copied `tbl`, so it doubles as the host probe
        # shadow without aliasing device memory.
        self._htbl, self._kslot = tbl, kslot
        self._cvid, self._cprev, self._nc = cvid, cprev, nc
        self._nnext = nc
        self._blastw.clear()
        self._node_val = {}
        self.last_applied = applied
        self.mirror = dict(kv)
        self.mirror_applied = applied
        _M_LOAD.set(len(i2k) / S)

    def _rebase(self) -> None:
        """Collapse chains and GC dead intern ids: readback → resolve →
        rebuild.  The mirror-sync moment; bounds host intern growth and
        chain occupancy between drains."""
        self.load_from_dict(self.sync_mirror(), self.last_applied)
        _M_REBASES.inc()

    @property
    def nkeys(self) -> int:
        return len(self._i2k)

    def table_load(self) -> float:
        return len(self._i2k) / self.slots


class ShardedApplyBank:
    """Stacked per-group device KV states over a mesh's 'g' axis — the
    composition hook `apply_step_groups` promised, made real (meshfab).

    G group states ride ONE stacked DevKVState whose leaves lead with a
    ladder-padded group axis (`jitshape.shard_groups`), applied by
    `parallel.mesh.sharded_apply_step_groups`: one jitted,
    collective-free device step applies EVERY group's drain, each mesh
    shard touching only its own groups' table/chain columns.

    Deliberately leaner than DevApplyEngine — no interning, no mirror,
    no rebase: callers speak integer ids, `(kind, kid, vid)` per op, and
    read back pre-nodes.  The host bookkeeping is the engine's same
    slot-probe/chain-cursor discipline (host_insert against a per-group
    tbl_kid shadow, consecutive chain nodes, last-write tmask,
    same-batch read-after-write prevs), vectorized per group.  The
    kvpaxos decided path keeps DevApplyEngine; this bank is the mesh
    real-path building block the multichip bench and the meshfab smoke
    drive."""

    def __init__(self, mesh, ngroups: int, slots: int = 1 << 10,
                 bucket: int = 256):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        from tpu6824.core.jitshape import shard_groups
        from tpu6824.parallel.mesh import sharded_apply_step_groups

        if slots & (slots - 1):
            raise ValueError(f"slots must be a power of two: {slots}")
        self.mesh = mesh
        self.G_live = int(ngroups)
        self.G = shard_groups(ngroups, mesh.shape["g"])
        self.slots = slots
        self.chain = 4 * slots
        self.bucket = int(bucket)
        self._step = sharded_apply_step_groups(mesh)
        G, S, C = self.G, slots, self.chain
        lead = NamedSharding(mesh, PartitionSpec("g"))
        self._state = DevKVState(
            tbl_kid=jax.device_put(np.full((G, S + 1), -1, np.int32), lead),
            tbl_node=jax.device_put(np.full((G, S + 1), -1, np.int32), lead),
            chain_vid=jax.device_put(np.zeros((G, C + 1), np.int32), lead),
            chain_prev=jax.device_put(np.full((G, C + 1), -1, np.int32),
                                      lead),
            n_chain=jax.device_put(np.zeros(G, np.int32), lead),
        )
        # Host shadows (slot authority + chain walk), per group:
        self._htbl = np.full((G, S + 1), -1, np.int32)
        self._nc = np.zeros(G, np.int64)
        # node → (vid, prev) per group: the host-known chain shadow a
        # get's pre-node resolves through (the bank's analog of the
        # engine's _node_val memo, ids only).
        self._nodes: list[dict] = [dict() for _ in range(G)]
        # kid → last chain node per group — the host shadow of
        # tbl_node, so append chains link across batches exactly as
        # the device's table gather does.
        self._lastn: list[dict] = [dict() for _ in range(G)]
        self._fills = col_fills(S)

    def apply(self, ops_per_group) -> np.ndarray:
        """One stacked device step over every group's ops.

        `ops_per_group`: sequence (≤ G_live long) of per-group op lists,
        each op `(kind, kid, vid)` with kind in {"get", "put",
        "append"}; vid ignored for gets.  Returns the (G, bucket)
        pre-node readback — `pre[g, i]` is group g's op i's key chain
        node BEFORE the op (the get result / append prev), -1 for
        a key never written.  Callers chunk batches wider than
        `bucket` (the jitshape chunking discipline)."""
        import jax

        G, S, B = self.G, self.slots, self.bucket
        if max((len(o) for o in ops_per_group), default=0) > B:
            raise ValueError(f"batch wider than bucket {B}: chunk it")
        cols = np.tile(self._fills, (G, 1, B)).astype(np.int32)
        for g, ops in enumerate(ops_per_group):
            nodes, htbl = self._nodes[g], self._htbl[g]
            lastn = self._lastn[g]
            nc = int(self._nc[g])
            lastw: dict[int, int] = {}
            last_slot: dict[int, int] = {}
            for i, (kind, kid, vid) in enumerate(ops):
                slot = host_insert(htbl, S, kid)
                cols[g, C_SLOT, i] = slot
                cols[g, C_KID, i] = kid
                cols[g, C_PREV, i] = lastw.get(kid, -1)
                if kind == "get":
                    cols[g, C_KIND, i] = K_GET
                    continue
                if nc >= self.chain:
                    raise RuntimeError(
                        f"sharded bank chain full (group {g}): "
                        "snapshot/rebuild before more writes")
                code = _KIND_CODE[kind]
                cols[g, C_KIND, i] = code
                cols[g, C_VID, i] = vid
                cols[g, C_NODE, i] = nc
                prevn = lastw.get(kid, lastn.get(kid, -1))
                nodes[nc] = (vid, prevn if code == K_APPEND else -1)
                lastw[kid] = lastn[kid] = nc
                last_slot[slot] = i
                nc += 1
            for i in last_slot.values():
                cols[g, C_TMASK, i] = 1
            cols[g, C_NC, 0] = nc
            self._nc[g] = nc
        self._state, pre = self._step(self._state, cols)
        # One host readback per stacked batch — the bank's whole-mesh
        # analog of the engine's one-readback-per-flush contract.
        return np.asarray(pre)

    def resolve_chain(self, g: int, node: int) -> list:
        """Value-id segments of the chain ending at `node`, root first
        (a put chain is one segment; appends accumulate)."""
        out = []
        while node >= 0:
            vid, prev = self._nodes[g][node]
            out.append(vid)
            node = prev
        out.reverse()
        return out
