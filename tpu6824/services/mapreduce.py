"""MapReduce — single-machine master/worker MapReduce with fault tolerance.

Capability parity with the reference Lab 1 (`mapreduce/mapreduce.go`,
`master.go`, `worker.go`): split the input into nmap map tasks, hash-partition
map output into nreduce buckets (FNV-1a, `mapreduce.go:185-189`), reduce each
bucket over sorted keys, merge to one sorted output; the master hands tasks to
dynamically-registering workers and re-enqueues a task whose worker failed
(`master.go:50-53`); a worker can be configured to die after N tasks
(`worker.go:60-92`) for churn tests; a sequential mode runs everything inline
(`mapreduce.go:344-356`).

TPU-shaped difference: the per-key partition hashing is a batched device op
(`ops/hashing.ihash_batch`) — one kernel call per map task instead of a
per-key host loop, and the same code path scales to batch-of-tasks on a mesh.
"""

from __future__ import annotations

import queue
import threading
from collections import defaultdict

from tpu6824.ops.hashing import ihash, partition_keys
from tpu6824.utils.errors import RPCError
from tpu6824.utils import crashsink


# --------------------------------------------------------------- data plane


def split_text(text: str, nmap: int) -> list[str]:
    """Split on line boundaries into ~equal byte chunks
    (mapreduce/mapreduce.go:141-179 Split)."""
    if nmap <= 1:
        return [text]
    target = max(1, len(text) // nmap)
    chunks, cur, size = [], [], 0
    for line in text.splitlines(keepends=True):
        cur.append(line)
        size += len(line)
        if size >= target and len(chunks) < nmap - 1:
            chunks.append("".join(cur))
            cur, size = [], 0
    chunks.append("".join(cur))
    while len(chunks) < nmap:
        chunks.append("")
    return chunks


def do_map(chunk: str, map_fn, nreduce: int, use_device: bool = True):
    """Run map_fn over a chunk and hash-partition the emitted pairs into
    nreduce buckets (DoMap, mapreduce/mapreduce.go:193-231)."""
    pairs = list(map_fn(chunk))
    buckets = [[] for _ in range(nreduce)]
    if use_device and len(pairs) >= 64:
        parts = partition_keys([k for k, _ in pairs], nreduce)
        for (k, v), b in zip(pairs, parts):
            buckets[int(b)].append((k, v))
    else:
        for k, v in pairs:
            buckets[ihash(k) % nreduce].append((k, v))
    return buckets


def do_reduce(bucket_pairs, reduce_fn):
    """Group by key, sort keys, apply reduce_fn (DoReduce,
    mapreduce/mapreduce.go:239-280)."""
    grouped: dict[str, list] = defaultdict(list)
    for k, v in bucket_pairs:
        grouped[k].append(v)
    return [(k, reduce_fn(k, grouped[k])) for k in sorted(grouped)]


def merge(reduce_outputs) -> list:
    """Merge the per-bucket sorted outputs into one sorted list
    (Merge, mapreduce/mapreduce.go:284-321)."""
    out = [kv for part in reduce_outputs for kv in part]
    out.sort(key=lambda kv: kv[0])
    return out


def run_sequential(text: str, nmap: int, nreduce: int, map_fn, reduce_fn):
    """RunSingle (mapreduce/mapreduce.go:344-356)."""
    chunks = split_text(text, nmap)
    maps = [do_map(c, map_fn, nreduce) for c in chunks]
    reduces = []
    for r in range(nreduce):
        bucket = [kv for m in maps for kv in m[r]]
        reduces.append(do_reduce(bucket, reduce_fn))
    return merge(reduces)


# --------------------------------------------------------------- workers


class Worker:
    """A map/reduce worker; `nrpc` >= 0 makes it die after that many task
    RPCs (worker.go:60-92) so the master's failure handling is exercised."""

    def __init__(self, name: str, map_fn, reduce_fn, nrpc: int = -1):
        self.name = name
        self.map_fn = map_fn
        self.reduce_fn = reduce_fn
        self.mu = threading.Lock()
        self.nrpc = nrpc
        self.njobs = 0
        self.dead = False

    def do_job(self, kind: str, payload, nreduce: int):
        with self.mu:
            if self.dead or self.nrpc == 0:
                self.dead = True
                raise RPCError(f"worker {self.name} dead")
            if self.nrpc > 0:
                self.nrpc -= 1
            self.njobs += 1
        if kind == "map":
            return do_map(payload, self.map_fn, nreduce)
        return do_reduce(payload, self.reduce_fn)

    def shutdown(self) -> int:
        """Returns the number of jobs performed (checked by the reference's
        `checkWorker`, mapreduce/test_test.go:87-93)."""
        with self.mu:
            self.dead = True
            return self.njobs


# --------------------------------------------------------------- master


class Master:
    """RunMaster (mapreduce/master.go:29-88): a dispatcher loop over an idle-
    worker pool; a failed task RPC re-enqueues the task and retires the
    worker."""

    def __init__(self, text: str, nmap: int, nreduce: int):
        self.text = text
        self.nmap = nmap
        self.nreduce = nreduce
        self.workers: "queue.Queue[Worker]" = queue.Queue()
        self.stats: dict[str, int] = {}
        self._registered: list[Worker] = []
        self._mu = threading.Lock()

    def register(self, w: Worker):
        """Workers announce themselves at any time (the registration RPC
        server, mapreduce/mapreduce.go:92-133)."""
        with self._mu:
            self._registered.append(w)
        self.workers.put(w)

    def _run_phase(self, kind: str, tasks: list):
        """Dispatch `tasks` to workers; barrier until all complete.  Failed
        RPC → task back on the queue (master.go:50-53)."""
        results: list = [None] * len(tasks)
        task_q: "queue.Queue[int]" = queue.Queue()
        for i in range(len(tasks)):
            task_q.put(i)
        done = threading.Semaphore(0)
        ndone = 0

        def dispatch():
            # tpusan: ok(unbounded-retry) — paced by the blocking
            # workers.get(): a failed worker is NOT returned to the
            # pool, so each retry waits for a DIFFERENT idle worker to
            # register — the pool, not a clock, is the bound (the
            # reference's master semantics, mapreduce/master.go).
            while True:
                try:
                    i = task_q.get_nowait()
                except queue.Empty:
                    return
                w = self.workers.get()  # blocks for an idle/registering worker
                try:
                    results[i] = w.do_job(kind, tasks[i], self.nreduce)
                except RPCError:
                    task_q.put(i)  # re-enqueue; w is NOT returned to the pool
                    continue
                self.workers.put(w)
                done.release()

        threads = [
            threading.Thread(
                target=crashsink.guarded(dispatch, "mapreduce-dispatch"),
                daemon=True)
            for _ in range(8)
        ]
        for t in threads:
            t.start()
        for _ in range(len(tasks)):
            done.acquire()
        for t in threads:
            t.join()
        return results

    def run(self):
        """Run() master side (mapreduce/mapreduce.go:369-380 + master.go)."""
        chunks = split_text(self.text, self.nmap)
        maps = self._run_phase("map", chunks)
        buckets = []
        for r in range(self.nreduce):
            buckets.append([kv for m in maps for kv in m[r]])
        reduces = self._run_phase("reduce", buckets)
        with self._mu:
            self.stats = {w.name: w.njobs for w in self._registered}
        return merge(reduces)


def run_distributed(text, nmap, nreduce, map_fn, reduce_fn, nworkers=3,
                    worker_nrpc=-1):
    """Boot a master + workers (the wc.go master/worker modes,
    main/wc.go:17-58)."""
    m = Master(text, nmap, nreduce)
    for i in range(nworkers):
        m.register(Worker(f"w{i}", map_fn, reduce_fn, nrpc=worker_nrpc))
    return m.run()


# --------------------------------------------------------------- apps


def wc_map(chunk: str):
    """Word count mapper (main/wc.go semantics: words are runs of letters)."""
    word = []
    for ch in chunk:
        if ch.isalpha():
            word.append(ch)
        else:
            if word:
                yield ("".join(word), "1")
            word = []
    if word:
        yield ("".join(word), "1")


def wc_reduce(key: str, values: list) -> str:
    return str(sum(int(v) for v in values))
