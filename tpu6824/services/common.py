"""Shared service-layer plumbing.

The reference's clerks talk to servers through `call()` — a dial-per-call RPC
that can fail before OR after the server executed the op
(`lockservice/client.go:26-40` spells out the contract).  Host services here
are plain objects, so the lossy client↔server leg is reproduced explicitly:
`flaky_call` drops a request before processing (op not executed) or drops the
reply after processing (op executed, caller can't tell) with the reference
accept-loop rates (`paxos/paxos.go:528-544`)."""

from __future__ import annotations

import os
import random
import threading
import time
from array import array

from tpu6824.obs import metrics as _metrics
from tpu6824.obs import tracing as _tracing
from tpu6824.utils.errors import RPCError
from tpu6824.utils.locks import new_lock

REQ_DROP = 0.10
REP_DROP = 0.20

_sysrand = random.SystemRandom()

# tpuscope metrics (module scope per the metric-unregistered rule):
# clerk retry pacing — how often clerks back off and for how long — and
# the in-process clerk↔server leg's fault-coin outcomes.
_M_BACKOFFS = _metrics.counter("clerk.backoff.sleeps")
_M_BACKOFF_US = _metrics.histogram("clerk.backoff.sleep_us")
_M_BUDGET_WAITS = _metrics.counter("clerk.backoff.budget_waits")
_M_FLAKY_DROP_REQ = _metrics.counter("clerk.flaky.dropped_requests")
_M_FLAKY_DROP_REP = _metrics.counter("clerk.flaky.dropped_replies")

# Retry BUDGET (ISSUE 12): sustained retries/sec a clerk may spend and
# the burst it may front-load.  Generous enough that healthy traffic
# and short blips never touch it (the jitter curve tops out near
# 10/s–500/s only in pathological storms); a clerk stuck in a retry
# storm decays to the sustained rate instead of amplifying.  0 disables.
RETRY_BUDGET_RATE = float(os.environ.get("TPU6824_RETRY_BUDGET", 50.0))
RETRY_BUDGET_BURST = float(os.environ.get("TPU6824_RETRY_BURST", 100.0))


class Backoff:
    """Clerk retry pacing: capped exponential backoff with DECORRELATED
    jitter (base 2ms, cap 100ms) by default, or the reference's fixed
    cadence via TPU6824_CLERK_BACKOFF=fixed.

    The reference clerks sleep a flat 10ms between retries
    (`kvpaxos/client.go:69-104` and kin) — under partition churn every
    blocked clerk then retries in phase, hammering the same minority
    server at 100Hz exactly when it can least make progress.
    Decorrelated jitter (sleep' = U(base, 3·sleep), capped) spreads the
    herd AND backs a long outage off toward the cap, while the first
    retry stays ~2ms so transient blips cost less latency than the flat
    10ms did.  `reset()` after a success so the next outage starts from
    the base again.

    Mode resolution: explicit `mode` arg > $TPU6824_CLERK_BACKOFF >
    jitter.  `fixed` keeps the 10ms cadence (fidelity tests pin this —
    and skips the budget, reference fidelity being the point of the
    mode); unknown values fall back to jitter.  Each Backoff owns a
    seeded RNG, so a seeded clerk's retry pattern is reproducible.

    Retry budget (ISSUE 12): each `sleep()` spends one token from a
    per-clerk bucket (RETRY_BUDGET_BURST capacity, refilled at
    RETRY_BUDGET_RATE/s).  An exhausted bucket stretches the sleep to
    the token-accrual time, so a clerk's sustained retry rate can
    never exceed the budget no matter what the backoff curve or the
    failure pattern does — retry storms decay by construction instead
    of amplifying (the 3× retry-collapse PR 8 fixed by schedule
    becomes structurally impossible).  `reset()` resets the
    exponential, NOT the bucket: the budget is a sustained-rate bound,
    not a per-outage one."""

    FIXED_SLEEP = 0.01  # the reference cadence (fixed mode)

    def __init__(self, base: float = 0.002, cap: float = 0.1,
                 mode: str | None = None, seed: int | None = None,
                 fixed_sleep: float = FIXED_SLEEP,
                 budget_rate: float | None = None,
                 budget_burst: float | None = None):
        self.base = base
        self.cap = cap
        self.mode = mode or os.environ.get("TPU6824_CLERK_BACKOFF", "jitter")
        self.fixed_sleep = fixed_sleep
        self._rng = random.Random(seed) if seed is not None \
            else random.Random(_sysrand.getrandbits(62))
        self._sleep = base
        self.budget_rate = RETRY_BUDGET_RATE if budget_rate is None \
            else float(budget_rate)
        self.budget_burst = RETRY_BUDGET_BURST if budget_burst is None \
            else float(budget_burst)
        self._tokens = self.budget_burst
        self._refill_at = time.monotonic()

    def next_interval(self) -> float:
        if self.mode == "fixed":
            return self.fixed_sleep
        s = min(self.cap, self._rng.uniform(self.base, self._sleep * 3))
        self._sleep = s
        return s

    def _budget_extend(self, dt: float) -> float:
        """Spend one retry token (borrowing allowed); when the bucket
        went dry, stretch `dt` to the accrual time of the debt — the
        sleep itself refills the bucket (accounted by elapsed time at
        the next call), so the sustained retry rate is exactly
        budget_rate."""
        if self.budget_rate <= 0 or self.mode == "fixed":
            return dt
        now = time.monotonic()
        self._tokens = min(self.budget_burst,
                           self._tokens
                           + (now - self._refill_at) * self.budget_rate)
        self._refill_at = now
        self._tokens -= 1.0
        # Debt floor: callers clamp sleeps to their remaining deadline
        # (max_s), so the stretched interval may never actually be
        # slept — without a floor, a long storm of clamped sleeps
        # accrues unbounded debt and a later UNclamped sleep would
        # block for all of it at once.  One burst of debt is the cap.
        if self._tokens < -self.budget_burst:
            self._tokens = -self.budget_burst
        if self._tokens < 0.0:
            need = -self._tokens / self.budget_rate
            if need > dt:
                _M_BUDGET_WAITS.inc()
                dt = need
        return dt

    def sleep(self, max_s: float | None = None) -> float:
        """Sleep the next interval — budget-extended when the retry
        bucket is dry — clamped to `max_s` (callers pass their
        remaining deadline so a stretched backoff can never overshoot a
        short op timeout)."""
        dt = self._budget_extend(self.next_interval())
        if max_s is not None:
            dt = max(0.0, min(dt, max_s))
        _M_BACKOFFS.inc()
        _M_BACKOFF_US.observe(dt * 1e6)
        time.sleep(dt)
        return dt

    def reset(self) -> None:
        self._sleep = self.base


class ColumnarDups:
    """Array-backed at-most-once duplicate store: cid → (max cseq, reply).

    The per-client dup filter is the hottest host-side state on the
    request path — every submit checks it and every applied op updates
    it.  The dict-of-tuples version allocates a fresh `(cseq, reply)`
    tuple per update and per miss-default; this store keeps one slot
    per client with the cseq column in a C int64 array and the reply
    refs in a parallel list, so the apply batch updates cells in place
    (zero allocation for a returning client) and the submit-side check
    is a dict probe + array read.

    `apply_batch` is the once-per-drain columnar update path: the apply
    loop collects its (cid → cseq, reply) writes in a plain dict (which
    also gives intra-batch read-your-writes via `pend`) and this folds
    them into the columns in one pass — one slot lookup per unique
    client per drain instead of one per op.

    Retirement (ISSUE 14, horizon): a third parallel column tracks the
    APPLIED LOG SEQ of each client's newest op (`pend` values may be
    (cseq, reply, seq) 3-tuples), and `retire_below(floor)` folds out
    every row whose last activity predates `floor` — called ONLY from
    the replicated `compact` log entry's apply, so every replica
    retires the identical rows at the identical log position and the
    table stays log-deterministic (what at-most-once rests on).  Rows
    written without a seq (legacy callers) carry -1 and are never
    retired.

    NOT thread-safe: callers hold the server mutex, exactly as they did
    for the dict it replaces."""

    __slots__ = ("_slot", "_cseqs", "_replies", "_seqs")

    def __init__(self, items=()):
        self._slot: dict[object, int] = {}
        self._cseqs = array("q")
        self._replies: list[object] = []
        self._seqs = array("q")  # applied seq of the row's newest op
        for cid, (cseq, reply) in dict(items).items():
            self.put(cid, cseq, reply)

    def __len__(self) -> int:
        return len(self._slot)

    def __contains__(self, cid) -> bool:
        return cid in self._slot

    def seen(self, cid) -> int:
        """Highest applied cseq for `cid` (-1 for a new client) — the
        submit-side dedup probe, tuple-free."""
        i = self._slot.get(cid)
        return -1 if i is None else self._cseqs[i]

    def seen_many(self, cids) -> list:
        """Columnar dedup probe over a native cid column (ISSUE 11):
        `cids` is a sequence of client ids (a numpy int64 array's
        .tolist(), or any iterable of ints); returns the parallel list
        of highest-applied cseqs (-1 for new clients).  One tight pass,
        no per-op tuple — the submit_columnar side of the at-most-once
        filter."""
        slot_get = self._slot.get
        cseqs = self._cseqs
        return [-1 if i is None else cseqs[i]
                for i in map(slot_get, cids)]

    def get(self, cid, default=(-1, None)):
        """Dict-compatible read: (max cseq, reply) or `default`."""
        i = self._slot.get(cid)
        if i is None:
            return default
        return (self._cseqs[i], self._replies[i])

    def reply(self, cid):
        """The cached reply ref for `cid` (caller checked `seen`)."""
        return self._replies[self._slot[cid]]

    def put(self, cid, cseq, reply, seq: int = -1) -> None:
        i = self._slot.get(cid)
        if i is None:
            self._slot[cid] = len(self._cseqs)
            self._cseqs.append(cseq)
            self._replies.append(reply)
            self._seqs.append(seq)
        else:
            self._cseqs[i] = cseq
            self._replies[i] = reply
            self._seqs[i] = seq

    def __setitem__(self, cid, pair) -> None:
        self.put(cid, pair[0], pair[1])

    def apply_batch(self, pend: dict) -> None:
        """Fold a drain's collected (cid → (cseq, reply[, seq])) writes
        into the columns — the once-per-drain batch update."""
        slot_get = self._slot.get
        cseqs = self._cseqs
        replies = self._replies
        seqs = self._seqs
        for cid, ent in pend.items():
            cseq, reply = ent[0], ent[1]
            seq = ent[2] if len(ent) > 2 else -1
            i = slot_get(cid)
            if i is None:
                self._slot[cid] = len(cseqs)
                cseqs.append(cseq)
                replies.append(reply)
                seqs.append(seq)
            else:
                cseqs[i] = cseq
                replies[i] = reply
                seqs[i] = seq

    def retire_below(self, seq_floor: int) -> int:
        """Fold out every row whose last applied seq is below
        `seq_floor` (rows with no recorded seq, -1, are kept); returns
        the retired count.  Deterministic rebuild — callers invoke this
        only from a replicated compact entry's apply."""
        seqs = self._seqs
        keep = [(cid, i) for cid, i in self._slot.items()
                if not (0 <= seqs[i] < seq_floor)]
        retired = len(self._slot) - len(keep)
        if not retired:
            return 0
        cseqs, replies = self._cseqs, self._replies
        self._slot = {}
        self._cseqs = array("q")
        self._replies = []
        self._seqs = array("q")
        for cid, i in keep:
            self.put(cid, cseqs[i], replies[i], seqs[i])
        return retired

    def last_seq(self, cid) -> int:
        i = self._slot.get(cid)
        return -1 if i is None else self._seqs[i]

    def items(self):
        cseqs = self._cseqs
        replies = self._replies
        for cid, i in self._slot.items():
            yield cid, (cseqs[i], replies[i])

    def items_with_seq(self):
        """(cid, (cseq, reply, last_seq)) rows — the snapshot export
        shape, so an installed table keeps its retirement clock."""
        cseqs = self._cseqs
        replies = self._replies
        seqs = self._seqs
        for cid, i in self._slot.items():
            yield cid, (cseqs[i], replies[i], seqs[i])

    def to_dict(self) -> dict:
        """Plain-dict snapshot (persistence / shard-transfer interop)."""
        return dict(self.items())


def pull_from_peers(attempt_once, deadline_s: float,
                    is_dead=None, retry_sleep: float = 0.15) -> str:
    """THE peer-recovery retry discipline (ISSUE 14, generalized from
    diskv's `_snapshot_from_peer` so every service shares one hardened
    implementation).  `attempt_once()` tries every reachable donor once
    and returns:

      - "ok"          — state adopted; done.
      - "behind"      — every REACHABLE donor is at/below our watermark
                        (nothing to pull, ever): limping is safe.
      - "unreachable" — donors exist but none answered this pass (busy
                        mutex, mid-persist fsync, partition): retried
                        until `deadline_s`, because treating a busy
                        donor like "no donor exists" lets the caller's
                        limp-forward path permanently skip GC'd data a
                        donor could still supply (the PR 7 flake).

    `deadline_s=0` is the single-pass form (drain-path callers, whose
    tick cadence IS the retry loop); boot-path callers pass seconds."""
    deadline = time.monotonic() + deadline_s
    while True:
        st = attempt_once()
        if st != "unreachable" or (is_dead is not None and is_dead()) \
                or time.monotonic() >= deadline:
            return st
        time.sleep(retry_sleep)


def fresh_cid() -> int:
    """Unique client id — 62-bit random, exactly the reference's nrand()
    (`kvpaxos/client.go` et al).  Must NOT be a per-process counter: clerks
    in different OS processes would collide (cid=1, 2, ...) and each other's
    ops would be swallowed by the servers' duplicate filters."""
    return _sysrand.getrandbits(62)


class DecidedTap:
    """Reassembles a decided-delta feed (`PaxosFabric.subscribe_decided`)
    into the contiguous run an RSM applies.

    The feed delivers (seq, value) as cells decide — unordered across
    seqs, since Paxos instances resolve independently.  The tap buffers
    out-of-order arrivals and `pop_ready(applied)` returns the values for
    seqs applied+1, applied+2, ... up to the first gap — exactly the
    prefix `drain_decided(applied + 1)` would return, without any replica
    re-scanning the fabric mirrors (the fan-out replaces P duplicate
    vectorized scans per group per driver tick).

    Single-consumer, no locking of its own: called from the one driver
    thread that owns `applied`."""

    __slots__ = ("sub", "pending", "_booted", "_gap_at", "_gap_passes")

    # How many consecutive empty drains the SAME gap must block before
    # should_probe_min re-probes the backend's Min() (see below).
    GAP_PROBE_PASSES = 8

    def __init__(self, sub):
        self.sub = sub
        self.pending: dict[int, object] = {}
        self._booted = False    # one unconditional boot-time probe
        self._gap_at = -1       # seq the last empty drain blocked on
        self._gap_passes = 0

    def pop_ready(self, applied: int) -> list:
        """Values decided at applied+1..applied+k (contiguous); [] if
        applied+1 hasn't been delivered yet."""
        pending = self.pending
        for seq, val in self.sub.pop():
            if seq > applied:
                pending[seq] = val
        out = []
        nxt = applied + 1
        while nxt in pending:
            out.append(pending.pop(nxt))
            nxt += 1
        if out:
            self._gap_at = -1  # progress: any prior gap is gone
        return out

    def should_probe_min(self, applied: int) -> bool:
        """Gate the consumer's FORGOTTEN probe (a Min() call on the
        consensus backend — a fabric-lock acquisition) after an empty
        `pop_ready`.  While the subscriber lives, the window GC can never
        pass its own `applied` (Min waits on its Done), so a gap below
        Min is only possible when the subscription started on an
        already-GC'd group (warm boot / checkpoint restore): probe once
        at boot, then only when the SAME gap has blocked
        `GAP_PROBE_PASSES` consecutive drains — transient out-of-order
        decide gaps are the common case, and probing each would
        re-create the per-pass lock traffic the feed removes."""
        probe = not self._booted
        self._booted = True
        if self.pending:
            if applied + 1 == self._gap_at:
                self._gap_passes += 1
                probe = probe or self._gap_passes >= self.GAP_PROBE_PASSES
            else:
                self._gap_at = applied + 1
                self._gap_passes = 0
        if probe:
            self._gap_passes = 0
        return probe

    def discard_through(self, applied: int) -> None:
        """Drop buffered entries at or below `applied` (after a FORGOTTEN
        fast-forward, or when the server applied seqs through another
        path, e.g. shardkv's _sync walk)."""
        pending = self.pending
        for seq in [s for s in pending if s <= applied]:
            del pending[seq]

    def close(self) -> None:
        self.sub.close()


class FlakyNet:
    """Per-server unreliability switch for the clerk↔server leg."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self._unreliable: set[object] = set()
        # Budgeted tightly: this lock sits on EVERY clerk-leg call; it
        # may only ever guard the two RNG draws + the membership probe
        # (the fault-injected fn itself runs outside it).
        self._lock = new_lock("FlakyNet._lock", hold_budget_s=0.05)

    def set_unreliable(self, server_key, flag: bool):
        with self._lock:
            if flag:
                self._unreliable.add(server_key)
            else:
                self._unreliable.discard(server_key)

    def call(self, server_key, fn, *args, **kwargs):
        """Invoke fn; under unreliability, maybe drop the request (RPCError
        before execution) or the reply (fn runs, RPCError after) — the two
        failure modes at-most-once machinery must survive.

        Trace propagation: when the calling thread carries a tpuscope
        context (the clerk opened a root span), the leg is wrapped in an
        `rpc.call` child span and the span's context is made current for
        the downcall — the in-process twin of `transport.call`'s wire
        envelope, so the server-side submit stamps the same chain."""
        with self._lock:
            unrel = server_key in self._unreliable
            r1 = self._rng.random()
            r2 = self._rng.random()
        if unrel and r1 < REQ_DROP:
            _M_FLAKY_DROP_REQ.inc()
            raise RPCError("request dropped")
        sp = _tracing.child("rpc.call", comp="rpc") \
            if _tracing.enabled() else None
        if sp is None:
            out = fn(*args, **kwargs)
        else:
            try:
                with _tracing.use_ctx(sp.ctx):
                    out = fn(*args, **kwargs)
            finally:
                sp.end()
        if unrel and r2 < REP_DROP:
            _M_FLAKY_DROP_REP.inc()
            raise RPCError("reply dropped")
        return out
