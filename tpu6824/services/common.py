"""Shared service-layer plumbing.

The reference's clerks talk to servers through `call()` — a dial-per-call RPC
that can fail before OR after the server executed the op
(`lockservice/client.go:26-40` spells out the contract).  Host services here
are plain objects, so the lossy client↔server leg is reproduced explicitly:
`flaky_call` drops a request before processing (op not executed) or drops the
reply after processing (op executed, caller can't tell) with the reference
accept-loop rates (`paxos/paxos.go:528-544`)."""

from __future__ import annotations

import random
import threading

from tpu6824.utils.errors import RPCError

REQ_DROP = 0.10
REP_DROP = 0.20

_sysrand = random.SystemRandom()


def fresh_cid() -> int:
    """Unique client id — 62-bit random, exactly the reference's nrand()
    (`kvpaxos/client.go` et al).  Must NOT be a per-process counter: clerks
    in different OS processes would collide (cid=1, 2, ...) and each other's
    ops would be swallowed by the servers' duplicate filters."""
    return _sysrand.getrandbits(62)


class FlakyNet:
    """Per-server unreliability switch for the clerk↔server leg."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self._unreliable: set[object] = set()
        self._lock = threading.Lock()

    def set_unreliable(self, server_key, flag: bool):
        with self._lock:
            if flag:
                self._unreliable.add(server_key)
            else:
                self._unreliable.discard(server_key)

    def call(self, server_key, fn, *args, **kwargs):
        """Invoke fn; under unreliability, maybe drop the request (RPCError
        before execution) or the reply (fn runs, RPCError after) — the two
        failure modes at-most-once machinery must survive."""
        with self._lock:
            unrel = server_key in self._unreliable
            r1 = self._rng.random()
            r2 = self._rng.random()
        if unrel and r1 < REQ_DROP:
            raise RPCError("request dropped")
        out = fn(*args, **kwargs)
        if unrel and r2 < REP_DROP:
            raise RPCError("reply dropped")
        return out
