"""horizon — service-level log compaction, snapshot-install catch-up,
and bounded-memory operation for the replicated-KV services (ISSUE 14,
the compaction half of ROADMAP item 3).

The fabric's window GC (Done()/Min()) has always been able to reclaim
instance slots, but nothing above it ever shrank: kvpaxos/shardkv dup
tables, txnkv's decision records, and the replay state a revived replica
needs all grew monotonically with every decided op, and a replica
revived BEHIND Min() could only catch up in diskv (which persists its
state).  This module closes both gaps for the in-memory services:

  - **Snapshotter** — a per-server snapshot cell: every `snapshot_every`
    applied ops the server copies its applied state under its own mutex
    (copy only — serialization and any disk spill run OFF the lock,
    checkpointd-style), frames it with the PR 7 checksum frame
    (`core.fabric.frame_checkpoint`), publishes the immutable
    `(applied, bytes)` pair for lock-free donor serving, and optionally
    spills it durably (durafs discipline) when a `persist_dir` is
    configured.  The published snapshot is what `snapshot_fetch` serves
    — chunked, resumable, never under `mu` (the tpusan rules).
  - **Catch-up** — a server whose next-needed seq is below a peer's
    Min() installs a peer snapshot over the `snapshot_fetch` route and
    resumes log replay from the watermark.  The "behind vs unreachable"
    retry discipline diskv pioneered lives in
    `services.common.pull_from_peers`; this module supplies the chunked
    fetch/assemble half (`install_from_peer`).
  - **Compaction horizon** — dup-table retirement and txn record GC are
    driven by a REPLICATED `compact` log entry (proposed by any
    replica's snapshot cadence, applied deterministically by all), so
    every replica trims the identical rows at the identical log
    position: host state stays log-deterministic, which is the property
    at-most-once rests on.  The trim thresholds are expressed in
    applied-ops (log progress), not wall time, so replay is exact.

Knobs (TUNING round 18): `TPU6824_SNAPSHOT_EVERY` (applied ops between
snapshots; 0 disables — the per-server `snapshot_every=` kwarg
overrides), `TPU6824_SNAPSHOT_KEEP` (persisted files kept),
`TPU6824_SNAP_CHUNK` (fetch chunk bytes), `TPU6824_DUP_RETIRE_OPS`
(dup rows idle for this many applied ops fold out at the next compact;
0 disables), and the txnkv linger knobs documented there.
"""

from __future__ import annotations

import os
import pickle
import re
import threading

from tpu6824.core.fabric import (  # the PR 7 checksum frame, reused
    CorruptCheckpointError,
    frame_checkpoint,
    unframe_checkpoint,
)
from tpu6824.obs import metrics as _metrics
from tpu6824.utils import durafs
from tpu6824.utils.locks import new_lock

__all__ = [
    "Snapshotter", "install_from_peer", "load_newest",
    "SNAPSHOT_EVERY", "DUP_RETIRE_OPS", "CHUNK_BYTES",
    "register_tracker", "unregister_tracker", "sample_gauges",
]

#: Applied-ops cadence between service snapshots (0 = no snapshots; the
#: per-server kwarg overrides).  Deliberately an env default so soaks
#: and deployments can turn bounded-memory operation on fleet-wide.
SNAPSHOT_EVERY = int(os.environ.get("TPU6824_SNAPSHOT_EVERY", "0"))
#: Persisted snapshot files kept per server (persist_dir spill).
SNAPSHOT_KEEP = int(os.environ.get("TPU6824_SNAPSHOT_KEEP", "2"))
#: Dup-table retirement horizon in applied ops: a client row whose last
#: applied op is older than this folds into the snapshot at the next
#: compact entry (0 disables).  Must comfortably exceed any clerk retry
#: window measured in ops — a retry of a retired row would re-apply.
DUP_RETIRE_OPS = int(os.environ.get("TPU6824_DUP_RETIRE_OPS", "0"))
#: snapshot_fetch chunk size (bytes) — the resumable-install unit.
CHUNK_BYTES = int(os.environ.get("TPU6824_SNAP_CHUNK", str(256 * 1024)))

# Persisted snapshot naming: monotone applied watermark, so "newest" is
# an ordering on names (never mtimes), checkpointd-style.
SNAP_RE = re.compile(r"^svc-(\d{12})\.bin$")

# tpuscope metrics (module scope per the metric-unregistered rule).
_M_SNAPSHOTS = _metrics.counter("horizon.snapshots")
_M_INSTALLS = _metrics.counter("horizon.installs")
_M_INSTALL_BYTES = _metrics.counter("horizon.install_bytes")
_M_DUP_RETIRED = _metrics.counter("horizon.dup_retired")
_G_SNAP_BYTES = _metrics.gauge("horizon.snapshot_bytes")
# Row-count gauges the bounded-memory contract watches (summed across
# every registered tracker by `sample_gauges`, which pulse drives).
_G_KV_ROWS = _metrics.gauge("horizon.kv_rows")
_G_DUP_ROWS = _metrics.gauge("horizon.dup_rows")
_G_PREPARED = _metrics.gauge("horizon.txn_prepared_rows")
_G_DECISIONS = _metrics.gauge("horizon.txn_decision_rows")
_G_DONE_ROWS = _metrics.gauge("horizon.txn_done_rows")
_G_WINDOW = _metrics.gauge("horizon.window_live_slots")


def note_dup_retired(n: int) -> None:
    """Counter hook for the services' compact applies (the metric
    object stays module-scoped here per the metric-unregistered rule)."""
    _M_DUP_RETIRED.inc(n)


class Snapshotter:
    """One server's snapshot cell: cadence bookkeeping + the published
    immutable snapshot + optional durable spill.

    Thread contract: `due`/`note_applied` are called with the server
    mutex held (cheap int math); `publish` runs OFF the mutex with the
    already-copied state; `chunk` is called from ANY thread with no lock
    at all — it reads the one-slot `self.snap` reference atomically
    (tuple publication is a single store under the GIL) and never
    blocks, per the never-under-mu donor rule."""

    def __init__(self, every: int | None = None,
                 persist_dir: str | None = None,
                 keep: int | None = None):
        self.every = SNAPSHOT_EVERY if every is None else int(every)
        self.persist_dir = persist_dir
        self.keep = max(1, SNAPSHOT_KEEP if keep is None else int(keep))
        #: (applied, framed_bytes) — immutable once published.
        self.snap: tuple[int, bytes] | None = None
        self.written = 0
        self.last_applied = -1  # watermark of the newest snapshot
        #: A puller found our snapshot stale: cut a fresh one promptly
        #: (checked by the owner's driver/ticker next pass).
        self.nudged = False
        if persist_dir:
            os.makedirs(persist_dir, exist_ok=True)

    def enabled(self) -> bool:
        return self.every > 0

    def due(self, applied: int) -> bool:
        """True when `applied` has advanced at least `every` ops past
        the newest snapshot (or a puller nudged us)."""
        if not self.enabled():
            return False
        if applied < 0:
            return False
        if self.nudged and applied > self.last_applied:
            return True
        return applied - self.last_applied >= self.every

    def publish(self, applied: int, blob: dict) -> bytes:
        """Serialize + frame + publish `blob` as the snapshot at
        `applied`; spill durably when persist_dir is set.  Runs OFF the
        server mutex (the caller copied the state under it)."""
        framed = frame_checkpoint(
            pickle.dumps(blob, protocol=pickle.HIGHEST_PROTOCOL))
        self.snap = (applied, framed)
        self.last_applied = applied
        self.nudged = False
        self.written += 1
        _M_SNAPSHOTS.inc()
        _G_SNAP_BYTES.set(len(framed))
        if self.persist_dir:
            path = os.path.join(self.persist_dir,
                                f"svc-{applied:012d}.bin")
            durafs.atomic_write(path, framed)
            self._prune()
        return framed

    def _prune(self) -> None:
        snaps = sorted(
            ((int(m.group(1)), n) for n in os.listdir(self.persist_dir)
             for m in (SNAP_RE.match(n),) if m),
            reverse=True)
        for _seq, name in snaps[self.keep:]:
            try:
                os.unlink(os.path.join(self.persist_dir, name))
            except OSError:
                continue
        # Torn-write debris from an injected/real fault mid-spill: the
        # SNAP_RE never matches a ".tmp", so sweep it like checkpointd
        # does or a fault-heavy soak grows the dir without bound.
        for name in os.listdir(self.persist_dir):
            if name.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(self.persist_dir, name))
                except OSError:
                    continue

    # ------------------------------------------------------- donor side

    def chunk(self, floor: int, off: int, n: int | None = None,
              donor_applied: int = -1) -> dict:
        """One `snapshot_fetch` answer — lock-free (see class docstring).

        Returns {"applied", "total", "off", "data"} for a snapshot that
        covers `floor`; {"behind": True} when the donor itself has not
        applied to `floor` (`donor_applied` is the donor's live
        watermark, passed by the RPC wrapper); {"stale": True} when the
        donor HAS the state but its published snapshot predates `floor`
        — the puller retries after the donor's nudged cadence cuts a
        fresh one."""
        n = CHUNK_BYTES if n is None else min(int(n), 4 * CHUNK_BYTES)
        snap = self.snap  # one atomic read; immutable afterwards
        if snap is None or snap[0] < floor:
            if donor_applied >= 0 and donor_applied < floor:
                return {"behind": True, "applied": donor_applied}
            self.nudged = True
            return {"stale": True,
                    "applied": -1 if snap is None else snap[0]}
        applied, framed = snap
        off = max(0, int(off))
        return {"applied": applied, "total": len(framed), "off": off,
                "data": framed[off:off + n]}


def decode_snapshot(framed: bytes) -> dict:
    """Verified blob of a framed service snapshot (raises
    CorruptCheckpointError on a torn/bit-rotted frame)."""
    return pickle.loads(unframe_checkpoint(framed, "<service-snapshot>"))


def load_newest(persist_dir: str):
    """(applied, blob) from the newest VALID persisted snapshot under
    `persist_dir`, discarding torn frames newest-first (the durafault
    acceptance property), or None when nothing restores."""
    try:
        names = os.listdir(persist_dir)
    except FileNotFoundError:
        return None
    snaps = sorted(((int(m.group(1)), n) for n in names
                    for m in (SNAP_RE.match(n),) if m), reverse=True)
    for applied, name in snaps:
        try:
            with open(os.path.join(persist_dir, name), "rb") as f:
                return applied, decode_snapshot(f.read())
        except (CorruptCheckpointError, OSError, pickle.UnpicklingError,
                EOFError):
            continue
    return None


def install_from_peer(fetch, floor: int) -> tuple[str, int, dict | None]:
    """Pull one donor's snapshot through its chunked `snapshot_fetch`
    surface: `fetch(floor, off, n)` is the bound RPC.  Returns
    (status, applied, blob): status "ok" (blob decoded, covers floor),
    "behind" (donor itself below floor), or "unreachable" (stale
    snapshot pending a nudge, torn data, or transport failure — the
    caller's pull_from_peers discipline retries).

    Resumable by construction: a published snapshot is immutable per
    `applied`, so chunks re-fetched after a transient failure continue
    at the same offset; a donor that re-snapshotted mid-pull (applied
    changed) restarts the assembly at the new watermark."""
    buf = bytearray()
    applied = -1
    while True:
        try:
            r = fetch(floor, len(buf), CHUNK_BYTES)
        except Exception:  # noqa: BLE001 — transport failure: next donor
            return "unreachable", -1, None
        if not isinstance(r, dict):
            return "unreachable", -1, None
        if r.get("behind"):
            return "behind", int(r.get("applied", -1)), None
        if r.get("stale"):
            return "unreachable", int(r.get("applied", -1)), None
        if r["applied"] != applied:
            # First chunk, or the donor re-snapshotted mid-pull:
            # restart assembly at the new (immutable) watermark.
            applied = r["applied"]
            buf = bytearray()
            if r["off"] != 0:
                continue  # re-request from 0 against the new snapshot
        buf += r["data"]
        if len(buf) >= r["total"]:
            break
        if not r["data"]:
            return "unreachable", applied, None  # donor went quiet
    try:
        blob = decode_snapshot(bytes(buf))
    except (CorruptCheckpointError, pickle.UnpicklingError, EOFError):
        return "unreachable", applied, None
    _M_INSTALLS.inc()
    _M_INSTALL_BYTES.inc(len(buf))
    return "ok", applied, blob


# ---------------------------------------------------- row-count gauges
# The bounded-memory observability satellite: servers register a
# tracker callable returning their live row counts; `sample_gauges`
# (driven by pulse's per-tick sampler hook) sums them into the horizon.*
# gauges so the memory-growth watchdog and the soak assertions read one
# surface.  Registration is explicit and unregistration happens at
# kill(), so the registry is bounded by live servers.

_trackers_mu = new_lock("horizon.trackers_mu")
_trackers: dict[object, object] = {}  # key -> fn() -> dict


def register_tracker(key, fn) -> None:
    with _trackers_mu:
        _trackers[key] = fn
    # Ride the pulse sampling clock, whichever side starts first: the
    # GLOBAL sampler registry is consulted by every Pulse instance at
    # each tick, so gauges refresh at sampling cadence with no thread
    # of their own and no registration-order dependency.
    try:
        from tpu6824.obs import pulse as _pulse

        _pulse.add_global_sampler(sample_gauges)
    except Exception:  # noqa: BLE001 — gauges are advisory telemetry
        pass


def unregister_tracker(key) -> None:
    with _trackers_mu:
        _trackers.pop(key, None)


_GAUGE_FIELDS = (
    ("kv_rows", _G_KV_ROWS),
    ("dup_rows", _G_DUP_ROWS),
    ("txn_prepared_rows", _G_PREPARED),
    ("txn_decision_rows", _G_DECISIONS),
    ("txn_done_rows", _G_DONE_ROWS),
    ("window_live_slots", _G_WINDOW),
)


def sample_gauges() -> dict:
    """Sum every registered tracker's row counts into the horizon.*
    gauges; returns the totals (the soak assertions read them
    directly).  Window cells are MAXed per distinct fabric, not summed
    per server (P replicas share one window)."""
    with _trackers_mu:
        fns = list(_trackers.values())
    totals = {k: 0 for k, _ in _GAUGE_FIELDS}
    windows: dict[int, int] = {}
    for fn in fns:
        try:
            d = fn()
        except Exception:  # noqa: BLE001 — a dying server is not data
            continue
        for k, _g in _GAUGE_FIELDS:
            if k == "window_live_slots":
                continue
            totals[k] += int(d.get(k, 0))
        w = d.get("window_live_slots")
        if w is not None:
            windows[d.get("window_key", id(fn))] = int(w)
    totals["window_live_slots"] = sum(windows.values())
    for k, g in _GAUGE_FIELDS:
        g.set(totals[k])
    return totals
