"""pbservice — primary/backup replicated KV on top of the viewservice.

Capability parity with the reference Lab 2B (`pbservice/server.go`,
`pbservice/client.go`): the primary forwards every operation to the backup
before replying; reads also go through the backup (the backup's answer is the
trusted one, `pbservice/server.go:108-149`) — that is what defeats the
stale-primary partition scenario: a primary cut off from the viewservice
cannot get its ex-backup (now promoted) to co-sign, so it cannot serve stale
data (`pbservice/test_test.go:956-1150`).  A new backup is bootstrapped with a
full state transfer (`InitState`, server.go:274-296).

At-most-once uses the per-client monotonic filter (the reference's
OpID+10s-TTL cache, server.go:23,57-92, has timing races by construction);
filter state rides the state transfer so retries survive failover.
"""

from __future__ import annotations

import threading
import time

from tpu6824.services import viewservice
from tpu6824.services.common import FlakyNet, fresh_cid
from tpu6824.utils import crashsink
from tpu6824.utils.errors import (
    OK,
    ErrNoKey,
    ErrUninitServer,
    ErrWrongServer,
    RPCError,
)


class PBServer:
    RPC_METHODS = ["get", "put_append", "backup_get", "backup_put_append",
                   "init_state"]  # wire surface (rpc.Server)

    def __init__(self, me: str, vs: viewservice.ViewServer, net: FlakyNet,
                 directory: dict, tick_interval: float | None = None):
        self.me = me
        self.vck = viewservice.Clerk(me, vs)
        self.net = net
        self.directory = directory
        directory[me] = self
        self.mu = threading.RLock()
        self.view = viewservice.View(0, "", "")
        self.kv: dict[str, str] | None = None  # None = uninitialized backup
        self.dup: dict[int, tuple[int, object]] = {}
        self.dead = False
        if tick_interval is None:
            # vs may be a socket Proxy, where attribute access yields an RPC
            # stub rather than a number — fall back to the protocol constant.
            tick_interval = getattr(vs, "ping_interval", None)
            if not isinstance(tick_interval, (int, float)):
                tick_interval = viewservice.PING_INTERVAL
        self.tick_interval = tick_interval
        self._ticker = threading.Thread(
            target=crashsink.guarded(self._tick_loop, "pbservice-ticker"),
            daemon=True)
        self._ticker.start()

    # ------------------------------------------------------------- helpers

    def _backup_srv(self):
        b = self.view.backup
        return self.directory.get(b) if b else None

    def _apply(self, kind: str, key: str, value: str, cid: int, cseq: int):
        seen, reply = self.dup.get(cid, (-1, None))
        if cseq <= seen:
            return reply
        if kind == "get":
            reply = (OK, self.kv[key]) if key in self.kv else (ErrNoKey, "")
        elif kind == "put":
            self.kv[key] = value
            reply = (OK, "")
        elif kind == "append":
            self.kv[key] = self.kv.get(key, "") + value
            reply = (OK, "")
        self.dup[cid] = (cseq, reply)
        return reply

    # ------------------------------------------------------------- primary

    def get(self, key: str, cid: int, cseq: int):
        with self.mu:
            self._check()
            if self.view.primary != self.me or self.kv is None:
                return (ErrWrongServer, "")
            bk = self._backup_srv()
            if bk is not None:
                # Read through the backup; its answer is the trusted one
                # (pbservice/server.go:129-141).
                try:
                    # tpusan: ok(lock-blocking-call) — reference semantics:
                    # the primary SERIALIZES through mu while reading via
                    # the backup (pbservice/server.go:129-141); mu is this
                    # one server's, not the fabric hot path.
                    err, val = self.net.call(
                        bk, bk.backup_get, self.view.viewnum, key, cid, cseq
                    )
                except RPCError:
                    return (ErrWrongServer, "")
                if err == ErrUninitServer:
                    self._transfer_state_locked()
                    return (ErrWrongServer, "")  # client retries
                if err == ErrWrongServer:
                    return (ErrWrongServer, "")
                return (err, val)
            return self._apply("get", key, cid=cid, cseq=cseq, value="")

    def put_append(self, key: str, kind: str, value: str, cid: int, cseq: int):
        """pbservice/server.go:196-272: forward to backup, then apply."""
        with self.mu:
            self._check()
            if self.view.primary != self.me or self.kv is None:
                return (ErrWrongServer, "")
            seen, reply = self.dup.get(cid, (-1, None))
            if cseq <= seen:
                return reply
            bk = self._backup_srv()
            if bk is not None:
                try:
                    # tpusan: ok(lock-blocking-call) — same serialization
                    # contract as get(): forward-to-backup must complete
                    # before the primary applies (server.go:196-272).
                    err, _ = self.net.call(
                        bk, bk.backup_put_append,
                        self.view.viewnum, key, kind, value, cid, cseq,
                    )
                except RPCError:
                    return (ErrWrongServer, "")
                if err == ErrUninitServer:
                    self._transfer_state_locked()
                    return (ErrWrongServer, "")
                if err != OK:
                    return (ErrWrongServer, "")
            return self._apply(kind, key, value, cid, cseq)

    # ------------------------------------------------------------- backup

    def backup_get(self, viewnum: int, key: str, cid: int, cseq: int):
        with self.mu:
            self._check()
            if self.view.backup != self.me or viewnum < self.view.viewnum:
                return (ErrWrongServer, "")
            if self.kv is None:
                return (ErrUninitServer, "")
            return self._apply("get", key, "", cid, cseq)

    def backup_put_append(self, viewnum: int, key: str, kind: str, value: str,
                          cid: int, cseq: int):
        with self.mu:
            self._check()
            if self.view.backup != self.me or viewnum < self.view.viewnum:
                return (ErrWrongServer, "")
            if self.kv is None:
                return (ErrUninitServer, "")
            return self._apply(kind, key, value, cid, cseq)

    def init_state(self, viewnum: int, kv: dict, dup: dict):
        """pbservice/server.go:45-55: full-state bootstrap of a new backup."""
        with self.mu:
            self._check()
            if self.view.backup != self.me:
                return (ErrWrongServer, "")
            self.kv = dict(kv)
            self.dup = dict(dup)
            return (OK, "")

    def _transfer_state_locked(self):
        bk = self._backup_srv()
        if bk is None:
            return
        try:
            # tpusan: ok(lock-blocking-call) — whole-state handoff to a
            # fresh backup; racing a concurrent put would fork the copies
            # (the reference holds its lock across Transfer too).
            self.net.call(bk, bk.init_state, self.view.viewnum,
                          dict(self.kv), dict(self.dup))
        except RPCError:
            pass

    # ------------------------------------------------------------- liveness

    def _tick_loop(self):
        while not self.dead:
            time.sleep(self.tick_interval)
            self.tick()

    def tick(self):
        """pbservice/server.go:334-352: ping the viewservice; on becoming
        primary with a fresh backup, push state."""
        with self.mu:
            if self.dead:
                return
            old = self.view
            try:
                view = self.vck.ping(self.view.viewnum)
            except RPCError:
                return
            self.view = view
            if view.primary == self.me and self.kv is None:
                # First primary of the system starts empty.
                if view.viewnum == 1 or old.viewnum == 0:
                    self.kv = {}
            if (
                view.primary == self.me
                and view.backup
                and view.backup != old.backup
                and self.kv is not None
            ):
                self._transfer_state_locked()

    def _check(self):
        if self.dead:
            raise RPCError("dead")

    def kill(self):
        with self.mu:
            self.dead = True
            del self.directory[self.me]


class Clerk:
    """pbservice/client.go:67-115: cache the view; refresh from the
    viewservice on error; retry forever (at-most-once via cid/cseq)."""

    def __init__(self, vs: viewservice.ViewServer, directory: dict,
                 net: FlakyNet | None = None):
        self.vs = vs
        self.directory = directory
        self.net = net or FlakyNet()
        self.cid = fresh_cid()
        self.cseq = 0
        self.primary = ""
        self.mu = threading.Lock()

    def _next(self):
        with self.mu:
            self.cseq += 1
            return self.cseq

    def _refresh(self):
        try:
            self.primary = self.vs.get().primary
        except RPCError:
            pass

    def _loop(self, fn_name, *args, timeout=None):
        cseq = self._next()
        deadline = time.monotonic() + timeout if timeout else None
        while True:
            if not self.primary:
                self._refresh()
            srv = self.directory.get(self.primary)
            if srv is not None:
                try:
                    err, val = self.net.call(
                        srv, getattr(srv, fn_name), *args, self.cid, cseq
                    )
                    if err != ErrWrongServer:
                        return err, val
                except RPCError:
                    pass
            if deadline and time.monotonic() >= deadline:
                raise RPCError("clerk timeout")
            time.sleep(0.01)
            self._refresh()

    def get(self, key: str, timeout=None) -> str:
        err, val = self._loop("get", key, timeout=timeout)
        return val if err == OK else ""

    def put(self, key: str, value: str, timeout=None):
        self._loop("put_append", key, "put", value, timeout=timeout)

    def append(self, key: str, value: str, timeout=None):
        self._loop("put_append", key, "append", value, timeout=timeout)
