"""lockservice — primary/backup lock server (the at-most-once warm-up lab).

Capability parity with the reference (`lockservice/server.go`,
`lockservice/client.go`): Lock(name) returns whether the lock was acquired;
Unlock(name) releases it; the primary forwards every op to the backup so a
client can fail over; retried RPCs must not double-execute (the reference's
`DeafConn`/`dying` machinery, server.go:75-87,122-156, exists to test exactly
the reply-lost case).

The reference fork left `Unlock` as a stub on both sides
(`lockservice/server.go:51-56`, `client.go:88-93`); it is implemented for
real here.  At-most-once uses the per-client monotonic filter.

Fault knobs for tests: `die_after_next_deaf()` makes the server process one
more request, drop the reply, then die — the fail-just-before-reply scenario.
"""

from __future__ import annotations

import threading

from tpu6824.services.common import fresh_cid
from tpu6824.utils.errors import RPCError


class LockServer:
    RPC_METHODS = ["lock", "unlock"]  # wire surface (rpc.Server)

    def __init__(self, am_primary: bool, backup: "LockServer | None" = None):
        self.am_primary = am_primary
        self.backup = backup
        self.mu = threading.Lock()
        self.locks: dict[str, bool] = {}
        self.dup: dict[int, tuple[int, object]] = {}
        self.dead = False
        self.dying = False  # serve one more op deafly, then die

    def _apply(self, kind: str, name: str, cid: int, cseq: int) -> bool:
        seen, reply = self.dup.get(cid, (-1, None))
        if cseq <= seen:
            return reply
        held = self.locks.get(name, False)
        if kind == "lock":
            reply = not held
            # tpusan: ok(unbounded-host-state) — the lock table IS the
            # service's data: one row per distinct lock NAME (the
            # app's keyspace), not per op; unlock flips the row, it
            # does not leak
            self.locks[name] = True
        else:  # unlock
            reply = held
            self.locks[name] = False
        # tpusan: ok(unbounded-host-state) — reference-fidelity lab 2
        # surface: one dup row per CLIENT, and this service predates
        # the horizon machinery by design (kvpaxos/shardkv carry the
        # bounded-memory contract)
        self.dup[cid] = (cseq, reply)
        return reply

    def _serve(self, kind: str, name: str, cid: int, cseq: int) -> bool:
        with self.mu:
            if self.dead:
                raise RPCError("dead")
            dying = self.dying
            if self.am_primary and self.backup is not None:
                # Forward through the backup's PUBLIC wire surface so the
                # backup may be an in-process object or a socket Proxy alike.
                try:
                    getattr(self.backup, kind)(name, cid, cseq)
                except RPCError:
                    pass  # backup gone; keep serving
            out = self._apply(kind, name, cid, cseq)
            if dying:
                self.dead = True
                raise RPCError("reply lost (server died)")
            return out

    def lock(self, name: str, cid: int, cseq: int) -> bool:
        return self._serve("lock", name, cid, cseq)

    def unlock(self, name: str, cid: int, cseq: int) -> bool:
        return self._serve("unlock", name, cid, cseq)

    def die_after_next_deaf(self):
        """Process one more request, discard its reply, then die — the
        DeafConn + dying path (lockservice/server.go:75-87,122-156)."""
        with self.mu:
            self.dying = True

    def kill(self):
        with self.mu:
            self.dead = True


class Clerk:
    """lockservice/client.go:42-93: primary first, then backup; same (cid,
    cseq) on the retry so the op executes at most once."""

    def __init__(self, primary: LockServer, backup: LockServer):
        self.servers = (primary, backup)
        self.cid = fresh_cid()
        self.cseq = 0
        self.mu = threading.Lock()

    def _next(self):
        with self.mu:
            self.cseq += 1
            return self.cseq

    def _call_both(self, fn_name: str, name: str) -> bool:
        cseq = self._next()
        for srv in self.servers:
            try:
                return getattr(srv, fn_name)(name, self.cid, cseq)
            except RPCError:
                continue
        raise RPCError("both lock servers unreachable")

    def lock(self, name: str) -> bool:
        return self._call_both("lock", name)

    def unlock(self, name: str) -> bool:
        return self._call_both("unlock", name)


def make_pair() -> tuple[LockServer, LockServer]:
    backup = LockServer(am_primary=False)
    primary = LockServer(am_primary=True, backup=backup)
    return primary, backup
