"""kvpaxos — linearizable replicated KV store on the Paxos fabric.

Capability parity with the reference's Lab 3B service (`kvpaxos/server.go`,
`kvpaxos/client.go`): Get/Put/Append sequenced through the shared Paxos log;
every replica applies the log in order; duplicate client requests are filtered
so retries are at-most-once.

Differences from the reference, by design:
  - The reference's TTL-based OpID filter (`kvpaxos/server.go:49-62,187-198`)
    is replaced by the per-client monotonic-sequence filter the reference
    itself uses in shardkv (`shardkv/server.go:186-203`) — no timing races.
  - The reference's sync loop holds the server mutex and polls Status with
    10ms→1s backoff (`kvpaxos/server.go:69-113`); here the poll waits on the
    fabric clock, and gives up after `op_timeout` so a minority-partitioned
    server surfaces the same 'call failed' the reference's RPC timeout does.
"""

from __future__ import annotations

import threading
import time
from typing import NamedTuple

from tpu6824.core.fabric import PaxosFabric, WindowFullError
from tpu6824.core.peer import Fate, PaxosPeer
from tpu6824.services.common import FlakyNet, fresh_cid
from tpu6824.utils.errors import OK, ErrNoKey, RPCError


class Op(NamedTuple):
    """One log entry (the gob-encoded Op of kvpaxos/server.go:25-33)."""

    kind: str  # 'get' | 'put' | 'append'
    key: str
    value: str
    cid: int
    cseq: int


class KVPaxosServer:
    RPC_METHODS = ["get", "put_append"]  # wire surface (rpc.Server)

    def __init__(self, fabric: PaxosFabric | None, g: int, me: int,
                 op_timeout: float = 8.0, px=None):
        """`px` overrides the consensus backend: anything with the PaxosPeer
        contract (start/status/done/min/max/kill) — the batched TPU fabric
        peer by default, or a decentralized `HostOpPeer` (see
        `make_host_cluster`) for per-message-RPC deployments."""
        if fabric is None and px is None:
            raise ValueError("KVPaxosServer needs a fabric or an explicit px")
        self.px = px if px is not None else PaxosPeer(fabric, g, me)
        self.me = me
        self.mu = threading.RLock()
        self.kv: dict[str, str] = {}
        self.applied = -1  # highest paxos seq applied to kv
        self.dup: dict[int, tuple[int, object]] = {}  # cid -> (max cseq, reply)
        self.op_timeout = op_timeout
        self.dead = False
        # Background catch-up: apply already-decided instances and advance
        # Done() even when no client talks to this replica.  The reference
        # only applies inside RPC handlers (kvpaxos/server.go:69-113), which
        # lets passive replicas pin the log forever; shardkv's tick()/catchUp
        # (shardkv/server.go:162-184,488-493) is the pattern generalized here.
        # Without it the fixed instance window could never recycle.
        self._ticker = threading.Thread(target=self._tick_loop, daemon=True)
        self._ticker.start()

    def _tick_loop(self):
        while not self.dead:
            time.sleep(0.02)
            try:
                with self.mu:
                    if self.dead:
                        return
                    self._drain_decided()
            except RPCError:
                # Transient backend outage (e.g. a fabricd restarting from
                # a checkpoint behind a remote_fabric handle): keep the
                # drain ticker alive and retry — shardkv's ticker has the
                # same tolerance.
                continue

    def _drain_decided(self):
        """Apply every already-decided instance in order; never proposes."""
        while True:
            fate, v = self.px.status(self.applied + 1)
            if fate == Fate.DECIDED:
                self._apply(v)
                self.applied += 1
                self.px.done(self.applied)
            elif fate == Fate.FORGOTTEN:
                self.applied += 1
            else:
                return

    # ------------------------------------------------------------ RSM core

    def _apply(self, op: Op):
        """Apply one decided op (doGet/doPutAppend, kvpaxos/server.go:115-162)
        with at-most-once duplicate suppression."""
        seen, reply = self.dup.get(op.cid, (-1, None))
        if op.cseq <= seen:
            return reply
        if op.kind == "get":
            reply = (OK, self.kv[op.key]) if op.key in self.kv else (ErrNoKey, "")
        elif op.kind == "put":
            self.kv[op.key] = op.value
            reply = (OK, "")
        elif op.kind == "append":
            self.kv[op.key] = self.kv.get(op.key, "") + op.value
            reply = (OK, "")
        else:
            reply = (OK, "")
        self.dup[op.cid] = (op.cseq, reply)
        return reply

    def _sync(self, want: Op):
        """Drive `want` into the log and apply everything up to it
        (kvpaxos/server.go:69-113).  Returns the op's reply, or raises
        RPCError on timeout (the caller's RPC would have timed out)."""
        deadline = time.monotonic() + self.op_timeout
        seq = self.applied + 1
        started_here = False
        while True:
            if self.dead:
                raise RPCError("server killed")
            fate, v = self.px.status(seq)
            if fate == Fate.DECIDED:
                reply = self._apply(v)
                self.applied = seq
                self.px.done(seq)
                if isinstance(v, Op) and v.cid == want.cid and v.cseq == want.cseq:
                    return reply
                seq += 1
                started_here = False
                continue
            if fate == Fate.FORGOTTEN:
                # Another replica applied + GC'd past us; our dup filter will
                # be refreshed by the ops we *can* still see.
                seq += 1
                continue
            if not started_here:
                try:
                    self.px.start(seq, want)
                    started_here = True
                except WindowFullError:
                    pass  # transient: wait for GC to recycle a slot
            if time.monotonic() >= deadline:
                raise RPCError("op timeout (no majority?)")
            time.sleep(0.002)

    # ------------------------------------------------------------ RPC surface

    def get(self, key: str, cid: int, cseq: int):
        with self.mu:
            if self.dead:
                raise RPCError("dead")
            seen, reply = self.dup.get(cid, (-1, None))
            if cseq <= seen:
                return reply
            return self._sync(Op("get", key, "", cid, cseq))

    def put_append(self, kind: str, key: str, value: str, cid: int, cseq: int):
        with self.mu:
            if self.dead:
                raise RPCError("dead")
            seen, reply = self.dup.get(cid, (-1, None))
            if cseq <= seen:
                return reply
            return self._sync(Op(kind, key, value, cid, cseq))

    def kill(self):
        with self.mu:
            self.dead = True
        self.px.kill()


class Clerk:
    """kvpaxos/client.go:69-104 — try every server forever, at-most-once via
    (cid, cseq)."""

    def __init__(self, servers: list[KVPaxosServer], net: FlakyNet | None = None):
        self.servers = servers
        self.net = net or FlakyNet()
        self.cid = fresh_cid()
        self.cseq = 0
        self.mu = threading.Lock()

    def _next(self) -> int:
        with self.mu:
            self.cseq += 1
            return self.cseq

    def _loop(self, fn_name, *args, timeout=None):
        cseq = self._next()
        deadline = time.monotonic() + timeout if timeout else None
        i = 0
        while True:
            srv = self.servers[i % len(self.servers)]
            i += 1
            try:
                fn = getattr(srv, fn_name)
                err, val = self.net.call(srv, fn, *args, self.cid, cseq)
                return err, val
            except RPCError:
                pass
            if deadline and time.monotonic() >= deadline:
                raise RPCError("clerk timeout")
            time.sleep(0.01)

    def get(self, key: str, timeout=None) -> str:
        err, val = self._loop("get", key, timeout=timeout)
        return val if err == OK else ""

    def put(self, key: str, value: str, timeout=None):
        self._loop("put_append", "put", key, value, timeout=timeout)

    def append(self, key: str, value: str, timeout=None):
        self._loop("put_append", "append", key, value, timeout=timeout)


def make_cluster(nservers=3, ninstances=64, fabric=None, g=0, **kw):
    """Boot a kvpaxos replica group on (a group of) a fabric."""
    if fabric is None:
        fabric = PaxosFabric(ngroups=1, npeers=nservers, ninstances=ninstances,
                             auto_step=True)
    servers = [KVPaxosServer(fabric, g, p, **kw) for p in range(nservers)]
    return fabric, servers


# ---------------------------------------------------------------------------
# Decentralized backend: the same RSM over per-message gob RPC
# (core/hostpeer.py) — the reference's own runtime model, so this service
# can be deployed one-replica-per-process with no shared fabric.
# (shim.gob is stdlib-only, so importing it here costs nothing next to the
# jax-backed fabric import above.)

from tpu6824.services.host_backend import StructOpPeer
from tpu6824.shim.gob import INT, STRING, Struct

KVOP_WIRE = Struct("KVOp", [
    ("Kind", STRING), ("Key", STRING), ("Value", STRING),
    ("CID", INT), ("Seq", INT),
])
KVOP_NAME = "tpu6824.KVOp"


def HostOpPeer(host_peer) -> StructOpPeer:
    """kvpaxos ops over the decentralized wire backend (the reference's
    `gob.Register(Op{})`, kvpaxos/server.go)."""
    return StructOpPeer(
        host_peer, KVOP_NAME, KVOP_WIRE,
        to_wire=lambda op: {"Kind": op.kind, "Key": op.key,
                            "Value": op.value, "CID": op.cid,
                            "Seq": op.cseq},
        from_wire=lambda d: Op(d["Kind"], d["Key"], d["Value"], d["CID"],
                               d["Seq"]),
    )


def make_host_replica(sockdir: str, nservers: int, me: int,
                      seed: int | None = None,
                      persist_dir: str | None = None,
                      peer_kw: dict | None = None, **kw):
    """One decentralized replica — peer endpoint + RSM server — suitable
    for one-replica-per-OS-process deployment (the reference's model:
    every server process embeds its own Paxos peer,
    kvpaxos/server.go StartServer).  With `persist_dir`, the peer survives
    crash+restart.  `peer_kw` goes to HostPaxosPeer (pooled=,
    parallel_fanout=, ...); other keywords go to the server.  Returns
    (host_peer, server)."""
    from tpu6824.services.host_backend import make_host_replica as _mk

    return _mk(sockdir, "px", KVOP_NAME, KVOP_WIRE,
               lambda p: KVPaxosServer(None, 0, p.me, px=HostOpPeer(p), **kw),
               nservers, me, seed=seed, persist_dir=persist_dir,
               **(peer_kw or {}))


def make_host_cluster(sockdir: str, nservers: int = 3, seed: int | None = None,
                      pooled: bool = False, peer_kw: dict | None = None,
                      **kw):
    """kvpaxos on the decentralized wire path: one gob Paxos endpoint per
    replica, consensus by per-message Prepare/Accept/Decided RPC — the
    reference's deployment model end to end.  pooled=True runs the peers
    on long-lived net/rpc client connections (the optimized profile);
    `peer_kw` passes any further HostPaxosPeer options."""
    from tpu6824.services.host_backend import make_host_cluster as _mk

    pk = dict(peer_kw or {})
    if pooled:
        pk["pooled"] = True
    return _mk(sockdir, "px", KVOP_NAME, KVOP_WIRE,
               lambda p: KVPaxosServer(None, 0, p.me, px=HostOpPeer(p), **kw),
               nservers, seed=seed, **pk)
