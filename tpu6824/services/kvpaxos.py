"""kvpaxos — linearizable replicated KV store on the Paxos fabric.

Capability parity with the reference's Lab 3B service (`kvpaxos/server.go`,
`kvpaxos/client.go`): Get/Put/Append sequenced through the shared Paxos log;
every replica applies the log in order; duplicate client requests are filtered
so retries are at-most-once.

Differences from the reference, by design:
  - The reference's TTL-based OpID filter (`kvpaxos/server.go:49-62,187-198`)
    is replaced by the per-client monotonic-sequence filter the reference
    itself uses in shardkv (`shardkv/server.go:186-203`) — no timing races.
  - The reference's sync loop holds the server mutex and polls Status with
    10ms→1s backoff (`kvpaxos/server.go:69-113`); here the poll waits on the
    fabric clock, and gives up after `op_timeout` so a minority-partitioned
    server surfaces the same 'call failed' the reference's RPC timeout does.
"""

from __future__ import annotations

import os
import threading
import time
from typing import NamedTuple

from tpu6824.core.devapply_kernel import K_APPEND, K_GET, K_PUT
from tpu6824.core.fabric import PaxosFabric, WindowFullError
from tpu6824.core.peer import Fate, PaxosPeer
from tpu6824.obs import blackbox as _blackbox
from tpu6824.obs import metrics as _metrics
from tpu6824.obs import opscope as _opscope
from tpu6824.obs import tracing as _tracing
from tpu6824.rpc import wire as _wire
from tpu6824.services import horizon as _horizon
from tpu6824.services.devapply import DevApplyEngine
from tpu6824.services.common import (
    Backoff,
    ColumnarDups,
    DecidedTap,
    FlakyNet,
    fresh_cid,
    pull_from_peers,
)
from tpu6824.utils.errors import OK, ErrNoKey, RPCError
from tpu6824.utils.profiling import PhaseProfiler
from tpu6824.utils import crashsink
from tpu6824.utils.locks import new_rlock

# tpuscope metrics (module scope per the metric-unregistered rule).
_M_RETRIES = _metrics.counter("clerk.retries")
_M_OP_LAT = _metrics.histogram("clerk.op_latency_us")
_M_APPLIED = _metrics.counter("kvpaxos.applied")


class Op(NamedTuple):
    """One log entry (the gob-encoded Op of kvpaxos/server.go:25-33).

    `tc` is tpuscope trace metadata — the proposer's (trace_id, span_id)
    2-tuple, stamped at submit when tracing is enabled (None, allocation-
    free, otherwise).  It rides the proposed value through consensus so
    the decided-feed/apply side can emit fabric-dispatch/apply spans
    parented into the clerk's causal chain; it is NOT identity — dup
    filtering and lost-proposal matching key on (cid, cseq) only."""

    kind: str  # 'get' | 'put' | 'append'
    key: str
    value: str
    cid: int
    cseq: int
    tc: tuple | None = None


_DEAD = object()  # future sentinel: server killed while ops waited


class _Fut:
    """One submitted op's completion slot (value = the RSM reply).
    `t_set` records the resolve instant so latency accounting reads the
    real completion time, not the time a sweeping waiter got around to
    noticing it (the pipelined clerk parks up to 0.2s between sweeps).
    `tctx` is the tpuscope context of the apply-side span (set BEFORE
    the event fires, so the waiter can parent its reply span to the
    apply that resolved it); None on untraced ops.
    `sink`, when set, is invoked with the future right after `set()` —
    the clerk frontend's completion hook, so the driver's one-sweep
    retire notify delivers straight into the frontend's event loop with
    no per-op waiter thread parked anywhere.  A sink must be O(1) and
    non-blocking: it runs on the driver thread, under the server mutex."""

    __slots__ = ("ev", "value", "t_set", "tctx", "sink")

    def __init__(self):
        self.ev = threading.Event()
        self.value = None
        self.t_set = None
        self.tctx = None
        self.sink = None

    def set(self, v):
        self.value = v
        self.t_set = time.monotonic()
        self.ev.set()
        s = self.sink
        if s is not None:
            s(self)

    def wait(self, timeout):
        return self.ev.wait(timeout)


def _push_cnotif(cnotif) -> None:
    """Deliver a drain's resolved columnar waiters to their owning
    frontends' reply rings.  Reply tags are ring-LOCAL: a tag pushed
    into another frontend's ring would answer an unrelated op's slot,
    so a fleet drain groups by owner.  The one-owner drain (the only
    shape a single-frontend deployment ever sees, and the common fleet
    case) stays a single push with the original lists — no copies."""
    ctags, creps, ctctx, cowns = cnotif
    own0 = cowns[0]
    for o in cowns:
        if o is not own0:
            break
    else:
        if own0 is not None:
            own0.push(ctags, creps, ctctx)
        return
    groups: dict[int, list] = {}
    for i, o in enumerate(cowns):
        if o is None:
            continue  # owner detached mid-flight: nobody is listening
        g = groups.get(id(o))
        if g is None:
            groups[id(o)] = g = [o, [], [], []]
        g[1].append(ctags[i])
        g[2].append(creps[i])
        g[3].append(ctctx[i])
    for o, tags, reps, tctxs in groups.values():
        o.push(tags, reps, tctxs)


class KVPaxosServer:
    RPC_METHODS = ["get", "put_append", "snapshot_fetch"]  # wire surface

    def __init__(self, fabric: PaxosFabric | None, g: int, me: int,
                 op_timeout: float = 8.0, px=None, peers=None,
                 snapshot_every: int | None = None,
                 persist_dir: str | None = None,
                 dup_retire_ops: int | None = None,
                 devapply: bool | None = None):
        """`px` overrides the consensus backend: anything with the PaxosPeer
        contract (start/status/done/min/max/kill) — the batched TPU fabric
        peer by default, or a decentralized `HostOpPeer` (see
        `make_host_cluster`) for per-message-RPC deployments.  Batched
        extensions (start_many/status_many/wait_progress) are used when the
        backend has them, falling back to the scalar contract otherwise.

        Concurrency model (GROUP COMMIT — VERDICT r4 weak #4: the old
        per-op `_sync` held the server mutex through consensus, so one op
        progressed per decided round per server).  Client RPCs enqueue the
        op and wait on a future; a single driver thread batches everything
        queued since its last pass into one consecutive block of seqs (one
        start_many), drains the decided prefix in bulk (one status_many)
        and resolves futures.  The reference's hot loop
        (`kvpaxos/server.go:69-113`), done batched: N concurrent clients
        on one server now cost one proposal round, not N serialized ones.
        """
        if fabric is None and px is None:
            raise ValueError("KVPaxosServer needs a fabric or an explicit px")
        self.px = px if px is not None else PaxosPeer(fabric, g, me)
        self.me = me
        # Named + budgeted for the lockwatch sanitizer: the driver's
        # batched apply passes run under mu; a per-op regression here is
        # the service-layer twin of the fabric-lock budget.
        self.mu = new_rlock("kvpaxos.mu")
        self.kv: dict[str, str] = {}
        self.applied = -1  # highest paxos seq applied to kv
        # devapply (ISSUE 16): the hot get/put/append state machine on
        # the device as a per-drain columnar step; `self.kv` demoted to
        # a lazily-synced mirror (cadence / snapshot cut / kv_view).
        # Default OFF: the host dict path stays byte-for-byte, and
        # `set_devapply` can flip a live server for bench A/B.
        if devapply is None:
            devapply = os.environ.get("TPU6824_DEVAPPLY", "") not in ("", "0")
        self._dev: DevApplyEngine | None = \
            DevApplyEngine() if devapply else None
        # At-most-once filter, columnar: cid → (max cseq, reply) with the
        # cseq column in a C array and reply refs in a parallel list —
        # batch-updated once per drain (see _apply_batch_locked).
        self.dup = ColumnarDups()
        self.op_timeout = op_timeout
        self.dead = False
        # TEST-ONLY linearizability fault hook: True disables at-most-once
        # duplicate suppression everywhere (apply, submit dedup, proposal
        # collection), so a clerk retry after a dropped reply re-applies —
        # the classic lost-dup-table bug.  Exists so the Wing–Gong checker
        # (harness/linearize.py) can prove it catches a real violation;
        # never set outside tests.
        self._test_disable_dup = False
        # TEST-ONLY opscope seam: a per-drain stall injected between the
        # decide-feed delivery and the batch apply, so the attribution
        # tests can seed a KNOWN slow stage and assert the waterfall,
        # the watchdog bundle, and the tail exemplars all name `apply`.
        self._test_apply_delay = 0.0
        self._waiters: dict[tuple[int, int], _Fut] = {}  # (cid, cseq) -> fut
        # tpuscope: (cid, cseq) -> proposal monotonic_ns for traced ops
        # (empty when tracing is off) — lets the apply side emit the
        # fabric-dispatch span with the real propose→decide window.
        self._trace_prop: dict[tuple[int, int], int] = {}
        self._subq: list[Op] = []        # submitted, not yet proposed
        self._inflight: dict[int, Op] = {}  # seq -> my undecided proposal
        self._next_seq = 0               # next seq I would propose at
        # Columnar waiters (ISSUE 11, the native-ingest seam): ops arrive
        # as int columns, not Op objects — the waiter state is two int→int
        # dicts (cid → awaited cseq, cid → reply-ring tag) instead of a
        # per-op future, and materialization into log entries is deferred
        # to the driver's proposal pass (`_collect_proposals_locked`).
        # A FLEET of frontends may front this server (ISSUE 18): each
        # parked columnar waiter records its owning sink, because the
        # reply tag indexes that frontend's reply ring — pushing it into
        # another frontend's ring answers some unrelated op's slot.  A
        # clerk retry that migrated frontends re-parks the same
        # (cid, cseq) with the new owner (last-writer-wins is the
        # routing truth: the clerk is now listening over there).
        # `_csinks` keeps every sink ever attached so kill() can fan the
        # server-dead wake out to the whole fleet; `columnar_drained` is
        # the ticket fence the engines' deferred intern-decrefs wait on
        # (a single monotonic counter — conservative and correct with
        # interleaved blocks from several frontends).
        self._csinks: dict[int, object] = {}   # id(sink) -> sink
        self._cowner: dict[int, object] = {}   # cid -> owning sink
        self._ccseq: dict[int, int] = {}
        self._ctag: dict[int, int] = {}
        self._cblocks: list = []         # (ticket, block, accepted idxs)
        self._cblocks_submitted = 0
        self.columnar_drained = 0
        self._wake = threading.Event()
        # Done() variant for the driver's per-drain watermark: the
        # lock-free deferred form when the backend has one (the fabric
        # folds it at its next dispatch staging), else the locked call.
        # A hot driver calling the locked form convoys behind the
        # clock's retire fold at clerk-frontend load.
        self._done_fn = getattr(self.px, "done_deferred", None) \
            or self.px.done
        # Decided-delta feed (fabric backends): the fabric computes each
        # retire's newly-decided (seq, value) delta ONCE per group and
        # fans it out, waking this driver — so the P replicas stop
        # re-scanning the decided mirror via drain_decided (3× duplicate
        # vectorized scan per group per tick) and stop polling
        # wait_progress.  Other backends keep the drain/status paths.
        self._prof = getattr(self.px, "profiler", None) or PhaseProfiler()
        sub_fn = getattr(self.px, "subscribe_decided", None)
        sub = sub_fn(wake=self._wake.set) if sub_fn is not None else None
        self._tap = DecidedTap(sub) if sub is not None else None
        # horizon (ISSUE 14): service snapshots + Done()-driven
        # compaction + snapshot-install catch-up.  `peers` (sibling
        # servers/proxies; make_cluster wires it) is what makes a
        # revived replica behind the GC horizon installable instead of
        # skip-forwarded; `snapshot_every`/`persist_dir` configure the
        # Snapshotter (env defaults; 0 disables and keeps the legacy
        # fast-forward semantics byte-for-byte).
        self.peers = peers
        self.g = g
        # Crash forensics (ISSUE 20): each drain pass stamps its applied
        # high-water into the blackbox heartbeat table — one GIL-atomic
        # dict store per DRAIN with a key precomputed here, so a
        # postmortem over a SIGKILLed process names the last decided seq
        # this replica applied.
        self._bb_key = f"kvpaxos.applied.g{g}.s{me}"
        # meshfab shard binding: which mesh shard owns this group's
        # device columns (0 off-mesh / non-fabric backends).  Read at
        # every drain fold for the opscope shard dimension — bound once
        # here so the hot path never touches the fabric's placement map.
        fab = getattr(self.px, "fabric", None)
        self.shard = (fab.shard_of(g)
                      if fab is not None and hasattr(fab, "shard_of") else 0)
        self.dup_retire_ops = (_horizon.DUP_RETIRE_OPS
                               if dup_retire_ops is None
                               else int(dup_retire_ops))
        self.horizon = _horizon.Snapshotter(every=snapshot_every,
                                            persist_dir=persist_dir)
        self._behind_min = 0  # FORGOTTEN floor awaiting snapshot-install
        self._cmp_cid = f"cmp-{g}-{me}"
        self._cmp_cseq = 0
        if self.horizon.enabled():
            _horizon.register_tracker(self, self._horizon_rows)
            if persist_dir:
                loaded = _horizon.load_newest(persist_dir)
                if loaded is not None and loaded[0] > self.applied:
                    self._adopt_blob_locked(loaded[0], loaded[1])
                    self._done_fn(self.applied)
        # The driver doubles as the background catch-up ticker: it applies
        # already-decided instances and advances Done() even when no client
        # talks to this replica.  The reference only applies inside RPC
        # handlers (kvpaxos/server.go:69-113), which lets passive replicas
        # pin the log forever; shardkv's tick()/catchUp
        # (shardkv/server.go:162-184,488-493) is the pattern generalized
        # here.  Without it the fixed instance window could never recycle.
        self._driver = threading.Thread(
            target=crashsink.guarded(self._drive_loop, "kvpaxos-driver"),
            daemon=True)
        self._driver.start()

    # ------------------------------------------------------------ RSM core

    def _trace_apply(self, v: Op):
        """tpuscope: the apply side of a traced op — emit the
        `fabric.dispatch` span (propose→decide window, parented to the
        proposer's service-submit span carried in `v.tc`) and the
        `service.apply` span; returns the apply-side TraceContext the
        reply span chains off.  Only ever called for ops whose value
        carries trace metadata (tracing was on at submit), and only on
        the replica resolving a waiter — passive replicas applying the
        same decided op emit nothing."""
        now = time.monotonic_ns()
        t_prop = self._trace_prop.pop((v.cid, v.cseq), now)
        tid, submit_sid = v.tc
        did = _tracing.complete("fabric.dispatch", tid, submit_sid,
                                t_prop, now, comp="fabric", key=v.key)
        aid = _tracing.complete("service.apply", tid, did, now, now,
                                comp="kvpaxos", me=self.me, key=v.key,
                                cid=v.cid, cseq=v.cseq)
        return _tracing.TraceContext(tid, aid)

    def _trace_resolve(self, v: Op, fut: _Fut) -> None:
        """Park the apply-side trace context on the future so the
        waiter's reply span chains off the apply that resolved it."""
        fut.tctx = self._trace_apply(v)

    def _apply(self, op: Op):
        """Apply one decided op (doGet/doPutAppend, kvpaxos/server.go:115-162)
        with at-most-once duplicate suppression; resolves any waiter parked
        on this (cid, cseq)."""
        seen, reply = self.dup.get(op.cid, (-1, None))
        if op.cseq > seen or self._test_disable_dup:
            dev = self._dev
            if dev is not None and op.kind in ("get", "put", "append"):
                # Device path, batch of one (feedless backends drain per
                # op); non-hot kinds fall through to the host branches.
                reply = dev.apply_one(op.kind, op.key, op.value,
                                      self.applied + 1)
            elif op.kind == "get":
                # tpusan: ok(host-walk-in-decided-path) — the host
                # FALLBACK engine (devapply off, the bench A/B control
                # arm): these branches only run when self._dev is None
                # and must stay byte-for-byte the pre-devapply
                # semantics.
                reply = ((OK, self.kv[op.key]) if op.key in self.kv
                         else (ErrNoKey, ""))
            elif op.kind == "put":
                self.kv[op.key] = op.value
                reply = (OK, "")
            elif op.kind == "append":
                self.kv[op.key] = self.kv.get(op.key, "") + op.value
                reply = (OK, "")
            elif op.kind == "compact":
                self._compact_locked(self.applied + 1)
                reply = (OK, "")
            else:
                reply = (OK, "")
            self.dup.put(op.cid, op.cseq, reply, self.applied + 1)
        fut = self._waiters.pop((op.cid, op.cseq), None)
        if fut is not None:
            if op.tc is not None:
                self._trace_resolve(op, fut)
            fut.set(reply)
        elif self._ccseq.get(op.cid) == op.cseq:
            # Columnar waiter on the scalar-drain path (feedless
            # backends): resolve straight into the OWNING frontend's
            # native reply ring (the tag is ring-local).
            del self._ccseq[op.cid]
            tag = self._ctag.pop(op.cid)
            owner = self._cowner.pop(op.cid, None)
            tctx = self._trace_apply(op) if op.tc is not None else None
            if owner is not None:
                owner.push([tag], [reply], [tctx])
        return reply

    def _pop_lost_inflight_locked(self, v):
        """Post-apply bookkeeping at self.applied: if my proposal for this
        slot lost to `v`, re-queue it (its waiter is still parked)."""
        mine = self._inflight.pop(self.applied, None)
        if (mine is not None
                and (not isinstance(v, Op)
                     or (mine.cid, mine.cseq) != (v.cid, v.cseq))
                and ((mine.cid, mine.cseq) in self._waiters
                     or self._ccseq.get(mine.cid) == mine.cseq)):
            self._subq.append(mine)

    def _apply_batch_locked(self, vals, cnotif=None,
                            scope_cids=None) -> list:
        """Apply one contiguous decided run as a tight batch — the batched
        doGet/doPutAppend (kvpaxos/server.go:115-162) with the dict
        lookups hoisted and every per-op branch inline.  Futures are
        COLLECTED, not resolved: the caller sets them in one notify sweep
        after the batch, so waiter wakeups never interleave with apply
        work.  Dup-filter writes are likewise collected in `pend` (which
        doubles as the intra-batch read-your-writes overlay) and folded
        into the columnar store in ONE `apply_batch` pass per drain.
        Columnar waiters (native ingest) collect into `cnotif` — four
        parallel lists (tags, replies, trace ctxs, owning sinks; int/ref
        appends only, no per-op tuples) the caller pushes into the
        owning frontends' reply rings once per drain.  Returns
        [(fut, reply), ...]."""
        dup = self.dup
        kv = self.kv
        kv_get = kv.get
        dup_seen = dup.seen
        waiters_pop = self._waiters.pop
        ccseq = self._ccseq
        ccseq_get = ccseq.get
        ctag_pop = self._ctag.pop
        cowner_pop = self._cowner.pop
        if cnotif is not None:
            ctags, creps, ctctx, cowns = cnotif
        nodup = self._test_disable_dup
        notif = []
        pend: dict = {}  # cid -> (cseq, reply): this batch's dup writes
        pend_get = pend.get
        for v in vals:
            self.applied += 1
            if isinstance(v, Op):
                ent = pend_get(v.cid)
                seen = ent[0] if ent is not None else dup_seen(v.cid)
                if v.cseq > seen or nodup:
                    kind = v.kind
                    if kind == "get":
                        # tpusan: ok(host-walk-in-decided-path) — the
                        # host FALLBACK batch engine:
                        # `_drain_feed_locked` dispatches here only
                        # when self._dev is None; the devapply twin is
                        # `_apply_batch_dev_locked`.
                        reply = ((OK, kv[v.key]) if v.key in kv
                                 else (ErrNoKey, ""))
                    elif kind == "put":
                        kv[v.key] = v.value
                        reply = (OK, "")
                    elif kind == "append":
                        kv[v.key] = kv_get(v.key, "") + v.value
                        reply = (OK, "")
                    elif kind == "compact":
                        # Fold the batch's pending dup writes FIRST so
                        # the retirement scan sees exactly the table
                        # every op below this seq produced — batch
                        # boundaries differ per replica, the compact's
                        # log position does not (determinism).
                        if pend:
                            dup.apply_batch(pend)
                            pend.clear()
                        self._compact_locked(self.applied)
                        reply = (OK, "")
                    else:
                        reply = (OK, "")
                    pend[v.cid] = (v.cseq, reply, self.applied)
                else:
                    reply = ent[1] if ent is not None else dup.reply(v.cid)
                fut = waiters_pop((v.cid, v.cseq), None)
                if fut is not None:
                    if v.tc is not None:
                        self._trace_resolve(v, fut)
                    notif.append((fut, reply))
                    if scope_cids is not None:
                        scope_cids.append(v.cid)
                elif cnotif is not None and ccseq_get(v.cid) == v.cseq:
                    del ccseq[v.cid]
                    ctags.append(ctag_pop(v.cid))
                    creps.append(reply)
                    ctctx.append(self._trace_apply(v)
                                 if v.tc is not None else None)
                    cowns.append(cowner_pop(v.cid, None))
                    if scope_cids is not None:
                        scope_cids.append(v.cid)
            self._pop_lost_inflight_locked(v)
        if pend:
            dup.apply_batch(pend)
        return notif

    def _apply_batch_dev_locked(self, vals, cnotif=None,
                                scope_cids=None) -> list:
        """`_apply_batch_locked`, devapply edition: the run's hot ops
        build int columns (one intern probe per op — no dict walk, no
        string concat) and ONE jitted device step per drain applies them
        all (`DevApplyEngine.batch_commit`).  Only gets defer: their
        reply slot carries the op's drain-local index `j` until the
        commit's readback resolves node→value, then one sweep rewrites
        the sentinels in notif/cnotif/pend — put/append replies are
        `(OK, "")` by construction and never wait.  A mid-run `compact`
        forces an early commit (flush) so the dup-retire scan runs at
        its exact log position, identical on every replica."""
        dev = self._dev
        dup = self.dup
        dup_seen = dup.seen
        waiters_pop = self._waiters.pop
        ccseq = self._ccseq
        ccseq_get = ccseq.get
        ctag_pop = self._ctag.pop
        cowner_pop = self._cowner.pop
        if cnotif is not None:
            ctags, creps, ctctx, cowns = cnotif
        nodup = self._test_disable_dup
        notif = []
        pend: dict = {}  # cid -> (cseq, reply-or-sentinel, applied)
        pend_get = pend.get
        batch_op = dev.batch_op
        dev.batch_reset(len(vals))
        dres: dict = {}  # get sentinel j -> resolved reply tuple

        def flush():
            for j, node in dev.batch_commit(self.applied):
                dres[j] = dev.get_reply(node)

        def fix_pend():
            for cid, ent in pend.items():
                if type(ent[1]) is int:
                    pend[cid] = (ent[0], dres[ent[1]], ent[2])

        for v in vals:
            self.applied += 1
            if isinstance(v, Op):
                ent = pend_get(v.cid)
                seen = ent[0] if ent is not None else dup_seen(v.cid)
                if v.cseq > seen or nodup:
                    kind = v.kind
                    if kind == "get":
                        reply = batch_op(K_GET, v.key, "")
                    elif kind == "put":
                        batch_op(K_PUT, v.key, v.value)
                        reply = (OK, "")
                    elif kind == "append":
                        batch_op(K_APPEND, v.key, v.value)
                        reply = (OK, "")
                    elif kind == "compact":
                        # Commit the columns built so far and fold the
                        # batch's dup writes FIRST (host path contract:
                        # the retirement scan's view is a pure function
                        # of log position).  `j` stays monotone across
                        # the early commit, so later sentinels don't
                        # collide.
                        flush()
                        if pend:
                            fix_pend()
                            dup.apply_batch(pend)
                            pend.clear()
                        self._compact_locked(self.applied)
                        reply = (OK, "")
                    else:
                        reply = (OK, "")
                    pend[v.cid] = (v.cseq, reply, self.applied)
                else:
                    reply = ent[1] if ent is not None else dup.reply(v.cid)
                fut = waiters_pop((v.cid, v.cseq), None)
                if fut is not None:
                    if v.tc is not None:
                        self._trace_resolve(v, fut)
                    notif.append((fut, reply))
                    if scope_cids is not None:
                        scope_cids.append(v.cid)
                elif cnotif is not None and ccseq_get(v.cid) == v.cseq:
                    del ccseq[v.cid]
                    ctags.append(ctag_pop(v.cid))
                    creps.append(reply)
                    ctctx.append(self._trace_apply(v)
                                 if v.tc is not None else None)
                    cowns.append(cowner_pop(v.cid, None))
                    if scope_cids is not None:
                        scope_cids.append(v.cid)
            self._pop_lost_inflight_locked(v)
        flush()  # also advances dev.last_applied to self.applied
        if pend:
            fix_pend()
            dup.apply_batch(pend)
        if dres:
            notif = [(f, dres[r] if type(r) is int else r)
                     for f, r in notif]
            if cnotif is not None:
                # Earlier runs in this drain already rewrote theirs —
                # any int left in the shared lists is from this run.
                for i, r in enumerate(creps):
                    if type(r) is int:
                        creps[i] = dres[r]
        return notif

    def _drain_feed_locked(self):
        """Feed-based drain: pop the tap's contiguous decided run, apply
        it as one batch, resolve the batch's futures in one notify sweep,
        Done() once — no fabric-mirror scan, no per-op lock round-trips.

        FORGOTTEN handling: `DecidedTap.should_probe_min` gates the Min()
        probe (once at boot, then only for a gap that has aged several
        passes — see its docstring for why transient gaps must not
        probe); on a forgotten span we fast-forward, dropping the skipped
        seqs' in-flight proposals."""
        tap = self._tap
        prof = self._prof
        base0 = self.applied + 1
        # Hoisted once per drain (toggles happen under mu, never mid-
        # drain): the devapply columnar step or the host dict batch.
        apply_batch = (self._apply_batch_dev_locked if self._dev is not None
                       else self._apply_batch_locked)
        notif = []
        cnotif = ([], [], [], []) if self._csinks else None
        # opscope (ISSUE 15): per-drain stage stamps — decide-feed
        # delivery, batch apply done, notify/reply push — plus the
        # resolved ops' cids, folded ONCE per drain into the per-stage
        # histograms (numpy diff + bincount, never per op).
        scope_cids = [] if _opscope.enabled() else None
        t_decide = 0
        apply_ns = 0
        while True:
            run = tap.pop_ready(self.applied)
            if not run:
                if tap.should_probe_min(self.applied):
                    mn = self.px.min()
                    if mn > self.applied + 1:
                        if self._can_install():
                            # Behind the GC horizon with donors
                            # configured: flag for the driver's
                            # OUTSIDE-mu snapshot-install pass instead
                            # of skipping state (ISSUE 14).
                            self._behind_min = mn
                            break
                        while self.applied + 1 < mn:
                            self.applied += 1
                            self._inflight.pop(self.applied, None)
                        tap.discard_through(self.applied)
                        continue
                break
            if t_decide == 0:
                t_decide = time.monotonic_ns()
            if self._test_apply_delay:
                # tpusan: ok(lock-blocking-call) — TEST-ONLY seeded
                # stall for the opscope attribution acceptance: the
                # injected slow stage must sit exactly between the
                # decide and apply stamps; never set outside tests.
                time.sleep(self._test_apply_delay)
            t0 = time.perf_counter_ns()
            notif.extend(apply_batch(run, cnotif, scope_cids))
            apply_ns += time.perf_counter_ns() - t0
        applied_n = self.applied + 1 - base0
        if applied_n > 0:
            prof.add("apply", apply_ns)
            _M_APPLIED.inc(applied_n)  # columnar: one bump per drain
            t_apply = time.monotonic_ns() if scope_cids else 0
            t0 = time.perf_counter_ns()
            for fut, reply in notif:
                fut.set(reply)
            if cnotif is not None and cnotif[0]:
                # Columnar waiters: ONE reply-ring push per owning
                # frontend per drain — the single-frontend fast path is
                # still exactly one push; a fleet's drain fans out once
                # per distinct owner, order-preserving within each.
                _push_cnotif(cnotif)
            prof.add("notify", time.perf_counter_ns() - t0)
            if scope_cids:
                _opscope.fold(scope_cids, t_decide, t_apply,
                              time.monotonic_ns(), shard=self.shard)
        self._last_drain = applied_n
        if self.applied >= base0:
            if self._dev is not None:
                # A trailing FORGOTTEN fast-forward advances `applied`
                # past the last commit — no KV effect, note it so the
                # snapshot cut's watermark assert stays exact.
                self._dev.note_applied(self.applied)
            self._done_fn(self.applied)
            _blackbox.stamp(self._bb_key, self.applied)

    def _drain_bulk_locked(self, status_many):
        """Apply every already-decided instance in order, in bulk.  On the
        fabric backend the decided-delta FEED delivers each retire's new
        (seq, value) pairs — computed once per group, decoded once, fanned
        out to every replica (`_drain_feed_locked`).  Backends without the
        feed get the vectorized `drain_decided` prefix scan; backends
        without that fall back to status_many probes.  One Done()
        high-water call per drain; my in-flight proposals whose slot
        another server's op won are re-queued."""
        if self._tap is not None:
            return self._drain_feed_locked()
        drain = getattr(self.px, "drain_decided", None)
        if drain is None:
            return self._drain_bulk_scalar_locked(status_many)
        base0 = self.applied + 1
        while True:
            # 1024-wide drain: with the pipelined clock a single dispatch
            # can decide several waves' worth of seqs (K micro-steps per
            # retire), and the vectorized fabric pass costs the same lock
            # acquisition either way — don't make the driver loop to keep
            # up with it.
            vals, nxt, forgotten = drain(self.applied + 1, 1024)
            if forgotten:
                # Everything below Min() is gone everywhere; our dup
                # filter refreshes from the ops we can still see.
                mn = self.px.min()
                if mn <= self.applied + 1:
                    break  # transient view; retry next pass
                if self._can_install():
                    self._behind_min = mn  # driver installs outside mu
                    break
                while self.applied + 1 < mn:
                    self.applied += 1
                    self._inflight.pop(self.applied, None)
                continue
            if not vals:
                break
            for v in vals:
                if isinstance(v, Op):
                    self._apply(v)
                self.applied += 1
                self._pop_lost_inflight_locked(v)
        self._last_drain = self.applied + 1 - base0
        if self.applied >= base0:
            if self._dev is not None:
                self._dev.note_applied(self.applied)
            self._done_fn(self.applied)
            _blackbox.stamp(self._bb_key, self.applied)

    def _drain_bulk_scalar_locked(self, status_many):
        """status_many-probe drain for backends without drain_decided."""
        base0 = self.applied + 1
        # Probe sizing: start from the last pass's drain count (steady
        # state hits the right window in one call), floor 1 so an idle
        # replica's 20ms tick costs one status query; a longer decided
        # run widens geometrically.
        probe = min(256, max(1, getattr(self, "_last_drain", 1)))
        while True:
            base = self.applied + 1
            res = status_many(range(base, base + probe))
            n = 0
            for fate, v in res:
                if fate == Fate.DECIDED:
                    # isinstance guard: this log may carry foreign entries
                    # (shardkv's drain has the same guard, shardkv.py).
                    if isinstance(v, Op):
                        self._apply(v)
                    self.applied += 1
                    self._pop_lost_inflight_locked(v)
                elif fate == Fate.FORGOTTEN:
                    # Another replica applied + GC'd past us; our dup filter
                    # will be refreshed by the ops we *can* still see.
                    self.applied += 1
                    self._inflight.pop(self.applied, None)
                else:
                    break
                n += 1
            if n < probe:
                break
            probe = min(2 * probe, 256)  # long decided run: widen the probe
        self._last_drain = self.applied + 1 - base0
        if self.applied >= base0:
            if self._dev is not None:
                self._dev.note_applied(self.applied)
            self._done_fn(self.applied)
            _blackbox.stamp(self._bb_key, self.applied)

    # ------------------------------------------------------ horizon (ISSUE 14)

    def _can_install(self) -> bool:
        """Donor-backed catch-up is possible: horizon configured and at
        least one sibling to pull from.  False keeps the legacy
        fast-forward semantics byte-for-byte."""
        return self.horizon.enabled() and bool(self.peers)

    def _compact_locked(self, seq: int) -> None:
        """Apply one replicated `compact` log entry at `seq`: retire
        dup-table rows idle for more than `dup_retire_ops` applied ops.
        Pure function of (seq, table state) — identical on every
        replica at this log position."""
        if self.dup_retire_ops > 0:
            floor = seq - self.dup_retire_ops
            if floor > 0:
                n = self.dup.retire_below(floor)
                if n:
                    _horizon.note_dup_retired(n)

    def _horizon_rows(self) -> dict:
        # Runs on the pulse sampler thread (tracker registry) while the
        # driver mutates these under mu — len() of a dict mid-resize is
        # not safe without the GIL, and mu is cheap at sampling cadence.
        with self.mu:
            nkv = self._dev.nkeys if self._dev is not None else len(self.kv)
            d = {"kv_rows": nkv, "dup_rows": len(self.dup)}
        fab = getattr(self.px, "fabric", None)
        if fab is not None:
            d["window_live_slots"] = fab.live_slots
            d["window_key"] = id(fab)
        return d

    def _adopt_blob_locked(self, applied: int, blob: dict) -> None:
        """Install a decoded snapshot: replace the applied state, jump
        the watermark, and settle anything parked below it."""
        self.kv = dict(blob["kv"])
        if self._dev is not None:
            # Snapshot-install catch-up lands in the device store: fresh
            # intern tables, host-probed key table (bit-identical to the
            # device probe), single-node chains.
            self._dev.load_from_dict(self.kv, applied)
        dup = ColumnarDups()
        for cid, row in blob["dup"]:
            dup.put(cid, row[0], row[1], row[2] if len(row) > 2 else -1)
        self.dup = dup
        self.applied = applied
        for seq in [s for s in self._inflight if s <= applied]:
            del self._inflight[seq]
        # Waiters whose ops the snapshot already covers resolve from
        # the installed dup table (their decided seqs are below the
        # horizon — nothing will ever apply them here again).
        for key in list(self._waiters):
            cid, cseq = key
            if cseq <= dup.seen(cid):
                self._waiters.pop(key).set(dup.reply(cid))
        if self._csinks and self._ccseq:
            cnotif = ([], [], [], [])
            for cid in list(self._ccseq):
                if self._ccseq[cid] <= dup.seen(cid):
                    del self._ccseq[cid]
                    cnotif[0].append(self._ctag.pop(cid))
                    cnotif[1].append(dup.reply(cid))
                    cnotif[2].append(None)
                    cnotif[3].append(self._cowner.pop(cid, None))
            if cnotif[0]:
                _push_cnotif(cnotif)
        if self._tap is not None:
            self._tap.discard_through(applied)
        self._next_seq = max(self._next_seq, applied + 1)
        # A restored/installed table may carry OUR compact cid from a
        # previous life at a higher cseq — reseed the counter or the
        # next `dup.seen(_cmp_cid)` proposals would be silently
        # dup-swallowed for a whole run of snapshot cadences.
        self._cmp_cseq = max(self._cmp_cseq,
                             self.dup.seen(self._cmp_cid))

    def _catchup_attempt_once(self) -> str:
        """One pass over the configured donors (the shared behind-vs-
        unreachable discipline's attempt body)."""
        floor = self._behind_min - 1
        behind = False
        for peer in self.peers or ():
            if peer is self or getattr(peer, "dead", False):
                continue
            fetch = getattr(peer, "snapshot_fetch", None)
            if fetch is None:
                continue
            st, applied, blob = _horizon.install_from_peer(fetch, floor)
            if st == "ok":
                with self.mu:
                    if not self.dead and applied > self.applied:
                        self._adopt_blob_locked(applied, blob)
                self._done_fn(self.applied)
                return "ok"
            if st == "behind":
                behind = True
        return "behind" if behind else "unreachable"

    def _catchup_pass(self) -> None:
        """Driver-side snapshot-install (OUTSIDE mu — donor fetches
        must never run under our own server mutex).  Single-pass per
        driver tick: the driver cadence is the retry loop, diskv
        drain-style."""
        st = pull_from_peers(self._catchup_attempt_once, deadline_s=0.0,
                             is_dead=lambda: self.dead)
        if st == "ok":
            self._behind_min = 0
            self._wake.set()
        elif st == "behind":
            # Every reachable donor is at/below our watermark (a whole-
            # group restart): nothing to install, ever — fall back to
            # the legacy skip-forward so the group keeps living.
            with self.mu:
                mn = self._behind_min
                while self.applied + 1 < mn:
                    self.applied += 1
                    self._inflight.pop(self.applied, None)
                if self._dev is not None:
                    self._dev.note_applied(self.applied)
                if self._tap is not None:
                    self._tap.discard_through(self.applied)
            self._behind_min = 0

    def _maybe_snapshot(self) -> None:
        """Driver-side snapshot cadence: copy under mu, serialize +
        publish + spill OFF it (checkpointd cost model), then ride the
        cadence with one replicated `compact` proposal so the whole
        group trims at one log position."""
        hz = self.horizon
        # tpusan: ok(unlocked-shared-state) — off-mu cadence probe:
        # `applied` is re-read under mu below before any cut is taken;
        # a stale read here only delays the snapshot one cadence tick.
        if not hz.due(self.applied):
            return
        with self.mu:
            if self.dead:
                return
            applied = self.applied
            if applied <= hz.last_applied:
                return
            dev = self._dev
            if dev is not None:
                # Fused cut (ISSUE 16): under mu the cut is O(1) — jax
                # arrays are immutable, so capturing the refs IS the
                # consistent copy; materialization happens off-mu below.
                # The watermark assert is the log-position-exactness
                # contract: a cut taken between drains names exactly the
                # prefix the device table has applied, even with a drain
                # in flight on this same thread.
                assert dev.last_applied == applied, \
                    (dev.last_applied, applied)
                cut = dev.snapshot_cut()
                dup_rows = list(self.dup.items_with_seq())
                blob = None
            else:
                blob = {"applied": applied, "kv": dict(self.kv),
                        "dup": list(self.dup.items_with_seq())}
        if blob is None:
            # Off-mu half: resolve the cut into the blob dict.  Safe —
            # every engine mutation runs on this driver thread, and the
            # chain/intern slots a cut references are append-only.
            # Doubles as a mirror sync, so snapshot cadence keeps
            # `self.kv` fresh for free.
            blob = {"applied": applied, "kv": dev.snapshot_resolve(cut),
                    "dup": dup_rows}
            self.kv = blob["kv"]
        hz.publish(applied, blob)
        if self.dup_retire_ops > 0:
            # tpusan: ok(unlocked-shared-state) — _cmp_cseq is touched
            # only on this driver thread, which is also the only
            # snapshot adopter (_catchup_pass → _adopt_blob_locked):
            # same-thread single-writer, mu would add nothing.
            self._cmp_cseq += 1
            try:
                self.submit_batch(
                    (Op("compact", "", "", self._cmp_cid,
                        self._cmp_cseq),))
            except RPCError:
                self._cmp_cseq -= 1  # dead/racing kill: nothing queued

    def snapshot_fetch(self, floor: int, off: int = 0, n: int | None = None):
        """The snapshot-install RPC route (chunked, resumable): serve a
        chunk of the last published snapshot covering `floor`.
        LOCK-FREE on purpose — the published snapshot is immutable and
        `applied` is an advisory int read, so a donor mid-drain never
        convoys a puller behind its mutex (the tpusan donor rule)."""
        if self.dead:
            raise RPCError("dead")
        return self.horizon.chunk(floor, off, n,
                                  donor_applied=self.applied)

    def _collect_proposals_locked(self):
        """Assign consecutive seqs to everything queued; returns the
        (seq, op) block to propose.  Columnar blocks (native ingest)
        MATERIALIZE here — kind/key/value strings resolved from the
        frontend's native intern stores only now, on the driver thread,
        at proposal time: the frame→submit path never built a Python
        object per op, and an op answered or abandoned before this pass
        is skipped without ever materializing."""
        props = []
        nxt = max(self._next_seq, self.applied + 1)
        ccseq_get = self._ccseq.get
        for op in self._subq:
            key = (op.cid, op.cseq)
            if key not in self._waiters \
                    and ccseq_get(op.cid) != op.cseq:
                continue  # timed out, resolved, or already applied
            if op.cseq <= self.dup.seen(op.cid) \
                    and not self._test_disable_dup:
                continue  # applied via another replica's proposal
            props.append((nxt, op))
            self._inflight[nxt] = op
            if op.tc is not None:  # tpuscope: dispatch-span start instant
                self._trace_prop[(op.cid, op.cseq)] = time.monotonic_ns()
            nxt += 1
        self._subq = []
        if self._cblocks:
            cblocks, self._cblocks = self._cblocks, []
            dup_seen = self.dup.seen
            nodup = self._test_disable_dup
            tr = _tracing.enabled()
            kinds = _wire.KINDS
            for ticket, block, idxs in cblocks:
                res = block.resolver
                key_str = res.key_str
                val_str = res.val_str
                bk, bc, bs = block.kinds, block.cids, block.cseqs
                bkid, bvid = block.key_ids, block.val_ids
                tcs = block.tcs
                # tpusan: ok(lock-nested-loop) — one flat pass over the
                # submitted ops: the outer loop is per-BLOCK bookkeeping,
                # this is the same per-op proposal collection the classic
                # _subq loop runs under mu; the body is dict probes and
                # intern lookups, no device or socket work.
                for i in idxs:
                    cid = bc[i]
                    cseq = bs[i]
                    if ccseq_get(cid) != cseq:
                        continue  # answered / abandoned / superseded
                    if cseq <= dup_seen(cid) and not nodup:
                        continue  # applied via another replica
                    key = key_str(bkid[i])
                    value = val_str(bvid[i])
                    if key is None or value is None:
                        # Intern freed under us: only possible once the
                        # op decided elsewhere and its frame completed —
                        # the decided instance precedes anything we could
                        # propose now, so skipping is safe.
                        continue
                    tc = None
                    if tr and tcs is not None and tcs[i] is not None:
                        sp = _tracing.child(
                            "service.submit",
                            parent=_tracing.TraceContext(*tcs[i]),
                            comp="kvpaxos", key=key)
                        if sp is not None:
                            tc = (sp.trace_id, sp.span_id)
                            sp.end()
                    op = Op(kinds[bk[i]], key, value, cid, cseq, tc)
                    props.append((nxt, op))
                    self._inflight[nxt] = op
                    if tc is not None:
                        self._trace_prop[(cid, cseq)] = \
                            time.monotonic_ns()
                    nxt += 1
                # The ticket fence: the engine's deferred decref of this
                # block's interns is legal from here on.
                self.columnar_drained = ticket
        self._next_seq = nxt
        if props and _opscope.enabled():
            # opscope materialize stamp: one instant for the whole
            # proposal pass (classic _subq ops and columnar blocks
            # alike materialized HERE, on the driver, at this pass).
            _opscope.note_materialize_many(
                [op.cid for _s, op in props], time.monotonic_ns())
        return props

    def _unpropose_locked(self, props, idx):
        """start_many backpressure rollback: props[idx:] never reached the
        window — return them to the queue and rewind the seq counter."""
        for seq, op in props[idx:]:
            self._inflight.pop(seq, None)
            self._subq.append(op)
        if idx < len(props):
            self._next_seq = props[idx][0]

    def _drive_loop(self):
        px = self.px
        # Backend-outage retry pacing: jittered exponential backoff (cap
        # 100ms) instead of a fixed 20ms — N drivers behind one restarting
        # fabricd must not re-dial it in phase at 50Hz each.
        bo = Backoff(fixed_sleep=0.02)
        start_many = getattr(px, "start_many", None)
        status_many = getattr(
            px, "status_many",
            lambda seqs: [px.status(s) for s in seqs])
        wait_progress = getattr(px, "wait_progress", None)
        busy = False
        # Idle-adaptive catch-up tick: 20ms while anything is moving, then
        # backed off geometrically to 120ms on a quiet replica.  A passive
        # replica's tick exists only to apply already-decided entries and
        # advance Done(); at clerk-bench shape (hundreds of replicas on one
        # host) a fixed 20ms tick costs thousands of wakeups/sec of pure
        # GIL+fabric-lock churn that starves the clock thread the pipeline
        # is trying to keep busy.  Any submitted op (_wake) snaps the tick
        # back instantly, so op latency never pays the backoff.
        idle_wait = 0.02
        while True:
            if not busy:
                if self._wake.wait(idle_wait):
                    idle_wait = 0.02
                else:
                    idle_wait = min(idle_wait * 2, 0.12)
            try:
                with self.mu:
                    if self.dead:
                        if self._tap is not None:
                            self._tap.close()  # idempotent; stops fan-out
                        # Queued columnar blocks will never materialize:
                        # release the engine's decref fence.
                        self._cblocks.clear()
                        self.columnar_drained = self._cblocks_submitted
                        return
                    self._wake.clear()
                    self._drain_bulk_locked(status_many)
                    props = self._collect_proposals_locked()
                    busy = bool(props or self._inflight or self._subq)
                    if busy or getattr(self, "_last_drain", 0):
                        idle_wait = 0.02
                if props:
                    try:
                        if start_many is not None:
                            start_many(props)
                        else:
                            for i, (s, v) in enumerate(props):
                                try:
                                    px.start(s, v)
                                except WindowFullError as e:
                                    e.index = i
                                    raise
                        if _opscope.enabled():
                            # opscope dispatch stamp: the whole block
                            # just entered the fabric window (rolled-
                            # back ops re-stamp on their retry pass).
                            _opscope.note_dispatch_many(
                                [op.cid for _s, op in props],
                                time.monotonic_ns())
                    except WindowFullError as e:
                        with self.mu:
                            self._unpropose_locked(
                                props,
                                len(props) if e.index is None else e.index)
                    except RPCError:
                        # Transport failure mid-propose: roll back the
                        # WHOLE block (re-proposing an applied prefix is
                        # idempotent; leaving it in _inflight without a
                        # retry path would hole the log forever).
                        with self.mu:
                            self._unpropose_locked(props, 0)
                        raise
                if self._behind_min:
                    self._catchup_pass()
                if self.horizon.enabled():
                    self._maybe_snapshot()
                # tpusan: ok(unlocked-shared-state) — single-reference
                # probe: set_devapply flips `_dev` under mu and the
                # mirror swap below rechecks the engine under mu, so a
                # stale reference here costs one wasted resolve at
                # worst (see the swap comment).
                dev = self._dev
                if dev is not None and dev.mirror_due(self.applied):
                    # Mirror cadence: the readback/resolve runs OFF mu
                    # so replies keep flowing through it; under-mu
                    # engine users (kv_view, set_devapply) serialize
                    # against it on the engine's own leaf lock `emu`.
                    # The swap rechecks the engine under mu so a
                    # concurrent set_devapply(False) can't have its
                    # fresher host dict clobbered by an orphaned
                    # engine's mirror.
                    snap = dev.sync_mirror()
                    with self.mu:
                        if self._dev is dev:
                            self.kv = snap
                if busy:
                    # Ops outstanding: pace on consensus progress, then
                    # drain again immediately — no idle tick in the
                    # decide→resolve path.  With the decided-delta feed
                    # the fabric WAKES us the moment a retire delivers to
                    # our tap (and submit_batch wakes us for new ops), so
                    # the driver parks on its own event — zero fabric-lock
                    # traffic while a dispatch is in flight, and a fast
                    # return always means there is work (no spin floor
                    # needed: the next pass consumes what woke us, and an
                    # empty tap blocks the next wait).  Feedless backends
                    # keep the retire-notify wait: it returns at the FIRST
                    # retire, so the long timeout adds no latency when the
                    # clock is moving — it only stops N busy drivers from
                    # re-taking the fabric lock at 20Hz each to harvest
                    # nothing while a loaded clock (hundreds of replicas,
                    # one core) is still mid-dispatch.  A paused or
                    # stopped clock makes wait_progress return instantly;
                    # floor that pace so it can't become a GIL-starving
                    # spin loop.
                    if self._tap is not None:
                        self._wake.wait(0.25)
                    else:
                        t0 = time.monotonic()
                        if wait_progress is not None:
                            wait_progress(0.25)
                        if time.monotonic() - t0 < 0.001:
                            time.sleep(0.002)
                bo.reset()  # a full pass succeeded: next outage starts cold
            except RPCError:
                # Transient backend outage (e.g. a fabricd restarting from
                # a checkpoint behind a remote_fabric handle): keep the
                # driver alive and retry with capped jittered backoff —
                # shardkv's ticker has the same tolerance.
                bo.sleep()
                continue
            except Exception as e:  # noqa: BLE001 — singleton thread
                # The driver is the server's only engine: if it dies, no
                # future resolves, this replica stops Done()ing, and the
                # whole group's window eventually jams.  Record the bug in
                # the crash sink (stats()["health"]["thread_crashes"]) —
                # AND on stderr — but keep driving.
                import traceback

                traceback.print_exc()
                crashsink.record("kvpaxos-driver", e, fatal=False)
                time.sleep(0.02)
                continue

    # ------------------------------------------------------------ RPC surface

    def submit_batch(self, ops, sink=None) -> list[_Fut]:
        """Enqueue a block of ops for the group-commit driver under ONE
        lock acquisition; returns their futures (already resolved for
        duplicates).  The in-process seam the pipelined clerk multiplexes
        on; the blocking RPC surface is _submit = submit_batch + wait.

        `sink` (optional) is attached to every returned future BEFORE it
        can resolve: `fut.set` then invokes `sink(fut)` exactly once —
        the clerk frontend's event-loop completion hook.  A re-submit of
        an already-parked (cid, cseq) re-points the waiter at the NEW
        sink (last-writer-wins): with a frontend fleet, the retry that
        migrated frontends must be heard by the frontend the clerk is
        connected to now, not the one that first parked it."""
        futs = []
        tr = _tracing.enabled()
        cur = _tracing.current() if tr else None
        scope_cids = [] if _opscope.enabled() else None
        with self.mu:
            if self.dead:
                raise RPCError("dead")
            dup = self.dup
            waiters = self._waiters
            subq = self._subq
            nodup = self._test_disable_dup
            for op in ops:
                seen = dup.seen(op.cid)
                if op.cseq <= seen and not nodup:
                    fut = _Fut()
                    if sink is not None:
                        fut.sink = sink
                    fut.set(dup.reply(op.cid))
                else:
                    key = (op.cid, op.cseq)
                    fut = waiters.get(key)
                    if fut is None:
                        fut = _Fut()
                        if sink is not None:
                            fut.sink = sink
                        if scope_cids is not None:
                            scope_cids.append(op.cid)
                        if tr:
                            # tpuscope: stamp the op's trace metadata —
                            # parent is the rpc leg's context (explicit
                            # on pipelined-clerk ops, the thread's
                            # current context on the direct path).
                            pctx = (_tracing.TraceContext(*op.tc)
                                    if op.tc is not None else cur)
                            sp = _tracing.child("service.submit",
                                                parent=pctx,
                                                comp="kvpaxos",
                                                key=op.key)
                            if sp is not None:
                                op = op._replace(
                                    tc=(sp.trace_id, sp.span_id))
                                sp.end()
                        waiters[key] = fut
                        subq.append(op)
                    elif sink is not None and fut.sink is not sink:
                        # A waiter parked by the blocking surface or by
                        # ANOTHER frontend (a migrated retry): re-point it
                        # so the frontend the clerk talks to now hears the
                        # resolution.  The displaced frontend times the op
                        # out and abandons — at-most-once holds either way.
                        fut.sink = sink
                futs.append(fut)
            if scope_cids:
                # opscope park stamp: one instant for the whole batch
                # (in-process clerks have no earlier stage; the fold
                # back-fills their missing parse/poll stamps from here).
                _opscope.note_park(scope_cids, time.monotonic_ns())
        self._wake.set()
        return futs

    def submit_columnar(self, block, idxs, sink):
        """The native-ingest submit seam (ISSUE 11): `block` carries the
        decoded frame columns as plain int lists (kinds, cids, cseqs,
        key_ids, val_ids, tags, optional per-op tcs) plus a `resolver`
        (id → string, lazily, against the frontend's native intern
        stores); `idxs` selects the slots to submit.  Under ONE lock
        acquisition each op either dedups (already applied — its tag and
        cached reply return immediately for the engine to push) or parks
        as a columnar waiter: two int→int dict entries, NO per-op Python
        object.  Materialization into Op log entries happens on the
        driver at proposal time.

        Returns (ticket, dup_tags, dup_replies).  The ticket is the
        block's drain fence: once `columnar_drained >= ticket`, every
        accepted slot has been materialized or skipped and the engine
        may drop its intern references."""
        with self.mu:
            if self.dead:
                raise RPCError("dead")
            dup = self.dup
            ccseq = self._ccseq
            ctag = self._ctag
            cowner = self._cowner
            nodup = self._test_disable_dup
            cids = block.cids
            cseqs = block.cseqs
            tags = block.tags
            seen = dup.seen_many([cids[i] for i in idxs])
            accepted = []
            dup_tags = []
            dup_replies = []
            for j, i in enumerate(idxs):
                cid = cids[i]
                if cseqs[i] <= seen[j] and not nodup:
                    dup_tags.append(tags[i])
                    dup_replies.append(dup.reply(cid))
                else:
                    # Last-writer-wins on a re-park: a clerk retry that
                    # migrated frontends re-submits the same (cid, cseq)
                    # — the NEW owner's ring is where the clerk listens.
                    ccseq[cid] = cseqs[i]
                    ctag[cid] = tags[i]
                    cowner[cid] = sink
                    accepted.append(i)
            if accepted and _opscope.enabled():
                # opscope park stamp for the columnar waiters, with the
                # block's frame-parse/engine-poll ts columns when the
                # engine carried them (int columns, one park instant).
                if block.ts0 is not None:
                    _opscope.note_columnar_park(
                        [cids[i] for i in accepted],
                        [block.ts0[i] for i in accepted],
                        [block.tpolls[i] for i in accepted],
                        time.monotonic_ns())
                else:
                    _opscope.note_park([cids[i] for i in accepted],
                                       time.monotonic_ns())
            self._csinks[id(sink)] = sink
            if accepted:
                self._cblocks_submitted += 1
                ticket = self._cblocks_submitted
                self._cblocks.append((ticket, block, accepted))
            else:
                ticket = 0  # nothing to drain: fence trivially satisfied
        self._wake.set()
        return ticket, dup_tags, dup_replies

    def abandon_columnar(self, cids, cseqs, sink=None) -> None:
        """Drop columnar waiters (the engine's failover/timeout path) —
        the ops may still decide here, dup-filtered as ever, but this
        server stops re-proposing them and will not answer their tags.
        `sink`, when given, is an OWNERSHIP guard: only waiters this
        sink still owns are dropped.  The cseq check alone cannot
        distinguish frontend A's stale park from frontend B's re-park
        of the same migrated retry (same cid, SAME cseq) — without the
        guard a dying frontend's cleanup would strand the live
        frontend's waiter.  FAILOVER ops keep their opscope stamps (the
        retry re-parks the same cid on the next replica, overwriting
        park onward while the frame-parse origin survives); a timed-out
        frame's residue is bounded by the trim cap."""
        with self.mu:
            ccseq = self._ccseq
            ctag = self._ctag
            cowner = self._cowner
            for i, cid in enumerate(cids):
                if ccseq.get(cid) == cseqs[i] and \
                        (sink is None or cowner.get(cid) is sink):
                    del ccseq[cid]
                    ctag.pop(cid, None)
                    cowner.pop(cid, None)

    def detach_columnar(self, sink) -> None:
        """A frontend is going away: drop every columnar waiter it still
        owns and forget its sink, in one lock acquisition per server.
        Waiters the same cids re-parked through a DIFFERENT frontend
        (migrated retries) are untouched — ownership, not cid, decides.
        Idempotent; safe on a sink that never submitted here."""
        with self.mu:
            self._csinks.pop(id(sink), None)
            cowner = self._cowner
            for cid in [c for c, o in cowner.items() if o is sink]:
                del cowner[cid]
                self._ccseq.pop(cid, None)
                self._ctag.pop(cid, None)

    def submit_nowait(self, op: Op) -> _Fut:
        return self.submit_batch((op,))[0]

    def abandon(self, cid: int, cseq: int) -> None:
        """Drop the waiter for (cid, cseq): the client gave up on this
        server.  The op may still decide here — the dup filter keeps any
        retry at-most-once — but the driver stops re-proposing it.
        Opscope stamps deliberately survive an abandon: the clerk's
        blocking retry re-submits the SAME (cid, cseq) to a sibling
        replica, whose fold still wants the original parse/park origin
        (a never-retried op's residue is the trim cap's job)."""
        with self.mu:
            self._waiters.pop((cid, cseq), None)
            self._trace_prop.pop((cid, cseq), None)

    def _submit(self, op: Op):
        t0 = time.monotonic()
        fut = self.submit_nowait(op)
        if not fut.wait(self.op_timeout):
            self.abandon(op.cid, op.cseq)
            raise RPCError("op timeout (no majority?)")
        if fut.value is _DEAD:
            raise RPCError("server killed")
        if fut.tctx is not None:
            # tpuscope: the reply leg, parented to the apply span that
            # resolved the future (closes the clerk→...→apply chain).
            sp = _tracing.child("clerk.reply", parent=fut.tctx,
                                comp="clerk")
            if sp is not None:
                sp.end()
        done_at = fut.t_set if fut.t_set is not None else time.monotonic()
        _M_OP_LAT.observe((done_at - t0) * 1e6)
        return fut.value

    def get(self, key: str, cid: int, cseq: int):
        return self._submit(Op("get", key, "", cid, cseq))

    def put_append(self, kind: str, key: str, value: str, cid: int, cseq: int):
        return self._submit(Op(kind, key, value, cid, cseq))

    def set_devapply(self, on: bool) -> None:
        """Flip the devapply engine on a LIVE server (bench A/B): on
        loads the device table from the current host dict; off syncs
        the mirror back and drops the engine.  Under mu, so the flip
        lands exactly between drains — no op ever applies half-here."""
        with self.mu:
            if self.dead:
                return
            if on and self._dev is None:
                dev = DevApplyEngine()
                dev.load_from_dict(self.kv, self.applied)
                self._dev = dev
            elif not on and self._dev is not None:
                self.kv = self._dev.sync_mirror()
                self._dev = None

    def kv_view(self) -> dict:
        """The applied store as a host dict (tests/debug — NEVER the
        decided path): the live dict on the host path, a fresh mirror
        sync on the devapply path."""
        with self.mu:
            if self._dev is not None:
                self.kv = self._dev.sync_mirror()
            return self.kv

    def kill(self):
        with self.mu:
            self.dead = True
            for fut in self._waiters.values():
                fut.set(_DEAD)
            self._waiters.clear()
            self._ccseq.clear()
            self._ctag.clear()
            self._cowner.clear()
            self._cblocks.clear()
            # Dropped blocks will never materialize: release the fence so
            # the engines' deferred intern decrefs are not stranded.
            self.columnar_drained = self._cblocks_submitted
            # The columnar twin of the _DEAD future: tell EVERY attached
            # frontend engine to rotate this server's frames NOW (O(1)
            # enqueue+wake per sink — a fleet hears it fleet-wide).
            for s in self._csinks.values():
                s.server_dead(self)
            self._csinks.clear()
            self._trace_prop.clear()
            if self._tap is not None:
                self._tap.close()  # stop the fabric fanning into a corpse
        _horizon.unregister_tracker(self)
        self._wake.set()
        self.px.kill()


class Clerk:
    """kvpaxos/client.go:69-104 — try every server forever, at-most-once via
    (cid, cseq)."""

    def __init__(self, servers: list[KVPaxosServer], net: FlakyNet | None = None):
        self.servers = servers
        self.net = net or FlakyNet()
        self.cid = fresh_cid()
        self.cseq = 0
        self.mu = threading.Lock()
        # Retry pacing: capped exponential + decorrelated jitter by
        # default; TPU6824_CLERK_BACKOFF=fixed restores the reference's
        # flat 10ms (kvpaxos/client.go:69-104) for fidelity runs.
        self._backoff = Backoff()

    def _next(self) -> int:
        with self.mu:
            self.cseq += 1
            return self.cseq

    def _loop(self, fn_name, *args, timeout=None):
        cseq = self._next()
        deadline = time.monotonic() + timeout if timeout else None
        i = 0
        self._backoff.reset()
        # tpuscope root span: born here, closed at the clerk reply.  The
        # root's context is made current around each attempt so the rpc
        # leg (FlakyNet.call) and the server's submit chain off it.
        root = _tracing.span("clerk.op", comp="clerk", op=fn_name,
                             key=args[0] if fn_name == "get"
                             else args[1] if args else "") \
            if _tracing.enabled() else None
        try:
            while True:
                srv = self.servers[i % len(self.servers)]
                i += 1
                try:
                    fn = getattr(srv, fn_name)
                    if root is None:
                        return self.net.call(srv, fn, *args, self.cid,
                                             cseq)
                    with _tracing.use_ctx(root.ctx):
                        return self.net.call(srv, fn, *args, self.cid,
                                             cseq)
                except RPCError:
                    pass
                now = time.monotonic()
                if deadline and now >= deadline:
                    raise RPCError("clerk timeout")
                _M_RETRIES.inc()
                self._backoff.sleep(deadline - now if deadline else None)
        finally:
            if root is not None:
                root.end()

    def get(self, key: str, timeout=None) -> str:
        err, val = self._loop("get", key, timeout=timeout)
        return val if err == OK else ""

    def put(self, key: str, value: str, timeout=None):
        self._loop("put_append", "put", key, value, timeout=timeout)

    def append(self, key: str, value: str, timeout=None):
        self._loop("put_append", "append", key, value, timeout=timeout)


class PipelinedClerk:
    """W logical clients multiplexed on ONE thread (VERDICT r4 weak #4:
    thread-per-clerk drowns the batched runtime in GIL contention long
    before the fabric saturates).

    Each logical client is strictly sequential — its op j+1 is submitted
    only after its op j resolved — so the per-client-order invariant
    checkAppends asserts (kvpaxos/test_test.go:342-362) holds exactly as
    it does for W separate reference clerks.  The window is across
    clients: one wave = one in-flight op per client, submitted to the
    server's future-based seam (`submit_nowait`) so the group-commit
    driver proposes the whole wave as one consecutive seq block.  Server
    failure falls back to the plain blocking path on the other replicas
    (the reference clerk's try-every-server-forever loop,
    kvpaxos/client.go:69-104)."""

    def __init__(self, servers: list[KVPaxosServer], width: int = 8,
                 op_timeout: float = 8.0):
        self.servers = servers
        self.width = width
        self.op_timeout = op_timeout
        self.clients = [[fresh_cid(), 0] for _ in range(width)]
        self._leader = 0
        self._backoff = Backoff()  # same knob semantics as Clerk's

    # ------------------------------------------------- tpuscope plumbing
    # The pipelined clerk bypasses the blocking RPC surface (it talks to
    # submit_batch directly), so it opens its own per-op root + rpc-leg
    # spans and stamps the op's trace metadata explicitly — the same
    # chain the direct path gets from Clerk._loop + FlakyNet.call.

    def _trace_open(self, op: Op):
        """(op', (root, rpc_span)) with op' stamped, or (op, None) when
        untraced (disabled / sampled out) — zero allocation then."""
        root = _tracing.span("clerk.op", comp="clerk", op=op.kind,
                             key=op.key)
        if root is None:
            return op, None
        rsp = _tracing.child("rpc.call", parent=root.ctx, comp="rpc")
        if rsp is None:
            return op._replace(tc=(root.trace_id, root.span_id)), \
                (root, None)
        return op._replace(tc=(rsp.trace_id, rsp.span_id)), (root, rsp)

    @staticmethod
    def _trace_close(pair, fut) -> None:
        """Close a traced op at the clerk reply: emit the reply span
        (parented to the apply span the future carries) and end the
        root."""
        root, _rsp = pair
        if fut is not None and fut.tctx is not None:
            sp = _tracing.child("clerk.reply", parent=fut.tctx,
                                comp="clerk")
            if sp is not None:
                sp.end()
        root.end()

    def append_wave(self, key: str, values: list[str]) -> None:
        """Append values[c] as logical client c (len(values) <= width),
        all concurrently in flight; returns when every one is applied.

        Raises RPCError if an op finds no live majority within
        op_timeout.  The raise means that op's fate is UNKNOWN (it may
        have applied); its logical client's cseq is already consumed, so
        re-appending the same payload would NOT be dup-filtered — treat
        the raise as fatal for this clerk instance.  (The reference
        clerk never surfaces this state: it blocks forever instead,
        kvpaxos/client.go:69-104.)"""
        assert len(values) <= self.width
        srv = self.servers[self._leader % len(self.servers)]
        tr = _tracing.enabled()
        spans: dict[int, tuple] = {}
        ops = []
        for c, val in enumerate(values):
            cid, cseq = self.clients[c]
            cseq += 1
            self.clients[c][1] = cseq
            op = Op("append", key, val, cid, cseq)
            if tr:
                op, pair = self._trace_open(op)
                if pair is not None:
                    spans[c] = pair
            ops.append(op)
        try:
            futs = srv.submit_batch(ops)
        except RPCError:
            futs = [None] * len(ops)
        for pair in spans.values():  # the rpc leg ends at submit return
            if pair[1] is not None:
                pair[1].end()
        deadline = time.monotonic() + self.op_timeout
        for c, (op, fut) in enumerate(zip(ops, futs)):
            ok = False
            if fut is not None:
                ok = fut.wait(max(0.0, deadline - time.monotonic()))
                ok = ok and fut.value is not _DEAD
            pair = spans.pop(c, None)
            if pair is not None:
                self._trace_close(pair, fut if ok else None)
            if not ok:
                self._fail_over(srv, op)

    def append_stream(self, key: str, values_per_client,
                      on_done=None, lat_sink: list | None = None) -> None:
        """Barrier-free form of append_wave, built to ride the pipelined
        fabric clock: logical client c appends `values_per_client[c]` in
        order, and each client's NEXT op is submitted the moment its
        previous one resolves — no cross-client wave barrier, so one
        straggler (an op that just missed a dispatch and waits a whole
        pipeline turn) no longer stalls the other width-1 clients'
        submissions.  Resolved clients are re-submitted in one
        `submit_batch`, which the group-commit driver proposes as one
        consecutive seq block.  The per-client sequential invariant
        (checkAppends' per-client order) holds exactly as in append_wave;
        failure semantics per op match append_wave's (abandon + blocking
        retry on the other replicas).  `on_done(n)` is called as ops
        complete (throughput accounting at op granularity — a long stream
        resolves incrementally, not as one lump at return).  `lat_sink`
        (a list) collects per-op submit→resolve latencies in seconds for
        fast-path completions — the clerk-leg p50/p95/p99 the reference
        bounds with waitn's poll budget (test_test.go:51-66)."""
        assert len(values_per_client) <= self.width
        srv = self.servers[self._leader % len(self.servers)]
        tr = _tracing.enabled()
        spans: dict[int, tuple] = {}
        queues = [list(vs) for vs in values_per_client]
        heads = [0] * len(queues)
        pend: dict[int, tuple[Op, _Fut | None, float]] = {}
        while True:
            ops, cs = [], []
            for c, q in enumerate(queues):
                if heads[c] < len(q) and c not in pend:
                    cid, cseq = self.clients[c]
                    cseq += 1
                    self.clients[c][1] = cseq
                    op = Op("append", key, q[heads[c]], cid, cseq)
                    if tr:
                        op, pair = self._trace_open(op)
                        if pair is not None:
                            spans[c] = pair
                    ops.append(op)
                    heads[c] += 1
                    cs.append(c)
            if ops:
                try:
                    futs = srv.submit_batch(ops)
                except RPCError:
                    futs = [None] * len(ops)
                dl = time.monotonic() + self.op_timeout
                for c, op, fut in zip(cs, ops, futs):
                    pend[c] = (op, fut, dl)
                    pair = spans.get(c)
                    if pair is not None and pair[1] is not None:
                        pair[1].end()  # the rpc leg ends at submit return
            if not pend:
                return
            # Park on the oldest outstanding future, then sweep them all:
            # group commit resolves whole blocks per clock retire, so one
            # wait usually frees a batch of clients at once (set() wakes
            # this immediately — the 0.2s cap only bounds the timeout
            # housekeeping pass, it is not added latency).
            _, fut0, dl0 = next(iter(pend.values()))
            if fut0 is not None:
                fut0.wait(min(0.2, max(0.0, dl0 - time.monotonic())))
            else:
                time.sleep(0.001)
            now = time.monotonic()
            resolved = 0
            lat_us: list[float] = []  # columnar: one observe per sweep
            for c in list(pend):
                op, fut, dl = pend[c]
                if fut is not None and fut.ev.is_set():
                    del pend[c]
                    pair = spans.pop(c, None)
                    if pair is not None:
                        self._trace_close(
                            pair, None if fut.value is _DEAD else fut)
                    if fut.value is _DEAD:
                        self._fail_over(srv, op)
                    else:
                        resolved += 1  # fast-path completion only
                        # submit instant = dl - op_timeout (no extra
                        # clock read on the submit side); resolve
                        # instant = fut.t_set, stamped by the driver
                        # at set() time so the sweep's park interval
                        # never inflates the percentile tail.
                        done_at = fut.t_set if fut.t_set is not None \
                            else now
                        lat_us.append(
                            (done_at - (dl - self.op_timeout)) * 1e6)
                        if lat_sink is not None:
                            lat_sink.append(
                                done_at - (dl - self.op_timeout))
                elif fut is None or now >= dl:
                    del pend[c]
                    pair = spans.pop(c, None)
                    if pair is not None:
                        self._trace_close(pair, None)
                    self._fail_over(srv, op)
            if lat_us:
                _M_OP_LAT.observe_many(lat_us)
            if resolved and on_done is not None:
                on_done(resolved)

    def _fail_over(self, srv, op: Op) -> None:
        """Give up on this server's fast path for the op (stops its driver
        re-proposing on our behalf), then fall back to the reference
        clerk's blocking loop."""
        try:
            srv.abandon(op.cid, op.cseq)
        except RPCError:
            pass
        self._retry_blocking(op)

    def _retry_blocking(self, op: Op) -> None:
        """The reference clerk's retry loop, for ops whose fast path
        failed (dup filtering makes the retry at-most-once) — bounded by
        op_timeout so a torn-down cluster (every server dead) raises
        instead of spinning forever."""
        deadline = time.monotonic() + self.op_timeout
        i = self._leader + 1
        self._backoff.reset()
        while True:
            srv = self.servers[i % len(self.servers)]
            i += 1
            try:
                srv.put_append(op.kind, op.key, op.value, op.cid, op.cseq)
                self._leader = (i - 1) % len(self.servers)
                return
            except RPCError:
                now = time.monotonic()
                if now >= deadline:
                    raise RPCError(
                        f"pipelined clerk: op ({op.cid},{op.cseq}) found "
                        f"no live majority within {self.op_timeout}s")
                _M_RETRIES.inc()
                self._backoff.sleep(deadline - now)

    def get(self, key: str) -> str:
        """Linearizable read through any live replica (plain path)."""
        i = self._leader
        self._backoff.reset()
        while True:
            srv = self.servers[i % len(self.servers)]
            i += 1
            try:
                cid, cseq = self.clients[0]
                cseq += 1
                self.clients[0][1] = cseq
                err, val = srv.get(key, cid, cseq)
                return val if err == OK else ""
            except RPCError:
                self._backoff.sleep()


def make_cluster(nservers=3, ninstances=64, fabric=None, g=0, **kw):
    """Boot a kvpaxos replica group on (a group of) a fabric."""
    if fabric is None:
        fabric = PaxosFabric(ngroups=1, npeers=nservers, ninstances=ninstances,
                             auto_step=True)
    # Sibling handles for horizon's snapshot-install catch-up go in via
    # the CTOR as the shared (progressively filled) list: each server's
    # driver starts inside __init__, and its boot-time Min probe must
    # already see `peers` — assigning after construction raced the
    # probe into the legacy skip-forward on a warm fabric.
    servers: list[KVPaxosServer] = []
    if "peers" not in kw:
        kw["peers"] = servers
    for p in range(nservers):
        servers.append(KVPaxosServer(fabric, g, p, **kw))
    return fabric, servers


# ---------------------------------------------------------------------------
# Decentralized backend: the same RSM over per-message gob RPC
# (core/hostpeer.py) — the reference's own runtime model, so this service
# can be deployed one-replica-per-process with no shared fabric.
# (shim.gob is stdlib-only, so importing it here costs nothing next to the
# jax-backed fabric import above.)

from tpu6824.services.host_backend import StructOpPeer
from tpu6824.shim.gob import INT, STRING, Struct

KVOP_WIRE = Struct("KVOp", [
    ("Kind", STRING), ("Key", STRING), ("Value", STRING),
    ("CID", INT), ("Seq", INT),
])
KVOP_NAME = "tpu6824.KVOp"


def HostOpPeer(host_peer) -> StructOpPeer:
    """kvpaxos ops over the decentralized wire backend (the reference's
    `gob.Register(Op{})`, kvpaxos/server.go)."""
    return StructOpPeer(
        host_peer, KVOP_NAME, KVOP_WIRE,
        to_wire=lambda op: {"Kind": op.kind, "Key": op.key,
                            "Value": op.value, "CID": op.cid,
                            "Seq": op.cseq},
        from_wire=lambda d: Op(d["Kind"], d["Key"], d["Value"], d["CID"],
                               d["Seq"]),
    )


def make_host_replica(sockdir: str, nservers: int, me: int,
                      seed: int | None = None,
                      persist_dir: str | None = None,
                      peer_kw: dict | None = None, **kw):
    """One decentralized replica — peer endpoint + RSM server — suitable
    for one-replica-per-OS-process deployment (the reference's model:
    every server process embeds its own Paxos peer,
    kvpaxos/server.go StartServer).  With `persist_dir`, the peer survives
    crash+restart.  `peer_kw` goes to HostPaxosPeer (pooled=,
    parallel_fanout=, ...); other keywords go to the server.  Returns
    (host_peer, server)."""
    from tpu6824.services.host_backend import make_host_replica as _mk

    return _mk(sockdir, "px", KVOP_NAME, KVOP_WIRE,
               lambda p: KVPaxosServer(None, 0, p.me, px=HostOpPeer(p), **kw),
               nservers, me, seed=seed, persist_dir=persist_dir,
               **(peer_kw or {}))


def make_host_cluster(sockdir: str, nservers: int = 3, seed: int | None = None,
                      pooled: bool = False, peer_kw: dict | None = None,
                      **kw):
    """kvpaxos on the decentralized wire path: one gob Paxos endpoint per
    replica, consensus by per-message Prepare/Accept/Decided RPC — the
    reference's deployment model end to end.  pooled=True runs the peers
    on long-lived net/rpc client connections (the optimized profile);
    `peer_kw` passes any further HostPaxosPeer options."""
    from tpu6824.services.host_backend import make_host_cluster as _mk

    pk = dict(peer_kw or {})
    if pooled:
        pk["pooled"] = True
    return _mk(sockdir, "px", KVOP_NAME, KVOP_WIRE,
               lambda p: KVPaxosServer(None, 0, p.me, px=HostOpPeer(p), **kw),
               nservers, seed=seed, **pk)
