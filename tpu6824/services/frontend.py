"""Columnar event-loop clerk frontend — the batched request path (ROADMAP
item 1, the "millions of users" bet).

The published clerk leg topped out around the host's thread-per-clerk
ceiling (BENCH_r05/r07 `service.clerk.phases`: fabric idle, Python
burning the core, clerk p50 421ms) — the same diagnosis *Network
Hardware-Accelerated Consensus* and *Paxos Made Switch-y* make for
host-bound consensus message handling: per-connection request paths do
not amortize, batched dataplanes do.  This module is that dataplane for
the clerk leg:

  - `ClerkFrontend` fronts one replica group on a Unix socket served by
    the NATIVE EPOLL LOOP (`rpc/native_server.py`): requests are decoded
    inline on the loop's callback thread (`register_inline` — zero
    handler threads per request) and enqueued; replies are deferred and
    re-enter the loop via eventfd from the frontend's engine thread.
  - The wire grows a MULTI-OP frame (`fe_batch`: many clerk ops per
    frame); classic single-op frames (`get`/`put_append`) keep working —
    both interop in a mixed fleet, in both directions, including the
    optional trace-context frame element (PR 5).
  - One engine thread drains everything queued since its last pass into
    ONE `KVPaxosServer.submit_batch` call — one columnar propose batch
    per fabric tick — and the group-commit driver resolves the futures
    in its existing one-sweep retire notify, which lands them right back
    here through the future `sink` hook (no per-op waiter thread,
    anywhere).
  - Clerk retry/backoff state lives IN the event loop: per-frame retry
    deadlines rotate unresolved ops across replicas with growing
    intervals — no thread ever sleeps on behalf of an op.

Event-loop discipline (tpusan `blocking-in-eventloop`): every `_on_*`
callback in this module only decodes/enqueues/wakes — no sleeps, no
lock waits, no blocking calls.  The engine thread MAY block briefly
(submit_batch takes the server mutex): that is the batching handoff,
one acquisition per pass, not per op.

Scale shape: ops/s grows with connection count × batch width, not
thread count — the frontend adds THREE threads total (epoll loop,
engine, and the server's reply path is the loop itself) no matter how
many clerks connect.
"""

from __future__ import annotations

import os
import pickle
import select
import threading
import time
from collections import deque

from tpu6824.obs import blackbox as _blackbox
from tpu6824.obs import metrics as _metrics
from tpu6824.obs import opscope as _opscope
from tpu6824.obs import pulse as _obs_pulse
from tpu6824.obs import tracing as _tracing
from tpu6824.rpc import transport, wire
from tpu6824.rpc.native_server import NativeServer, make_server
from tpu6824.services.common import Backoff, fresh_cid
from tpu6824.services.devapply import DevVal
from tpu6824.services.kvpaxos import _DEAD, Op
from tpu6824.utils import crashsink
from tpu6824.utils.locks import new_lock
from tpu6824.utils.errors import OK, ErrTxnLocked, RPCError

# The multi-op frame's rpc name.  An old server answers it with
# (False, "no such rpc: fe_batch") → RPCError at the client → the clerk
# falls back to single-op frames (mixed-fleet interop, new→old).
FE_BATCH = "fe_batch"

# Knobs (TUNING round 13): the frontend's per-op budget (retry deadlines
# and the hard frame timeout derive from it) and the stream clerk's
# default wire-pipelining depth (cohorts per connection).
OP_TIMEOUT = float(os.environ.get("TPU6824_FRONTEND_OP_TIMEOUT", 8.0))
STREAM_DEPTH = int(os.environ.get("TPU6824_FRONTEND_DEPTH", 2))
# Overload protection (ISSUE 12, TUNING round 16): the admission
# watermark — total ops the frontend will hold in flight before it
# SHEDS new frames with an explicit retryable error.  Shedding beats
# the alternatives it replaces: an unbounded queue turns overload into
# timeouts (the clerk can't tell shed from dead and burns its whole
# budget), and the native ring's hard bounce fires only when the ring
# is literally full.  The watermark is deliberately below the default
# ring cap so the explicit shed answers first.
MAX_INFLIGHT = int(os.environ.get("TPU6824_FE_MAX_INFLIGHT", 1 << 15))

# tpuscope metrics (module scope per the metric-unregistered rule).
_M_FRAMES = _metrics.counter("frontend.frames")
_M_OPS = _metrics.counter("frontend.ops")
_M_WIDTH = _metrics.histogram("frontend.frame_width")
_M_SUBMIT = _metrics.histogram("frontend.submit_ops")  # columnar batch size
_M_RETRIES = _metrics.counter("frontend.retries")
_M_TIMEOUTS = _metrics.counter("frontend.timeouts")
# Overload protection (ISSUE 12): frames shed at the admission
# watermark (explicit retryable error, not a timeout) and the live
# inflight gauge the watchdog watches.  A propagated deadline needs no
# counter of its own: it tightens the frame deadline, so expiry shows
# up as frontend.timeouts — reached sooner, which is the point.
_M_SHED = _metrics.counter("frontend.shed")
_M_INFLIGHT = _metrics.gauge("frontend.inflight_ops")
# meshfab cross-shard serving: ops arriving in a frame whose routed
# groups span MORE THAN ONE mesh shard — the frame fans out across
# devices to be served.  A mesh deployment whose clerks batch
# shard-locally keeps this near zero; a climbing rate says the key→
# group→shard placement is fighting the traffic shape.
_M_XSHARD = _metrics.counter("meshfab.cross_shard_ops")
# Native zero-GIL ingest (ISSUE 11): the C++ loop's decode counters,
# mirrored into the registry each engine pass so pulse/top/watchdog see
# the native path (the inflight gauge is what queue-growth watches).
_M_NI_FRAMES = _metrics.counter("frontend.native_ingest.frames")
_M_NI_OPS = _metrics.counter("frontend.native_ingest.ops")
_M_NI_BYTES = _metrics.counter("frontend.native_ingest.bytes")
_M_NI_FULL = _metrics.counter("frontend.native_ingest.ring_full")

_ONE8 = (1).to_bytes(8, "little")  # eventfd wake payload (preallocated)

_UNSET = object()  # reply slot not yet resolved

# Default frontend_id sequence: unique per (pid, instance) — see
# ClerkFrontend.frontend_id.
_FE_SEQ = iter(range(1 << 62))


def _kv_op(kind, key, value, cid, cseq, tc):
    """Default op factory: the kvpaxos log entry."""
    return Op(kind, key, value, cid, cseq, tc)


class _Frame:
    """One in-flight request frame: conn + per-op reply slots + the
    event-loop retry state that replaces per-thread clerk sleeps."""

    __slots__ = ("conn_id", "single", "ops", "gids", "futs", "replies",
                 "remaining", "deadline", "retry_at", "interval", "srv",
                 "last_remaining", "native", "crc")

    def __init__(self, conn_id, single, nops, now, op_timeout,
                 native=False, deadline_ms=None, crc=False):
        self.conn_id = conn_id
        self.single = single
        self.native = native  # arrived in the fe wire layout: reply in it
        self.crc = crc        # request carried FLAG_CRC: echo it back
        self.ops = None
        self.gids = None            # per-slot target group index
        self.futs = [None] * nops
        self.replies = [_UNSET] * nops
        self.remaining = nops
        # Deadline propagation (ISSUE 12): when the clerk's remaining op
        # budget rode the frame header, the server works to THAT bound —
        # never longer than its own op_timeout — so ops the clerk has
        # already abandoned stop consuming proposals.
        if deadline_ms:
            op_timeout = min(op_timeout, deadline_ms / 1000.0)
        self.deadline = now + op_timeout
        # First failover attempt after a good slice of the op budget
        # (the pipelined clerk waits the WHOLE budget before failing
        # over); under deep in-flight load a frame legitimately takes a
        # few dispatch periods, and an eager retry re-proposes its ops
        # on another replica — a self-amplifying storm.  The interval
        # then doubles, capped at half the budget — the clerk Backoff
        # curve, expressed as event-loop deadlines instead of sleeps.
        self.interval = max(1.0, op_timeout / 4.0)
        self.retry_at = now + self.interval
        self.srv = {}               # gid → replica idx last submitted to
        self.last_remaining = nops


class _NFrame:
    """One in-flight NATIVE-INGEST frame: the engine's bookkeeping for a
    frame whose ops live as int columns (decoded by the C++ loop) and
    whose replies flow through the native reply ring.  Columns are plain
    int lists (one tolist per frame at poll); `kid_arr`/`vid_arr` keep
    the numpy copies for the columnar intern decref at reap."""

    __slots__ = ("fid", "conn_id", "nops", "tc", "kinds", "cids", "cseqs",
                 "key_ids", "val_ids", "kid_arr", "vid_arr", "gids", "tcs",
                 "deadline", "retry_at", "interval", "srv", "cur_srv",
                 "tickets", "last_pending", "ts0", "tpoll")

    def __init__(self, fid, conn_id, nops, tc, now, op_timeout,
                 deadline_ms=0):
        self.fid = fid
        self.conn_id = conn_id
        self.nops = nops
        self.tc = tc
        self.gids = None
        self.tcs = None
        # opscope stage stamps (ISSUE 15): frame-parse origin (the C++
        # loop's ts column) and the engine-poll instant — per-frame
        # ints, broadcast per op at block build.
        self.ts0 = 0
        self.tpoll = 0
        if deadline_ms:  # propagated clerk budget (the _Frame rule)
            op_timeout = min(op_timeout, deadline_ms / 1000.0)
        self.deadline = now + op_timeout
        self.interval = max(1.0, op_timeout / 4.0)  # the _Frame curve
        self.retry_at = now + self.interval
        self.srv = {}       # gid → leader index last submitted to
        self.cur_srv = {}   # gid → server object last submitted to
        self.tickets = []   # (server, drain ticket) per submission
        self.last_pending = nops


class _CBlock:
    """One columnar submission: concatenated frame columns + the id→str
    resolver (the native intern mirror).  The exact shape
    KVPaxosServer.submit_columnar consumes."""

    __slots__ = ("kinds", "cids", "cseqs", "key_ids", "val_ids", "tags",
                 "tcs", "resolver", "ts0", "tpolls")

    def __init__(self, resolver):
        self.kinds = []
        self.cids = []
        self.cseqs = []
        self.key_ids = []
        self.val_ids = []
        self.tags = []
        self.tcs = None
        self.resolver = resolver
        # opscope ts columns (None when opscope is off): frame-parse
        # and engine-poll ns per op, parked with the columnar waiter.
        self.ts0 = None
        self.tpolls = None


class _NativeSink:
    """The columnar reply sink handed to submit_columnar: `push` runs on
    the group-commit driver's notify sweep (under the server mutex — one
    call per drain, arrays only, no locks taken here) and writes straight
    into the C++ reply ring; `server_dead` is the columnar twin of the
    _DEAD future (O(1) enqueue + engine wake)."""

    __slots__ = ("_ing", "_np", "_deadq", "_wake")

    def __init__(self, ing, deadq, wake):
        import numpy as np

        self._np = np
        self._ing = ing
        self._deadq = deadq
        self._wake = wake

    def push(self, tags, replies, tctxs=None) -> None:
        np = self._np
        ing = self._ing
        n = len(tags)
        t = np.array(tags, dtype=np.int64)
        errs = np.empty(n, dtype=np.uint8)
        reps = np.full(n, -1, dtype=np.int32)
        code_of = wire.ERR_CODE.get
        vidx = vbytes = None  # slots whose reply carries value bytes
        for i, rep in enumerate(replies):
            code = None
            if type(rep) is tuple and len(rep) == 2 \
                    and isinstance(rep[1], str):
                code = code_of(rep[0])
            if code is None:
                errs[i] = wire.ERR_OTHER
                vb = pickle.dumps(rep, protocol=pickle.HIGHEST_PROTOCOL)
            else:
                errs[i] = code
                val = rep[1]
                if not val:
                    continue  # (OK, "")-class reply: no value bytes
                # devapply get replies carry their bytes memoized per
                # chain NODE — repeated gets of a hot key hand the ring
                # the same bytes object instead of re-encoding each.
                vb = val.bytes() if type(val) is DevVal else val.encode()
            if vidx is None:
                vidx, vbytes = [], []
            vidx.append(i)
            vbytes.append(vb)
        if vidx is not None:
            # ONE C call for the whole sweep's get replies (review
            # finding: per-op val_intern under the server mutex).
            reps[vidx] = ing.val_intern_many(vbytes)
        ing.push(t, errs, reps)
        if tctxs is not None:
            for ctx in tctxs:
                if ctx is not None:
                    sp = _tracing.child("frontend.reply", parent=ctx,
                                        comp="frontend")
                    if sp is not None:
                        sp.end()

    def server_dead(self, server) -> None:
        self._deadq.append(server)
        self._wake()


class ClerkFrontend:
    """Batched event-loop frontend over one or many replica groups.

    `servers` is a single group's replica list (objects with the
    `submit_batch(ops, sink=)`/`abandon` seam — KVPaxosServer, or
    ShardKVServer via `op_factory=shardkv_op`), or — with `route` given
    — `groups` is a list of such replica lists and `route(key)` picks
    the group index per op, so ONE frontend (one socket, one engine
    thread) fronts a whole fleet of groups: every engine pass becomes
    one columnar submit_batch per group per fabric tick, and the thread
    count stays constant no matter how many groups or connections ride
    it.  Per group, all ops of a pass go to one leader replica;
    unresolved ops rotate to the next replica on event-loop retry
    deadlines."""

    def __init__(self, servers=None, addr: str = "", *,
                 op_timeout: float = OP_TIMEOUT, seed: int | None = None,
                 prefer_native: bool = True, op_factory=_kv_op,
                 groups=None, route=None, shard_of=None,
                 ingest_max_ops: int = 1 << 16,
                 max_inflight: int | None = None,
                 frontend_id: str | None = None):
        if groups is None:
            groups = [list(servers)]
        # Fleet identity (ISSUE 18): a fleet-unique name the frontend
        # stamps on its stats/caps surfaces, so Collector members and
        # obs.top rows attribute a sick frontend by NAME — N frontends
        # of one fleet usually share a socket basename pattern
        # (fe0.sock, fe1.sock in one dir, or fe.sock in N dirs), and
        # the basename-derived member names collide.  Default is
        # unique per process AND per instance (pid + instance seq).
        self.frontend_id = frontend_id if frontend_id \
            else f"fe-{os.getpid()}-{next(_FE_SEQ)}"
        # Crash forensics (ISSUE 20): with TPU6824_BLACKBOX_DIR set the
        # process records into a crash-surviving ring; the engine loop
        # stamps its in-flight count there (one GIL-atomic dict store
        # per PASS, key precomputed here — zero per-op cost) so a
        # postmortem over a SIGKILLed frontend reports the ops it died
        # holding.
        _blackbox.enable_from_env()
        self._bb_key = f"frontend.inflight.{self.frontend_id}"
        self.groups = [list(g) for g in groups]
        self._route = route if route is not None else (lambda key: 0)
        # meshfab cross-shard serving: per-group owning mesh shard,
        # defaulting to each group's lead replica's shard binding (the
        # kvpaxos/shardkv servers bind `shard` at attach) — so routing
        # ops to the shard owning their group needs no extra wiring, and
        # a frame spanning shards is observable (_note_shards).  Single-
        # device fabrics bind everything to shard 0 and the whole path
        # is one predicate.
        if shard_of is None:
            binds = [getattr(g[0], "shard", 0) if g else 0
                     for g in self.groups]
            shard_of = binds.__getitem__
        self._shard_of = shard_of
        self._multi_shard = len(
            {shard_of(i) for i in range(len(self.groups))}) > 1
        self._leaders = [0] * len(self.groups)
        self.addr = addr
        self.op_timeout = op_timeout
        self.op_factory = op_factory
        # Admission control (ISSUE 12): total ops held in flight before
        # new frames are shed with an explicit retryable error.
        self.max_inflight = MAX_INFLIGHT if max_inflight is None \
            else int(max_inflight)
        self._inflight = 0  # Python-path ops admitted, engine-owned
        self._rej_last = 0  # last-mirrored native wire_rejected count
        self._pending: deque = deque()   # (conn_id, ops_wire, wctx, single)
        self._doneq: deque = deque()     # resolved futures (sink hook)
        self._wake = threading.Event()
        self._dead = False
        srv = make_server(addr, seed=seed, prefer_native=prefer_native)
        self._srv = srv
        self.deferred = isinstance(srv, NativeServer)
        if self.deferred:
            srv.register_inline(FE_BATCH, self._on_batch)
            srv.register_inline("get", self._on_get)
            srv.register_inline("put_append", self._on_put_append)
            # fe wire frames that reach Python (C++ ingest off): decoded
            # by the shared schema, served by the same engine, answered
            # in the layout they arrived in.
            srv.register_native_batch(self._on_native_batch)
        else:
            # Python accept-loop fallback (no C++ toolchain): blocking
            # handlers, one thread per CONNECTION — the batch still
            # amortizes per-frame, only the thread economics degrade.
            # fe wire frames land on the SAME fe_batch handler through
            # transport.Server's native-frame branch (fallback parity).
            srv.register(FE_BATCH, self._fe_batch_blocking)
            srv.register("get", self._get_blocking)
            srv.register("put_append", self._put_append_blocking)
        # Capability probe: clerks ask once per endpoint whether the
        # versioned fe wire is spoken here ("no such rpc" = old peer),
        # and which caps-gated v1 extensions are safe to send: deadline
        # propagation and frame CRC (ISSUE 12).  An old clerk ignores
        # the extra keys; an old server's caps lack them, so a new
        # clerk never sends a flag this endpoint cannot parse.
        # `_ext_ok` gates the advertisement on the actual decoder: with
        # C++ ingest enabled on a STALE .so that predates the extension
        # flags, advertising them would make every extended frame
        # "malformed" — a retry loop, not an interop path (set after
        # enable_ingest below; the lambda reads it per probe).
        self._ext_ok = True
        # Txn capability (ISSUE 13): only an op factory that builds 2PC
        # log entries (shardkv_op marks itself) may receive the
        # caps-gated txn frame kinds — a kvpaxos frontend (incl. the
        # native-ingest path, whose C++ decoder refuses kind codes ≥ 3
        # by design) never advertises it, so old and txn-less endpoints
        # alike simply never see a txn frame.
        self._txn_ok = bool(getattr(op_factory, "supports_txn", False))
        srv.register("fe_caps", lambda: {"fe_wire": wire.VERSION,
                                         "fe_deadline": self._ext_ok,
                                         "fe_crc": self._ext_ok,
                                         "fe_txn": self._txn_ok,
                                         "fe_id": self.frontend_id})
        # Observability plane (regular threaded handlers — pollers are
        # rare and must never touch the event loop): a fleet Collector
        # polls a live frontend process like any fabric process — the
        # registry snapshot (frontend.* plus the clerk pool's
        # rpc.pool.*), engine-side stats, flight ring, and pulse series.
        srv.register("stats", self.stats)
        srv.register("metrics", self._metrics_rpc)
        srv.register("flight", _tracing.flight_snapshot)
        srv.register("pulse", _obs_pulse.series_snapshot)
        srv.register("opscope", _opscope.snapshot)
        srv.register("blackbox", _blackbox.status)
        srv.start()
        # Zero-GIL ingest (ISSUE 11): only the kvpaxos submit_columnar
        # seam can consume the columnar frames, so custom op factories
        # (shardkv) keep the Python decode path.
        self._ing = None
        self._deadq: deque = deque()
        self._csink = None
        self._wake_armed = False
        self._ing_last = None  # previous counter snapshot (mirror deltas)
        self._flush_last = None  # opscope flush-hist snapshot (deltas)
        self._mirror_mu = new_lock("frontend.mirror_mu")  # engine pass vs metrics RPC
        if self.deferred and op_factory is _kv_op and all(
                hasattr(s, "submit_columnar")
                for g in self.groups for s in g):
            self._ing = srv.enable_ingest(ingest_max_ops)
            if self._ing is not None:
                self._csink = _NativeSink(self._ing, self._deadq,
                                          self._wake_native)
                self._ing_last = {"frames": 0, "ops": 0, "bytes": 0,
                                  "ring_full": 0, "done_ops": 0}
                # The extension flags are parsed by the C++ decoder
                # now; the netfault ABI ships in the same compilation
                # unit, so its presence proves the lib is new enough.
                self._ext_ok = hasattr(srv._lib, "rpcsrv_netfault_arm")
        self._engine = None
        if self.deferred:
            self._engine = threading.Thread(
                target=crashsink.guarded(self._engine_loop,
                                         "frontend-engine"),
                daemon=True)
            self._engine.start()

    # ------------------------------------------------ event-loop callbacks
    # tpusan blocking-in-eventloop scope: decode + enqueue + wake ONLY.

    def _on_batch(self, conn_id, args, wctx) -> None:
        # The trailing element is the opscope frame-parse stamp (one
        # monotonic read per frame — decode/enqueue/wake discipline
        # intact); 0 when opscope is off.
        t0 = time.monotonic_ns() if _opscope.enabled() else 0
        self._pending.append((conn_id, args[0], wctx, False, False, None,
                              t0))
        self._wake_engine()

    def _on_native_batch(self, conn_id, ops, tc, meta) -> None:
        # fe wire frame decoded in Python (C++ ingest off): same queue,
        # native reply flag set so the answer leaves in the fe layout
        # (meta: propagated deadline + crc echo).
        t0 = time.monotonic_ns() if _opscope.enabled() else 0
        self._pending.append((conn_id, ops, tc, False, True, meta, t0))
        self._wake_engine()

    def _on_get(self, conn_id, args, wctx) -> None:
        key, cid, cseq = args
        t0 = time.monotonic_ns() if _opscope.enabled() else 0
        self._pending.append(
            (conn_id, (("get", key, "", cid, cseq),), wctx, True, False,
             None, t0))
        self._wake_engine()

    def _on_put_append(self, conn_id, args, wctx) -> None:
        kind, key, value, cid, cseq = args
        t0 = time.monotonic_ns() if _opscope.enabled() else 0
        self._pending.append(
            (conn_id, ((kind, key, value, cid, cseq),), wctx, True,
             False, None, t0))
        self._wake_engine()

    def _on_fut_done(self, fut) -> None:
        # The future sink: runs on the group-commit driver's notify
        # sweep, under the server mutex — O(1), no locks, no blocking.
        # The guards matter: a notify sweep delivers THOUSANDS of
        # futures back-to-back, and Event.set() takes the event's
        # condition lock every call — sampled at 14% of busy time before
        # the guard; is_set()/_wake_armed are lock-free flag reads.
        self._doneq.append(fut)
        self._wake_engine()

    # ------------------------------------------------------ engine wakes

    def _wake_native(self) -> None:
        """Wake the engine's eventfd wait (native-ingest mode) — armed
        flag keeps it one syscall per sleep, not one per event."""
        if not self._wake_armed:
            self._wake_armed = True
            try:
                os.write(self._ing.fd, _ONE8)
            except OSError:
                pass  # engine torn down under us

    def _wake_engine(self) -> None:
        if self._ing is not None:
            self._wake_native()
        elif not self._wake.is_set():
            self._wake.set()

    # ------------------------------------------------------------- stats

    def stats(self) -> dict:
        """Engine-side health for fleet pollers (served as the `stats`
        RPC): queue depths and shape — the frontend analog of the
        fabric's stats() surface, so `obs.top` and the Collector treat
        a frontend process like any other fleet member.  Reads are
        len() on deques (atomic under the GIL), never a lock."""
        ing = self._ing
        return {
            "frontend": {
                "id": self.frontend_id,
                "groups": len(self.groups),
                "replicas": [len(g) for g in self.groups],
                "pending_frames": len(self._pending),
                "done_queue": len(self._doneq),
                "deferred": self.deferred,
                "op_timeout": self.op_timeout,
                "inflight_ops": self._inflight,
                "max_inflight": self.max_inflight,
                "wire_rejected": getattr(self._srv, "wire_rejected", 0),
                "native_ingest": (ing.stats() if ing is not None
                                  else {"enabled": False}),
            },
        }

    # ------------------------------------------------------------- engine

    def _make_op(self, t, wctx):
        """Wire op tuple → log entry, trace-stamped when the op (len-6
        tuple tail) or the frame (wire envelope) carries a context."""
        kind, key, value, cid, cseq = t[:5]
        tc = None
        if _tracing.enabled():
            ptc = t[5] if len(t) > 5 else wctx
            if ptc is not None:
                sp = _tracing.child("frontend.submit",
                                    parent=_tracing.TraceContext(*ptc),
                                    comp="frontend", key=key)
                if sp is not None:
                    tc = (sp.trace_id, sp.span_id)
                    sp.end()
        return self.op_factory(kind, key, value, cid, cseq, tc)

    def _note_shards(self, gids) -> None:
        """Cross-shard accounting for ONE frame's routed groups: when
        they span more than one mesh shard, every op in the frame is a
        cross-shard op (serving it fans out across devices).  One
        predicate + at most one counter bump per frame; single-shard
        deployments early-out on a cached bool."""
        if not self._multi_shard or not gids:
            return
        so = self._shard_of
        first = so(gids[0])
        if any(so(g) != first for g in gids):
            _M_XSHARD.inc(len(gids))

    def _submit(self, ops, owners, gids, futmap) -> None:
        """This pass's ops, ONE columnar submit_batch per target group
        (to that group's leader replica; rotates on a refused/dead
        replica — with every replica refusing, the frames' retry
        deadlines take over)."""
        if len(self.groups) == 1:
            by_group = {0: range(len(ops))}
        else:
            by_group = {}
            for i, gid in enumerate(gids):
                by_group.setdefault(gid, []).append(i)
        for gid, idxs in by_group.items():
            gops = ops if len(self.groups) == 1 \
                else [ops[i] for i in idxs]
            servers = self.groups[gid]
            nsrv = len(servers)
            futs = None
            for _ in range(nsrv):
                srv = servers[self._leaders[gid] % nsrv]
                try:
                    futs = srv.submit_batch(gops, sink=self._on_fut_done)
                    break
                except RPCError:
                    self._leaders[gid] += 1
            now = None
            if futs is None:
                now = time.monotonic()  # group dead right now: retry soon
            _M_SUBMIT.observe(len(gops))
            for i, j in enumerate(idxs):
                fr, slot = owners[j]
                if futs is None:
                    fr.retry_at = min(fr.retry_at, now + 0.05)
                    continue
                fut = futs[i]
                fr.futs[slot] = fut
                fr.srv[gid] = self._leaders[gid]
                futmap.setdefault(id(fut), []).append((fr, slot))

    def _complete(self, fr, slot, fut, live, futmap) -> None:
        if fr.replies[slot] is not _UNSET:
            return  # late resolution of a slot a retry already answered
        v = fut.value
        if v is _DEAD:
            # Server killed under us: fail over NOW — and sync
            # last_remaining so a sibling slot resolving in the same
            # pass cannot flip the retry pass into its "actively
            # resolving, re-arm" branch and defer this rotation a
            # whole backoff interval.
            fr.retry_at = 0.0
            fr.last_remaining = fr.remaining
            return
        if type(v) is tuple and v and v[0] == ErrTxnLocked \
                and fr.ops[slot].kind not in wire.TXN_KINDS:
            # PLAIN op vs a prepared-transaction lock window (PR 12
            # flag f): requeue HERE instead of answering — a clerk that
            # never learned ErrTxnLocked would treat it as terminal.
            # Lock windows are short (prepare→resolve); re-submitting
            # the same (cid, cseq) shortly is dup-safe because the lock
            # reply is never recorded in the dup filter.  If the window
            # outlives the frame budget, the frame times out with the
            # standard RETRYABLE error — never a terminal lock reply.
            # Txn-kind ops pass through untouched: the txn clerk's
            # bounded lock_retries/deadlock breaker must SEE conflicts.
            fr.retry_at = min(fr.retry_at, time.monotonic() + 0.01)
            fr.last_remaining = fr.remaining
            return
        fr.replies[slot] = v
        fr.remaining -= 1
        if fut.tctx is not None:
            sp = _tracing.child("frontend.reply", parent=fut.tctx,
                                comp="frontend")
            if sp is not None:
                sp.end()
        if fr.remaining == 0:
            self._finish(fr, live, futmap)

    def _finish(self, fr, live, futmap) -> None:
        live.pop(id(fr), None)
        self._inflight -= len(fr.replies)
        for fut in fr.futs:
            self._unlink(futmap, fut, fr)
        scope = _opscope.enabled()
        t_ser = time.monotonic_ns() if scope else 0
        if fr.native:
            self._srv.send_reply_native(fr.conn_id, tuple(fr.replies),
                                        crc=fr.crc)
        else:
            payload = fr.replies[0] if fr.single else tuple(fr.replies)
            self._srv.send_reply(fr.conn_id, payload)
        if scope:
            # opscope flush stage, Python reply path: serialize+send of
            # this frame (one observation per FRAME, matching the C++
            # reply ring's per-reply accounting).
            _opscope.observe_flush(time.monotonic_ns() - t_ser)
        _M_OPS.inc(len(fr.replies))

    @staticmethod
    def _unlink(futmap, fut, fr) -> None:
        """Remove `fr`'s ownership entries for `fut` from the fut→slots
        map (leaving other frames' entries on a shared future intact)."""
        if fut is None:
            return
        ent = futmap.get(id(fut))
        if ent is not None:
            ent[:] = [p for p in ent if p[0] is not fr]
            if not ent:
                del futmap[id(fut)]

    def _abandon(self, fr, slot) -> None:
        """Stop the slot's last submit target re-proposing it."""
        gid = fr.gids[slot]
        servers = self.groups[gid]
        srv = servers[fr.srv.get(gid, 0) % len(servers)]
        op = fr.ops[slot]
        try:
            srv.abandon(op.cid, op.cseq)
        except RPCError:
            pass

    def _drop_frame(self, fr, live, futmap, msg) -> None:
        live.pop(id(fr), None)
        self._inflight -= len(fr.replies)
        scope = _opscope.enabled()
        for slot, fut in enumerate(fr.futs):
            if fut is None:
                continue
            self._unlink(futmap, fut, fr)
            if fr.replies[slot] is _UNSET:
                self._abandon(fr, slot)
                if scope:
                    # Terminal for this op: no fold will ever pop its
                    # stamps — drop them instead of leaning on the cap.
                    _opscope.drop(fr.ops[slot].cid)
        if fr.native:
            self._srv.send_error_native(fr.conn_id, msg)
        else:
            self._srv.send_error(fr.conn_id, msg)
        _M_TIMEOUTS.inc()

    def _retry_frame(self, fr, now, futmap) -> None:
        """Event-loop failover: abandon this frame's unresolved ops on
        the replica they were submitted to and re-submit them to the
        next one (same cid/cseq — the dup filter keeps retries
        at-most-once).  The retry interval doubles toward half the op
        budget."""
        ops, owners, gids = [], [], []
        for slot, op in enumerate(fr.ops):
            if fr.replies[slot] is _UNSET:
                self._unlink(futmap, fr.futs[slot], fr)
                self._abandon(fr, slot)
                ops.append(op)
                owners.append((fr, slot))
                gids.append(fr.gids[slot])
        if not ops:
            return
        _M_RETRIES.inc(len(ops))
        for gid in set(gids):  # rotate each involved group's leader
            self._leaders[gid] = fr.srv.get(gid, self._leaders[gid]) + 1
        fr.interval = min(fr.interval * 2.0, self.op_timeout / 2.0)
        fr.retry_at = now + fr.interval
        self._submit(ops, owners, gids, futmap)

    # ---------------------------------------------- native ingest engine

    def _mirror_ingest(self, ing) -> None:
        """Mirror the C++ decode counters into the registry (delta-inc,
        once per engine pass — and on demand when a fleet poller asks
        for `metrics`, so a quiet frontend's counters are never a pass
        stale) + the inflight gauge queue-growth watches."""
        with self._mirror_mu:
            st = ing.stats()
            last = self._ing_last
            d = st["frames"] - last["frames"]
            if d:
                _M_NI_FRAMES.inc(d)
            d = st["ops"] - last["ops"]
            if d:
                _M_NI_OPS.inc(d)
            d = st["bytes"] - last["bytes"]
            if d:
                _M_NI_BYTES.inc(d)
            d = st["ring_full"] - last["ring_full"]
            if d:
                _M_NI_FULL.inc(d)
            d = st["done_ops"] - last["done_ops"]
            if d:
                _M_OPS.inc(d)  # answered via the native reply ring
            self._ing_last = st
            _metrics.set_gauge("frontend.native_ingest.inflight_ops",
                               st["inflight_ops"])
            # opscope flush stage: the C++ reply ring's cumulative log2
            # histogram, delta-merged once per pass.  The snapshot
            # ALWAYS advances — the C++ side stamps unconditionally
            # (two clock reads + relaxed atomics per frame, far below
            # the A/B noise floor), so a disabled window's delta must
            # be DROPPED at re-enable, not lump-merged into the first
            # on-window pass as a phantom batch.
            cur = ing.scope_flush()
            if cur is not None:
                prev = self._flush_last
                if prev is not None and _opscope.enabled():
                    d = cur - prev
                    _opscope.merge_flush(d[:64], int(d[64]), int(d[65]))
                self._flush_last = cur

    def _metrics_rpc(self):
        """The `metrics` RPC: registry snapshot, with the native-ingest
        counters mirrored FIRST (pollers must not read a pass stale)."""
        if self._ing is not None:
            self._mirror_ingest(self._ing)
        return _metrics.snapshot()

    def _native_pass(self, ing, nframes, defer, now) -> None:
        """One engine pass over the zero-GIL ingest path: reap completed
        frames, drop intern refs behind the drain fence, rotate frames
        off dead servers, poll freshly decoded frames into columnar
        submissions, and run the event-loop retry/timeout curve — all
        without building a single per-op Python container."""
        for fid in ing.reap():
            nf = nframes.pop(fid, None)
            if nf is not None:
                defer.append(nf)
        if defer:
            # The decref fence: a frame's key/value interns drop only
            # once every server it was submitted to has materialized (or
            # provably never will) — columnar_drained is the per-server
            # ticket high-water the driver advances at proposal time.
            kept = []
            for nf in defer:
                if all(s.columnar_drained >= t or s.dead
                       for s, t in nf.tickets):
                    ing.decref_keys(nf.kid_arr)
                    ing.decref_vals(nf.vid_arr)
                else:
                    kept.append(nf)
            defer[:] = kept
        while True:  # killed servers: rotate their frames NOW
            try:
                srv = self._deadq.popleft()
            except IndexError:
                break
            for nf in nframes.values():
                if srv in nf.cur_srv.values():
                    nf.retry_at = 0.0
        new = None
        multi = len(self.groups) > 1
        route = self._route
        key_str = ing.key_str
        tr = _tracing.enabled()
        # Admission watermark over the native path: ops already held by
        # live native frames, sampled once per pass (C++ tracks the
        # authoritative count; the engine's view is one pass stale,
        # which the watermark's headroom below the ring cap absorbs).
        admitted = sum(nf.nops for nf in nframes.values())
        scope = _opscope.enabled()
        while True:
            got = ing.poll1()
            if got is None:
                break
            fid, conn_id, nops, tc, dl_ms, ts_ns, ka, ca, sa, kia, via = got
            nf = _NFrame(fid, conn_id, nops, tc, now, self.op_timeout,
                         deadline_ms=dl_ms)
            if scope:
                t_poll = time.monotonic_ns()
                # A stale .so reports no parse stamp (0): the poll
                # instant stands in and the poll edge reads 0.
                nf.ts0 = ts_ns or t_poll
                nf.tpoll = t_poll
            nf.kinds = ka.tolist()
            nf.cids = ca.tolist()
            nf.cseqs = sa.tolist()
            nf.key_ids = kia.tolist()
            nf.val_ids = via.tolist()
            nf.kid_arr = kia
            nf.vid_arr = via
            if admitted + nops > self.max_inflight:
                # Shed at the watermark (explicit retryable error) —
                # BEFORE the ring's hard bounce; the frame's interns
                # drop through the usual decref fence (no tickets).
                _M_SHED.inc(nops)
                ing.fail(fid, "frontend overloaded (shed): retry")
                defer.append(nf)
                continue
            admitted += nops
            if multi:
                try:
                    ng = len(self.groups)
                    gids = [route(key_str(k)) for k in nf.key_ids]
                    for gid in gids:
                        if not 0 <= gid < ng:
                            raise ValueError(
                                f"route() -> {gid} outside [0, {ng})")
                except Exception as e:  # noqa: BLE001 — bad frame ≠ dead loop
                    ing.fail(fid,
                             f"frontend: unroutable frame ({e!r:.100})")
                    defer.append(nf)  # no tickets: decref next pass
                    continue
                nf.gids = gids
                self._note_shards(gids)
            if tr and tc is not None:
                # The frame-scoped wire context fans out per op, same
                # span names as the Python decode path (tracing is the
                # sampled diagnostic mode — it may allocate).
                parent = _tracing.TraceContext(*tc)
                tcs = []
                for k in nf.key_ids:
                    sp = _tracing.child("frontend.submit", parent=parent,
                                        comp="frontend", key=key_str(k))
                    if sp is not None:
                        tcs.append((sp.trace_id, sp.span_id))
                        sp.end()
                    else:
                        tcs.append(None)
                nf.tcs = tcs
            nframes[fid] = nf
            _M_FRAMES.inc()
            _M_WIDTH.observe(nops)
            if new is None:
                new = []
            new.append((nf, None))
        if new:
            self._submit_native(ing, new, now)
        if nframes:
            now = time.monotonic()
            for nf in list(nframes.values()):
                if now < nf.retry_at and now < nf.deadline:
                    continue
                pend = ing.pending(nf.fid)
                npend = 0 if pend is None else len(pend)
                if npend == 0:
                    nf.retry_at = now + nf.interval  # completing: re-arm
                    continue
                idxs = pend.tolist()
                if now >= nf.deadline:
                    self._abandon_native(nf, idxs)
                    ing.fail(nf.fid,
                             "frontend: op timeout (no majority?)")
                    if scope:
                        # Terminal: these slots' stamps will never fold.
                        for i in idxs:
                            _opscope.drop(nf.cids[i])
                    _M_TIMEOUTS.inc()
                    continue
                if nf.retry_at > 0.0 and npend < nf.last_pending:
                    # Actively draining: never fail over mid-drain (the
                    # _Frame rule); retry_at == 0.0 is the dead-server
                    # override — rotate now regardless of progress.
                    nf.last_pending = npend
                    nf.retry_at = now + nf.interval
                    continue
                nf.last_pending = npend
                self._abandon_native(nf, idxs)
                _M_RETRIES.inc(npend)
                gset = {0} if not multi else {nf.gids[i] for i in idxs}
                for gid in gset:
                    self._leaders[gid] = \
                        nf.srv.get(gid, self._leaders[gid]) + 1
                nf.interval = min(nf.interval * 2.0, self.op_timeout / 2.0)
                nf.retry_at = now + nf.interval
                self._submit_native(ing, [(nf, idxs)], now)
        self._mirror_ingest(ing)

    def _submit_native(self, ing, parts, now) -> None:
        """parts: [(nframe, slot idxs | None=all)] — ONE columnar
        submit_batch per target group, concatenated across frames; dup
        hits answer straight back through the reply ring."""
        multi = len(self.groups) > 1
        buckets: dict[int, list] = {}
        for nf, idxs in parts:
            if idxs is None:
                idxs = range(nf.nops)
            if not multi:
                buckets.setdefault(0, []).append((nf, idxs))
            else:
                per: dict[int, list] = {}
                gids = nf.gids
                for i in idxs:
                    per.setdefault(gids[i], []).append(i)
                for gid, ii in per.items():
                    buckets.setdefault(gid, []).append((nf, ii))
        sink = self._csink
        scope = _opscope.enabled()
        for gid, bucket in buckets.items():
            block = _CBlock(ing)
            kinds, cids, cseqs = block.kinds, block.cids, block.cseqs
            kids, vids, tags = block.key_ids, block.val_ids, block.tags
            ts0 = tpolls = None
            if scope:
                block.ts0 = ts0 = []
                block.tpolls = tpolls = []
            tcs = None
            if any(nf.tcs is not None for nf, _ in bucket):
                block.tcs = tcs = []
            for nf, ii in bucket:
                base = nf.fid << 16
                fk, fc, fs = nf.kinds, nf.cids, nf.cseqs
                fki, fvi, ftc = nf.key_ids, nf.val_ids, nf.tcs
                ft0, ftp = nf.ts0, nf.tpoll
                for i in ii:
                    kinds.append(fk[i])
                    cids.append(fc[i])
                    cseqs.append(fs[i])
                    kids.append(fki[i])
                    vids.append(fvi[i])
                    tags.append(base + i)
                    if ts0 is not None:
                        # opscope ts columns: frame-level stamps
                        # broadcast per op (int appends, no objects).
                        ts0.append(ft0)
                        tpolls.append(ftp)
                    if tcs is not None:
                        tcs.append(ftc[i] if ftc is not None else None)
            servers = self.groups[gid]
            nsrv = len(servers)
            got = srv = None
            for _ in range(nsrv):
                srv = servers[self._leaders[gid] % nsrv]
                try:
                    got = srv.submit_columnar(block, range(len(tags)),
                                              sink)
                    break
                except RPCError:
                    self._leaders[gid] += 1
            if got is None:
                later = now + 0.05  # group dead right now: retry soon
                for nf, _ in bucket:
                    nf.retry_at = min(nf.retry_at, later)
                continue
            ticket, dup_tags, dup_reps = got
            _M_SUBMIT.observe(len(tags))
            for nf, _ in bucket:
                nf.srv[gid] = self._leaders[gid]
                nf.cur_srv[gid] = srv
                if ticket:
                    nf.tickets.append((srv, ticket))
            if dup_tags:
                sink.push(dup_tags, dup_reps)

    def _abandon_native(self, nf, idxs) -> None:
        """Drop the slots' columnar waiters on their last submit target
        (the failover/timeout prelude — same contract as _abandon)."""
        multi = len(self.groups) > 1
        per: dict[int, list] = {}
        for i in idxs:
            per.setdefault(nf.gids[i] if multi else 0, []).append(i)
        for gid, ii in per.items():
            srv = nf.cur_srv.get(gid)
            if srv is None:
                continue
            srv.abandon_columnar([nf.cids[i] for i in ii],
                                 [nf.cseqs[i] for i in ii])

    def _engine_loop(self) -> None:
        live: dict[int, _Frame] = {}
        futmap: dict[int, list] = {}
        nframes: dict[int, _NFrame] = {}  # native-ingest frames by fid
        defer: list = []                  # (nf) awaiting the decref fence
        ing = self._ing
        pending = self._pending
        doneq = self._doneq
        wake = self._wake
        while True:
            if ing is not None:
                # Native mode: ONE wait primitive — the ingest eventfd.
                # The C++ loop writes it per decoded frame; Python-side
                # producers (done sink, pickle frames, kill) write it via
                # _wake_native.  A short tick while work is in flight
                # drives the retry/reap/decref passes.
                busy = live or nframes or defer
                try:
                    r, _, _ = select.select([ing.fd], [], [],
                                            0.05 if busy else 2.0)
                    if r:
                        os.read(ing.fd, 8)
                        # Disarm AFTER the read: clearing first lets a
                        # producer's arm+write land between the two and
                        # be consumed with the flag still set — its next
                        # event would then wait out the whole idle
                        # timeout (a 2s latency spike, caught in review).
                        self._wake_armed = False
                except (OSError, ValueError):
                    self._wake_armed = False  # fd gone: kill in progress
            else:
                wake.wait(0.05 if live else None)
                wake.clear()
            if self._dead:
                for fr in list(live.values()):
                    self._drop_frame(fr, live, futmap, "frontend killed")
                if ing is not None:
                    # Fleet teardown (ISSUE 18): a dying frontend must
                    # not strand server-side state it owns.  (1) Drop
                    # every columnar waiter parked under OUR sink —
                    # ownership-guarded, so a sibling frontend's re-park
                    # of the same migrated (cid, cseq) survives.  The
                    # detached blocks still advance the drain-ticket
                    # fence at the driver's next proposal pass (skipped,
                    # not materialized).  (2) Release the intern refs of
                    # every live and fence-deferred frame NOW — safe:
                    # the waiters are gone, so no materialization will
                    # read the freed ids (and the driver's `key is None`
                    # guard covers any block already in flight).
                    sink = self._csink
                    for g in self.groups:
                        for s in g:
                            detach = getattr(s, "detach_columnar", None)
                            if detach is not None:
                                try:
                                    detach(sink)
                                except RPCError:
                                    pass
                    for nf in list(nframes.values()):
                        ing.fail(nf.fid, "frontend killed")
                        ing.decref_keys(nf.kid_arr)
                        ing.decref_vals(nf.vid_arr)
                    nframes.clear()
                    for nf in defer:
                        ing.decref_keys(nf.kid_arr)
                        ing.decref_vals(nf.vid_arr)
                    defer.clear()
                return
            now = time.monotonic()
            # ---- ingest: everything queued since the last pass becomes
            # ONE columnar submit_batch (one lock acquisition, one
            # consecutive seq block in the group-commit driver).
            if pending:
                batch_ops, owners, gids = [], [], []
                route = self._route
                multi = len(self.groups) > 1
                ngroups = len(self.groups)
                scope = _opscope.enabled()
                while True:
                    try:
                        (conn_id, ops_wire, wctx, single, native, meta,
                         t0) = pending.popleft()
                    except IndexError:
                        break
                    # EVERYTHING frame-derived stays inside the guard: a
                    # malformed payload (ops_wire not a sequence, bad op
                    # tuples, an out-of-range route result) must answer
                    # with an error, never kill the engine thread.
                    try:
                        nops = len(ops_wire)
                        crc = bool(meta and meta.get("crc"))
                        if not single and nops == 0:
                            # Degenerate empty batch: answer now — a
                            # frame with no ops would otherwise park in
                            # `live` forever (nothing ever resolves it)
                            # and desync the connection's reply FIFO.
                            if native:
                                self._srv.send_reply_native(conn_id, (),
                                                            crc=crc)
                            else:
                                self._srv.send_reply(conn_id, ())
                            continue
                        dl_ms = meta.get("deadline_ms") if meta else None
                        if self._inflight + nops > self.max_inflight:
                            # ADMISSION CONTROL (ISSUE 12): shed with an
                            # explicit retryable error BEFORE anything
                            # is proposed — overload must answer fast,
                            # not convert into timeouts.
                            _M_SHED.inc(nops)
                            raise RPCError(
                                "frontend overloaded (shed): retry")
                        fr = _Frame(conn_id, single, nops, now,
                                    self.op_timeout, native=native,
                                    deadline_ms=dl_ms, crc=crc)
                        fr.ops = [self._make_op(t, wctx) for t in ops_wire]
                        if multi:
                            fr.gids = [route(op.key) for op in fr.ops]
                            for gid in fr.gids:
                                if not 0 <= gid < ngroups:
                                    raise ValueError(
                                        f"route() -> {gid} outside "
                                        f"[0, {ngroups})")
                            self._note_shards(fr.gids)
                        else:
                            fr.gids = [0] * nops
                    except Exception as e:  # noqa: BLE001 — bad frame ≠ dead loop
                        # RPCError carries an intentional, client-facing
                        # message (shed / expired budget); anything else
                        # is a genuinely undecodable frame.
                        msg = str(e) if isinstance(e, RPCError) \
                            else f"frontend: undecodable op tuple " \
                                 f"({e!r:.100})"
                        if native:
                            self._srv.send_error_native(conn_id, msg)
                        else:
                            self._srv.send_error(conn_id, msg)
                        continue
                    _M_FRAMES.inc()
                    _M_WIDTH.observe(len(ops_wire))
                    self._inflight += nops
                    live[id(fr)] = fr
                    if scope and t0:
                        # Python decode path: frame-parse (enqueue) →
                        # engine poll, same stage names as C++ ingest.
                        _opscope.note_ingest_poll(
                            [op.cid for op in fr.ops], t0,
                            time.monotonic_ns())
                    for i, op in enumerate(fr.ops):
                        batch_ops.append(op)
                        owners.append((fr, i))
                        gids.append(fr.gids[i])
                if batch_ops:
                    self._submit(batch_ops, owners, gids, futmap)
            # ---- completions: the driver's one-sweep notify delivered
            # futures into the done queue via the sink hook.
            while True:
                try:
                    fut = doneq.popleft()
                except IndexError:
                    break
                for fr, slot in futmap.pop(id(fut), ()):
                    self._complete(fr, slot, fut, live, futmap)
            # ---- native ingest: reap / decref / poll / submit / retry
            if ing is not None:
                self._native_pass(ing, nframes, defer, now)
            # ---- retry/timeout pass (event-loop backoff, no sleeps)
            if live:
                now = time.monotonic()
                for fr in list(live.values()):
                    if not fr.remaining or now < fr.retry_at:
                        continue
                    if now >= fr.deadline:
                        self._drop_frame(fr, live, futmap,
                                         "frontend: op timeout "
                                         "(no majority?)")
                    elif fr.retry_at > 0.0 \
                            and fr.remaining < fr.last_remaining:
                        # The frame is actively resolving — under load a
                        # wide frame legitimately drains over several
                        # dispatches; failing over mid-drain would
                        # re-propose its tail for nothing.  retry_at ==
                        # 0.0 is the _DEAD override: a slot KNOWN to sit
                        # on a killed server rotates now, regardless of
                        # sibling progress in the same pass.
                        fr.last_remaining = fr.remaining
                        fr.retry_at = now + fr.interval
                    else:
                        fr.last_remaining = fr.remaining
                        self._retry_frame(fr, now, futmap)
            # Overload visibility: the Python-path inflight gauge (the
            # native path mirrors its own through _mirror_ingest), and
            # the C++ decode state machine's reject counter mirrored
            # into rpc.wire.rejected (delta-inc, one lock per pass).
            _M_INFLIGHT.set(self._inflight)
            _blackbox.stamp(self._bb_key, self._inflight)
            rej = getattr(self._srv, "wire_rejected", 0)
            if rej > self._rej_last:
                transport._M_WIRE_REJ.inc(rej - self._rej_last,
                                          key="native")
                self._rej_last = rej

    # ------------------------------------------- blocking fallback path

    def _serve_blocking(self, ops_wire, single):
        """transport.Server fallback: same wire semantics, thread-per-
        connection economics.  The whole frame is still ONE submit_batch
        per group; unresolved ops fail over across replicas within the
        op budget."""
        # Inflight visibility for the blocking path (ISSUE 20): the
        # engine loop stamps once per pass; here once per frame edge.
        # Telemetry-grade — racing += across connection threads may
        # transiently miscount, and the blackbox heartbeat only needs
        # the magnitude a victim died holding.
        self._inflight += len(ops_wire)
        _M_INFLIGHT.set(self._inflight)
        _blackbox.stamp(self._bb_key, self._inflight)
        try:
            return self._serve_blocking_inner(ops_wire, single)
        finally:
            self._inflight -= len(ops_wire)
            _M_INFLIGHT.set(self._inflight)
            _blackbox.stamp(self._bb_key, self._inflight)

    def _serve_blocking_inner(self, ops_wire, single):
        ops = [self._make_op(t, None) for t in ops_wire]
        if _opscope.enabled():
            # Blocking fallback (thread-per-connection transport): the
            # frame is decoded and consumed on one thread, so parse and
            # poll coincide — the stage-name SET stays identical.
            _opscope.note_ingest_poll([op.cid for op in ops],
                                      time.monotonic_ns(),
                                      time.monotonic_ns())
        multi = len(self.groups) > 1
        gids = [self._route(op.key) for op in ops] if multi \
            else [0] * len(ops)
        if multi:
            self._note_shards(gids)
        deadline = time.monotonic() + self.op_timeout
        replies = [_UNSET] * len(ops)
        todo = list(range(len(ops)))
        bo = Backoff()
        while todo:
            for gid in {gids[i] for i in todo}:
                idxs = [i for i in todo if gids[i] == gid]
                servers = self.groups[gid]
                nsrv = len(servers)
                futs = srv = None
                for _ in range(nsrv):
                    srv = servers[self._leaders[gid] % nsrv]
                    try:
                        futs = srv.submit_batch([ops[i] for i in idxs])
                        break
                    except RPCError:
                        self._leaders[gid] += 1
                if futs is None:
                    continue
                for i, fut in zip(idxs, futs):
                    v = fut.value \
                        if fut.wait(max(0.0, deadline - time.monotonic())) \
                        else _UNSET
                    if v is _UNSET or v is _DEAD:
                        try:
                            srv.abandon(ops[i].cid, ops[i].cseq)
                        except RPCError:
                            pass
                    elif type(v) is tuple and v and v[0] == ErrTxnLocked \
                            and ops[i].kind not in wire.TXN_KINDS:
                        # Lock-window requeue for plain ops (PR 12 flag
                        # f, blocking edition): keep the op in `todo` —
                        # the loop re-submits the same (cid, cseq) after
                        # the backoff; budget expiry raises the standard
                        # retryable timeout, never a terminal lock reply.
                        pass
                    else:
                        replies[i] = v
                        todo.remove(i)
            if todo:
                now = time.monotonic()
                if now >= deadline:
                    raise RPCError("frontend: op timeout (no majority?)")
                for gid in {gids[i] for i in todo}:
                    self._leaders[gid] += 1
                bo.sleep(deadline - now)
        return replies[0] if single else tuple(replies)

    def _fe_batch_blocking(self, ops):
        return self._serve_blocking(ops, single=False)

    def _get_blocking(self, key, cid, cseq):
        return self._serve_blocking((("get", key, "", cid, cseq),),
                                    single=True)

    def _put_append_blocking(self, kind, key, value, cid, cseq):
        return self._serve_blocking(((kind, key, value, cid, cseq),),
                                    single=True)

    # --------------------------------------------------------- lifecycle

    @property
    def rpc_count(self) -> int:
        return self._srv.rpc_count

    def set_unreliable(self, flag: bool) -> None:
        self._srv.set_unreliable(flag)

    def deafen(self) -> None:
        self._srv.deafen()

    def undeafen(self) -> None:
        self._srv.undeafen()

    def drain(self, timeout: float = 5.0) -> None:
        """SIGTERM-style graceful exit (the nemesis `fe_drain` action):
        stop accepting new dials, let the engine flush everything
        already admitted — parked columnar waiters included — then
        kill.  Clerks mid-stream on existing connections see their
        current frames answered and the next dial refused, which is the
        rotate-to-a-sibling signal; the wait is bounded, so a clerk
        that keeps streaming on a live connection cannot wedge the
        drain past `timeout`."""
        self.deafen()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            st = self.stats()["frontend"]
            ni = st["native_ingest"]
            if not (st["pending_frames"] or st["done_queue"]
                    or st["inflight_ops"]
                    or (ni.get("inflight_ops", 0)
                        if isinstance(ni, dict) else 0)):
                break
            time.sleep(0.02)
        self.kill()

    def kill(self) -> None:
        self._dead = True
        self._wake.set()
        if self._ing is not None:
            self._wake_armed = False
            self._wake_native()
        # Join the engine BEFORE tearing the server down: the engine's
        # last pass fails its native frames through the still-live ingest
        # handle (every NativeIngest call is also guarded on the server
        # lock, so late driver pushes after kill() are no-ops, never
        # use-after-free).
        if self._engine is not None:
            self._engine.join(timeout=5.0)
        self._srv.kill()


def shardkv_op(kind, key, value, cid, cseq, tc):
    """Op factory reusing the frontend per shardkv group (extra=None on
    client ops; the reconf path never travels this wire).  Txn phase
    ops (kind ∈ txnkv.TXN_KINDS, JSON payload in `value`) pass through
    unchanged — `supports_txn` below is what lets the frontend
    advertise the caps-gated `fe_txn` capability (ISSUE 13)."""
    from tpu6824.services.shardkv import Op as SOp

    return SOp(kind, key, value, cid, cseq, None, tc)


shardkv_op.supports_txn = True


# ---------------------------------------------------------------------------
# Client side


class FrontendClerk:
    """Blocking single-client clerk over the frontend wire — the
    reference clerk surface (get/put/append, at-most-once via cid/cseq),
    for harness/history use.  `addrs` lists the frontends (or plain
    kvpaxos endpoints) to rotate across; a peer that does not speak
    `fe_batch` is detected once ("no such rpc") and served single-op
    frames from then on — old↔new interop in one clerk."""

    def __init__(self, addrs, timeout: float = 10.0, wire_format="auto"):
        self.addrs = list(addrs)
        self.timeout = timeout
        self.cid = fresh_cid()
        self.cseq = 0
        self._conn: transport.FramedConn | None = None
        self._conn_addr = None
        self._legacy: set[str] = set()  # addrs that refused fe_batch
        # Versioned fe wire negotiation: "auto" probes each endpoint ONCE
        # via the fe_caps rpc ("no such rpc" = pickle peer); "native" /
        # "pickle" pin the format (tests, benches).  A probe that fails
        # on transport error is NOT cached — unreliable wire must not
        # permanently demote an endpoint.
        self.wire_format = wire_format
        self._fmt: dict[str, str] = {}
        # Per-endpoint capability dict from the fe_caps probe: which
        # caps-gated v1 extensions (deadline propagation, frame CRC)
        # are safe to send to this address (ISSUE 12).
        self._caps: dict[str, dict] = {}
        # The retry BUDGET rides the Backoff (services/common.py): a
        # clerk in a retry storm decays to the sustained token rate
        # instead of amplifying — 3×-collapse-by-retry is impossible by
        # construction, not by schedule tuning.
        self._backoff = Backoff()
        self._i = 0

    def _connect(self, addr):
        if self._conn is not None and self._conn_addr == addr:
            return self._conn
        if self._conn is not None:
            self._conn.close()
            self._conn = None
        self._conn = transport.FramedConn(addr, timeout=self.timeout)
        self._conn_addr = addr
        return self._conn

    def _teardown(self):
        if self._conn is not None:
            self._conn.close()
            self._conn = None
            self._conn_addr = None

    def _request(self, addr, frame):
        conn = self._connect(addr)
        try:
            ok, payload = conn.request(frame)
        except RPCError:
            self._teardown()
            raise
        if ok:
            return payload
        if isinstance(payload, BaseException):
            raise payload
        raise RPCError(f"{addr}: {payload}")

    def _request_native(self, addr, ops, tc=None, budget_s=None):
        conn = self._connect(addr)
        caps = self._caps.get(addr) or {}
        deadline_ms = None
        if budget_s is not None and caps.get("fe_deadline"):
            # Deadline propagation: the server stops working on this
            # frame once OUR remaining budget is gone (floored at 1ms —
            # 0 is the expired-on-arrival sentinel).
            deadline_ms = max(1, int(budget_s * 1000))
        try:
            conn.send_raw(wire.encode_batch(
                ops, tc=tc, deadline_ms=deadline_ms,
                crc=bool(caps.get("fe_crc"))))
            ok, payload = conn.recv()
        except RPCError:
            self._teardown()
            raise
        if ok:
            return payload
        raise RPCError(f"{addr}: {payload}")

    def _format_for(self, addr) -> str:
        """The frame format this endpoint speaks: pinned, cached, or
        probed once via fe_caps (one extra round-trip per endpoint).
        The caps dict also gates the v1 extension flags (deadline /
        crc) — "native"-pinned clerks that never probed simply send
        plain v1 frames."""
        if self.wire_format != "auto":
            return self.wire_format
        fmt = self._fmt.get(addr)
        if fmt is not None:
            return fmt
        try:
            caps = self._request(addr, ("fe_caps", ()))
            if isinstance(caps, dict) \
                    and caps.get("fe_wire") == wire.VERSION:
                fmt = "native"
                self._caps[addr] = caps
            else:
                fmt = "pickle"
        except RPCError as e:
            if "no such rpc" not in str(e):
                raise  # transport failure: do NOT cache a demotion
            fmt = "pickle"
        self._fmt[addr] = fmt
        return fmt

    def _call(self, op_tuple, timeout=None):
        """One logical op: send (retrying across addrs/reconnects with
        the SAME cseq — at-most-once rests on the server dup filter)."""
        deadline = time.monotonic() + timeout if timeout else None
        self._backoff.reset()
        sp = _tracing.span("clerk.op", comp="clerk", op=op_tuple[0],
                           key=op_tuple[1]) if _tracing.enabled() else None
        try:
            while True:
                addr = self.addrs[self._i % len(self.addrs)]
                # The budget that rides the frame header (deadline
                # propagation): our remaining deadline, else the
                # per-request socket budget.
                budget_s = (deadline - time.monotonic()) if deadline \
                    else self.timeout
                try:
                    if addr in self._legacy:
                        return self._single_op(addr, op_tuple, sp)
                    fmt = self._format_for(addr)
                    if sp is not None:
                        rsp = _tracing.child("rpc.call", parent=sp.ctx,
                                             comp="rpc")
                        ctx = (rsp.trace_id, rsp.span_id) \
                            if rsp is not None else None
                        try:
                            if fmt == "native":
                                try:
                                    replies = self._request_native(
                                        addr, (op_tuple,), tc=ctx,
                                        budget_s=budget_s)
                                except wire.CapacityError:
                                    # Op too big for the fe layout
                                    # (key > u16): this one request
                                    # rides the pickled frame instead.
                                    frame = (FE_BATCH, ((op_tuple,),))
                                    if ctx is not None:
                                        frame = frame + (ctx,)
                                    replies = self._request(addr, frame)
                            else:
                                frame = (FE_BATCH, ((op_tuple,),))
                                if ctx is not None:
                                    frame = frame + (ctx,)
                                replies = self._request(addr, frame)
                        finally:
                            if rsp is not None:
                                rsp.end()
                    elif fmt == "native":
                        try:
                            replies = self._request_native(
                                addr, (op_tuple,), budget_s=budget_s)
                        except wire.CapacityError:
                            replies = self._request(
                                addr, (FE_BATCH, ((op_tuple,),)))
                    else:
                        replies = self._request(addr,
                                                (FE_BATCH, ((op_tuple,),)))
                    rep = replies[0]
                    if not (isinstance(rep, tuple) and rep
                            and rep[0] == ErrTxnLocked):
                        return rep
                    # ErrTxnLocked (ISSUE 13): the key is held by a
                    # prepared cross-group transaction — paced retry
                    # with the SAME cseq (the lock reply is never
                    # recorded in the dup filter), same endpoint; falls
                    # through to the backoff below.
                except RPCError as e:
                    if "no such rpc" in str(e):
                        self._legacy.add(addr)
                        continue  # same addr, classic frames
                    self._i += 1
                now = time.monotonic()
                if deadline and now >= deadline:
                    raise RPCError("clerk timeout")
                self._backoff.sleep(deadline - now if deadline else None)
        finally:
            if sp is not None:
                sp.end()

    def _txn_caps(self, addr) -> dict:
        """The endpoint's capability dict, probed on demand — txn ops
        are STRICTLY caps-gated in BOTH frame forms (an endpoint that
        never advertised `fe_txn` must never see a txn kind, pickled or
        binary: a pre-txn apply loop has no branch for it).  Reuses
        `_format_for`'s probe (one fe_caps round-trip per endpoint);
        a non-dict answer is NOT cached, so a transient oddity never
        pins an endpoint as transaction-less forever."""
        self._format_for(addr)  # fills _caps for fe-wire endpoints
        caps = self._caps.get(addr)
        if caps is None:
            got = self._request(addr, ("fe_caps", ()))
            if isinstance(got, dict):
                self._caps[addr] = caps = got
            else:
                caps = {}
        return caps

    def txn_call(self, op_tuple, timeout=None):
        """One 2PC phase op (kind ∈ wire.TXN_KINDS) through the
        frontend wire → the (err, val) reply (ISSUE 13).  Caps-gated in
        both directions: an endpoint is sent txn frames — binary kind
        codes on the fe wire, or the pickled fe_batch form — ONLY after
        its fe_caps advertised `fe_txn`; pre-txn and pre-frontend
        endpoints refuse loudly, and old clerks never emit the kinds at
        all (interop unchanged both ways)."""
        deadline = time.monotonic() + timeout if timeout else None
        self._backoff.reset()
        while True:
            addr = self.addrs[self._i % len(self.addrs)]
            budget_s = (deadline - time.monotonic()) if deadline \
                else self.timeout
            try:
                if addr in self._legacy:
                    raise RPCError(
                        f"{addr}: endpoint predates the frontend wire "
                        "— no transaction support")
                caps = self._txn_caps(addr)
                if not caps.get("fe_txn"):
                    raise RPCError(
                        f"{addr}: endpoint does not advertise fe_txn "
                        "— no transaction support")
                if self.wire_format != "pickle" \
                        and caps.get("fe_wire") == wire.VERSION:
                    try:
                        replies = self._request_native(
                            addr, (op_tuple,), budget_s=budget_s)
                    except wire.CapacityError:
                        # Op does not FIT the binary layout (key >
                        # u16): this request rides the pickled frame —
                        # the _call fallback, same contract.
                        replies = self._request(
                            addr, (FE_BATCH, ((op_tuple,),)))
                else:
                    replies = self._request(addr,
                                            (FE_BATCH, ((op_tuple,),)))
                return replies[0]
            except RPCError as e:
                if "no such rpc" in str(e):
                    self._legacy.add(addr)
                    raise RPCError(
                        f"{addr}: endpoint predates the frontend wire "
                        "— no transaction support") from e
                if "no transaction support" in str(e):
                    raise
                self._i += 1
            now = time.monotonic()
            if deadline and now >= deadline:
                raise RPCError("txn clerk timeout")
            self._backoff.sleep(deadline - now if deadline else None)

    def _single_op(self, addr, t, sp):
        """Classic single-op frame against a legacy (pre-frontend)
        endpoint — new clerk → old server interop."""
        kind, key, value, cid, cseq = t
        if kind == "get":
            frame = ("get", (key, cid, cseq))
        else:
            frame = ("put_append", (kind, key, value, cid, cseq))
        if sp is not None:
            rsp = _tracing.child("rpc.call", parent=sp.ctx, comp="rpc")
            if rsp is not None:
                frame = frame + ((rsp.trace_id, rsp.span_id),)
            try:
                return self._request(addr, frame)
            finally:
                if rsp is not None:
                    rsp.end()
        return self._request(addr, frame)

    def _next(self) -> int:
        self.cseq += 1
        return self.cseq

    def get(self, key: str, timeout=None) -> str:
        err, val = self._call(("get", key, "", self.cid, self._next()),
                              timeout=timeout)
        return val if err == OK else ""

    def put(self, key: str, value: str, timeout=None):
        return self._call(("put", key, value, self.cid, self._next()),
                          timeout=timeout)

    def append(self, key: str, value: str, timeout=None):
        return self._call(("append", key, value, self.cid, self._next()),
                          timeout=timeout)

    def close(self) -> None:
        self._teardown()


class FrontendStream:
    """W logical clients × C connections driven from ONE thread — the
    bench-side of the batched request path.  Each connection owns a
    disjoint slice of the logical clients, split into `depth` COHORTS
    that pipeline on the wire: while cohort A's frame is deciding on the
    fabric, cohort B's frame is already buffered at the server (the
    epoll loop serves it the moment A's reply flushes), so a connection
    keeps the inject pipeline full instead of idling a dispatch per
    round-trip.  Every logical client still has at most ONE op in
    flight (its cohort's frame), so the per-client sequential invariant
    (checkAppends) holds exactly.  Reconnects resend the in-flight
    frames, same cseqs — at-most-once via the dup filter.

    FLEET mode (ISSUE 18): `addr` may be a LIST of frontend addresses.
    Connections spread round-robin across the fleet, and a torn
    connection redials the NEXT address — so the resent in-flight
    frames (same cseqs) land on a DIFFERENT frontend after a frontend
    death, and at-most-once must hold through the replicated dup
    table, not any frontend-local state.  Wire format and extension
    caps are tracked PER ADDRESS (a mixed fleet stays correct).

    Reply matching relies on the SERVER's per-connection FIFO: both
    transports serve one frame per connection at a time (the C++ loop's
    `handed_off` flag / the Python loop's sequential `_serve_conn`), so
    frame B is not even dispatched until frame A's reply has flushed —
    replies can never cross on one connection, and the in-flight
    deque's popleft always names the frame being answered."""

    def __init__(self, addr, conns: int, width: int,
                 op_timeout: float = 10.0, depth: int = STREAM_DEPTH,
                 wire_format: str = "auto"):
        assert conns >= 1 and width >= conns * depth
        self.addrs = [addr] if isinstance(addr, str) else list(addr)
        assert self.addrs
        self.addr = self.addrs[0]  # single-frontend back-compat alias
        self.op_timeout = op_timeout
        self.depth = depth
        # "auto": one fe_caps probe on the first dial PER ADDRESS
        # decides whether frames go out in the versioned fe wire layout
        # (zero-GIL server decode) or as classic pickled fe_batch
        # tuples.  The probe's caps dict also gates the v1 extension
        # flags (deadline propagation + frame CRC, ISSUE 12); pinned
        # "native" sends plain v1 frames (no probe ran, so no extension
        # is known-safe).
        self._pin = {"native": True, "pickle": False,
                     "auto": None}[wire_format]
        self._native: dict = {a: self._pin for a in self.addrs}
        self._caps: dict = {a: {} for a in self.addrs}
        self.clients = [[fresh_cid(), 0] for _ in range(width)]
        # conn ci, cohort k owns clients {c : c ≡ ci·depth+k (mod C·D)}.
        self._cohorts = [
            [list(range(ci * depth + k, width, conns * depth))
             for k in range(depth)]
            for ci in range(conns)
        ]

    def run_appends(self, key_of, value_of, stop, on_done=None,
                    lat_sink=None, max_per_client: int | None = None):
        """Each logical client c appends value_of(c, i) to key_of(c),
        i = 0, 1, ... until `stop` is set (or `max_per_client` ops).
        `on_done(n)` fires per reply frame; `lat_sink` collects per-op
        frame round-trip seconds.  Returns total ops completed."""
        import select as _select

        nconns = len(self._cohorts)
        conns: list = [None] * nconns
        # Fleet routing state: each connection's current position in the
        # frontend list.  Initial dials spread round-robin; a REdial
        # advances the position first, so a connection torn by a
        # frontend death resends its in-flight frames (same cseqs) to a
        # DIFFERENT frontend — the at-most-once migration path.
        addr_i = list(range(nconns))
        cur_addr = [self.addrs[ci % len(self.addrs)] for ci in range(nconns)]
        opened = [False] * nconns
        # Per-client next-op index.
        progress = {c: 0 for c in range(len(self.clients))}
        # Per-conn FIFO of in-flight cohorts: (k, ops, members, t_sent);
        # the server answers frames in order, so popleft matches.
        inflight: list[deque] = [deque() for _ in range(nconns)]
        total = 0
        alive = [True] * nconns
        done_conns = 0

        def build_ops(members):
            ops, took = [], []
            for c in members:
                i = progress[c]
                if max_per_client is not None and i >= max_per_client:
                    continue
                cid, cseq = self.clients[c]
                ops.append(("append", key_of(c), value_of(c, i), cid,
                            cseq + 1))
                took.append(c)
            return tuple(ops), took

        def send_frame(ci, ops):
            addr = cur_addr[ci]
            if self._native[addr]:
                caps = self._caps[addr]
                dl = max(1, int(self.op_timeout * 1000)) \
                    if caps.get("fe_deadline") else None
                conns[ci].send_raw(wire.encode_batch(
                    ops, deadline_ms=dl, crc=bool(caps.get("fe_crc"))))
            else:
                conns[ci].send((FE_BATCH, (ops,)))

        def send_cohort(ci, k):
            """Build + send cohort k's next frame; False when the cohort
            is drained (max_per_client reached for all members)."""
            ops, took = build_ops(self._cohorts[ci][k])
            if not ops:
                return False
            send_frame(ci, ops)
            inflight[ci].append((k, ops, took, time.monotonic()))
            return True

        def open_conn(ci):
            """(Re)dial and (re)send everything in flight, in order —
            same cseqs, so replays are dup-filtered server-side.  A
            redial after a failure ROTATES to the next frontend of the
            fleet (single-frontend streams rotate onto the same addr)."""
            if opened[ci]:
                addr_i[ci] += 1
            opened[ci] = True
            addr = self.addrs[addr_i[ci] % len(self.addrs)]
            cur_addr[ci] = addr
            conns[ci] = transport.FramedConn(addr,
                                             timeout=self.op_timeout)
            if self._native[addr] is None:
                # One fe_caps probe per address decides its wire format.
                ok, caps = conns[ci].request(("fe_caps", ()))
                self._native[addr] = bool(ok and isinstance(caps, dict)
                                          and caps.get("fe_wire")
                                          == wire.VERSION)
                if self._native[addr]:
                    self._caps[addr] = caps
            requeue = list(inflight[ci])
            inflight[ci].clear()
            for k, ops, took, _ in requeue:
                send_frame(ci, ops)
                inflight[ci].append((k, ops, took, time.monotonic()))
            if not requeue:
                started = False
                for k in range(self.depth):
                    started = send_cohort(ci, k) or started
                return started
            return True

        def conn_done(ci):
            nonlocal done_conns
            if alive[ci]:
                alive[ci] = False
                done_conns += 1
                if conns[ci] is not None:
                    conns[ci].close()
                    conns[ci] = None

        bo = Backoff()
        for ci in range(nconns):
            try:
                if not open_conn(ci):
                    conn_done(ci)
            except RPCError:
                if conns[ci] is not None:
                    conns[ci].close()
                conns[ci] = None
        try:
            while done_conns < nconns:
                if stop is not None and stop.is_set():
                    break
                # Redial torn connections (resends in-flight frames).
                for ci in range(nconns):
                    if alive[ci] and conns[ci] is None:
                        try:
                            if not open_conn(ci):
                                conn_done(ci)
                        except RPCError:
                            if conns[ci] is not None:
                                conns[ci].close()
                            conns[ci] = None
                live_socks = {conns[ci].fileno(): ci
                              for ci in range(nconns)
                              if alive[ci] and conns[ci] is not None}
                if not live_socks:
                    if all(not alive[ci] or conns[ci] is None
                           for ci in range(nconns)):
                        bo.sleep(0.2)  # every dial failing: pace redials
                    continue
                r, _, _ = _select.select(list(live_socks), [], [], 0.2)
                now = time.monotonic()
                for fd in r:
                    ci = live_socks[fd]
                    try:
                        ok, payload = conns[ci].recv()
                    except RPCError:
                        conns[ci].close()
                        conns[ci] = None  # redial + resend above
                        continue
                    if not ok:
                        # Frontend-side op failure (e.g. no majority
                        # within its budget): tear + resend — the dup
                        # filter keeps the replay at-most-once.
                        conns[ci].close()
                        conns[ci] = None
                        continue
                    k, ops, took, t_sent = inflight[ci].popleft()
                    n = len(took)
                    for c in took:  # commit: advance each member once
                        self.clients[c][1] += 1
                        progress[c] += 1
                    total += n
                    if lat_sink is not None:
                        lat_sink.extend([now - t_sent] * n)
                    if on_done is not None and n:
                        on_done(n)
                    if stop is not None and stop.is_set():
                        continue
                    try:
                        if not send_cohort(ci, k) and not inflight[ci]:
                            conn_done(ci)
                    except RPCError:
                        if conns[ci] is not None:
                            conns[ci].close()
                        conns[ci] = None
                # Frame-level timeout: tear + resend (dup-filtered).
                for ci in range(nconns):
                    q = inflight[ci]
                    if alive[ci] and q and conns[ci] is not None \
                            and now - q[0][3] > self.op_timeout:
                        conns[ci].close()
                        conns[ci] = None
        finally:
            for c in conns:
                if c is not None:
                    c.close()
        return total
