"""viewservice — non-replicated view server for primary/backup replication.

Capability parity with the reference Lab 2A (`viewservice/server.go`,
`viewservice/client.go`, `viewservice/common.go:36-48`): numbered
`View{viewnum, primary, backup}`; servers Ping every PING_INTERVAL; a server
missing DEAD_PINGS pings is dead; a restarted server (Ping(0) from the
current primary) is treated as dead; the view NEVER advances until the
current primary has acked (pinged with) the current viewnum.

Also fixes the reference's compile bug (`viewservice/server.go:158` assigns an
undeclared identifier) by not porting it.

This is pure control plane — no device work (SURVEY §2.2: "tiny host FSM").
"""

from __future__ import annotations

import threading
import time
from typing import NamedTuple

from tpu6824.utils.errors import RPCError
from tpu6824.utils import crashsink

PING_INTERVAL = 0.1  # viewservice/common.go:43 (100ms)
DEAD_PINGS = 5       # viewservice/common.go:48


class View(NamedTuple):
    viewnum: int
    primary: str
    backup: str


class ViewServer:
    RPC_METHODS = ["ping", "get", "get_rpccount"]  # wire surface (rpc.Server)

    def __init__(self, ping_interval: float = PING_INTERVAL):
        self.mu = threading.Lock()
        self.view = View(0, "", "")
        self.acked = False          # primary has pinged the current viewnum
        self.ttl: dict[str, int] = {}      # server -> remaining pings
        self.idle: set[str] = set()        # pinged, not in the view
        self.restarted: set[str] = set()   # primary pinged 0 (crash+restart)
        self.dead = False
        self.rpccount = 0
        self.ping_interval = ping_interval
        self._ticker = threading.Thread(
            target=crashsink.guarded(self._tick_loop, "viewservice-ticker"),
            daemon=True)
        self._ticker.start()

    # ------------------------------------------------------------- RPCs

    def ping(self, me: str, viewnum: int) -> View:
        """viewservice/server.go:56-112."""
        with self.mu:
            if self.dead:
                raise RPCError("dead")
            self.rpccount += 1
            self.ttl[me] = DEAD_PINGS

            if self.view.viewnum == 0:
                # First pinger becomes primary of view 1.
                self.view = View(1, me, "")
                self.acked = False
            elif me == self.view.primary:
                if viewnum == 0 and self.view.viewnum > 0:
                    # Restarted primary: treat as dead (restart detection,
                    # server.go:72-78) — but only once acked.
                    self.restarted.add(me)
                elif viewnum == self.view.viewnum:
                    self.acked = True
            elif me == self.view.backup:
                if viewnum == 0 and self.view.viewnum > 0:
                    self.restarted.add(me)
            else:
                self.idle.add(me)
            self._advance_locked()
            return self.view

    def get(self) -> View:
        """viewservice/server.go:117-127 — no liveness side effects."""
        with self.mu:
            if self.dead:
                raise RPCError("dead")
            self.rpccount += 1
            return self.view

    # ------------------------------------------------------------- FSM

    def _alive_locked(self, who: str) -> bool:
        return who != "" and self.ttl.get(who, 0) > 0 and who not in self.restarted

    def _advance_locked(self):
        """View-transition rules (viewservice/server.go:157-221): only when
        the current view is acked may it change."""
        if self.view.viewnum == 0 or not self.acked:
            return
        v = self.view
        primary, backup = v.primary, v.backup
        changed = False
        if not self._alive_locked(primary):
            # Promote backup; a dead/never-acked primary without backup
            # wedges the service forever (by design).
            if self._alive_locked(backup):
                primary, backup, changed = backup, "", True
            else:
                return
        if not self._alive_locked(backup):
            if backup != "":
                backup, changed = "", True
            cand = next(
                (s for s in sorted(self.idle)
                 if self._alive_locked(s) and s != primary),
                "",
            )
            if cand:
                backup, changed = cand, True
                self.idle.discard(cand)
        if changed:
            self.restarted.clear()
            self.view = View(v.viewnum + 1, primary, backup)
            self.acked = False

    def _tick_loop(self):
        while not self.dead:
            time.sleep(self.ping_interval)
            self.tick()

    def tick(self):
        """viewservice/server.go:199-221 — decrement TTLs, maybe advance."""
        with self.mu:
            if self.dead:
                return
            for s in list(self.ttl):
                self.ttl[s] -= 1
            self.idle = {s for s in self.idle if self._alive_locked(s)}
            self._advance_locked()

    def kill(self):
        with self.mu:
            self.dead = True

    def get_rpccount(self) -> int:
        with self.mu:
            return self.rpccount


class Clerk:
    """viewservice/client.go:56-88."""

    def __init__(self, me: str, vs: ViewServer):
        self.me = me
        self.vs = vs

    def ping(self, viewnum: int) -> View:
        return self.vs.ping(self.me, viewnum)

    def get(self) -> View:
        return self.vs.get()

    def primary(self) -> str:
        try:
            return self.get().primary
        except RPCError:
            return ""
