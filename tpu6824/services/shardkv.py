"""shardkv — sharded, reconfiguring, Paxos-replicated KV store (the capstone).

Capability parity with the reference Lab 4B (`shardkv/server.go`,
`shardkv/client.go`): many replica groups, each a Paxos RSM; the shardmaster
assigns shards; groups reconfigure at config boundaries, transferring shard
state while staying linearizable.

Design points carried over from the reference (by behavior, not code):
  - Reconfigurations walk configs strictly one at a time, in order
    (`shardkv/server.go:377-392,488-493`).
  - The receiving group's *proposing* replica pulls the shard snapshot once,
    then ships it THROUGH the Paxos log inside the Reconf op, so every replica
    of the group applies identical state (`shardkv/server.go:301-322` +
    catchUp `:162-184`).
  - Donors refuse `transfer_state` with ErrNotReady until they have reached
    the config themselves (`shardkv/server.go:340-349`), giving a monotone
    config lattice.
  - Per-client duplicate filters travel WITH the shard data
    (`XState{KVStore, MRRSMap, Replies}`, `shardkv/server.go:71-102`), so
    at-most-once survives re-sharding.

TPU-shaped difference: every replica group (and the shardmaster) lives on ONE
shared PaxosFabric — each group is a lane of the batched (G, I, P) consensus
kernel, so a 100-group deployment advances in the same lockstep kernel steps
as a 1-group one.

Deliberate in-process divergence: `transfer_state` acquires the donor's lock
with a timeout (cross-group pulls in-process could otherwise deadlock where
the reference's cross-process RPCs cannot).
"""

from __future__ import annotations

import threading
import time
from typing import NamedTuple

from tpu6824.core.fabric import PaxosFabric, WindowFullError
from tpu6824.core.peer import Fate, PaxosPeer
from tpu6824.obs import blackbox as _blackbox
from tpu6824.obs import opscope as _opscope
from tpu6824.obs import tracing as _tracing
from tpu6824.ops.hashing import NSHARDS, key2shard
from tpu6824.services import horizon as _horizon
from tpu6824.services import shardmaster, txnkv
from tpu6824.services.common import (
    Backoff,
    DecidedTap,
    FlakyNet,
    fresh_cid,
    pull_from_peers,
)
from tpu6824.services.kvpaxos import _DEAD, _Fut
from tpu6824.services.shardmaster import Config
from tpu6824.utils import crashsink
from tpu6824.utils.locks import new_rlock
from tpu6824.utils.errors import (
    OK,
    ErrNoKey,
    ErrNotReady,
    ErrTxnLocked,
    ErrWrongGroup,
    RPCError,
)


class Op(NamedTuple):
    kind: str  # 'get' | 'put' | 'append' | 'reconf' | txnkv.TXN_KINDS
    key: str
    value: str
    cid: str  # string CIDs, as on the reference wire (shardkv/common.go:23)
    cseq: int
    extra: object  # reconf: (Config, xstate)
    # tpuscope trace metadata: the submitting RPC leg's
    # (trace_id, span_id), stamped at _serve when tracing is enabled
    # (None otherwise); never part of op identity (dedup is (cid, cseq)).
    tc: tuple | None = None


class XState(NamedTuple):
    """Transferable shard state (shardkv/server.go:71-102).

    `txn` (ISSUE 13, arxiv 1906.01365): the prepared-lock-table rows
    whose keys fall in the migrating shards — (tid, coord_gid,
    coord_srv_names, sub-ops) — so a shard migrating MID-COMMIT carries
    its 2PC state to the new owner, which re-locks the keys and
    resolves the inherited prepares against the coordinator record
    before they can serve conflicting ops."""

    kv: tuple  # ((key, value), ...)
    dup: tuple  # ((cid, (cseq, reply)), ...)
    txn: tuple = ()  # ((tid, coord_gid, coord_srv, sub-ops), ...)


class ShardKVServer:
    RPC_METHODS = ["get", "put_append", "transfer_state",
                   "txn_op", "txn_status", "snapshot_fetch"]  # wire surface

    def __init__(
        self,
        fabric: PaxosFabric | None,
        fg: int,
        gid: int,
        me: int,
        sm_clerk_servers,
        directory: dict,
        op_timeout: float = 8.0,
        start_ticker: bool = True,
        sm_poll_interval: float = 0.05,
        px=None,
        snapshot_every: int | None = None,
        persist_dir: str | None = None,
        dup_retire_ops: int | None = None,
    ):
        """`px` overrides the consensus backend (PaxosPeer contract) — the
        batched fabric by default, or the decentralized wire backend via
        `make_host_group`."""
        if fabric is None and px is None:
            raise ValueError("ShardKVServer needs a fabric or an explicit px")
        self.px = px if px is not None else PaxosPeer(fabric, fg, me)
        self.gid = gid
        self.me = me
        # meshfab shard binding (see kvpaxos): the mesh shard owning
        # this group's fabric columns, 0 off-mesh — folded drains tag
        # their dispatch edge with it.
        _fab = getattr(self.px, "fabric", None)
        self.shard = (_fab.shard_of(fg)
                      if _fab is not None and hasattr(_fab, "shard_of")
                      else 0)
        self.name = f"g{gid}-{me}"
        # Crash forensics (ISSUE 20): drain exits stamp the applied
        # high-water into the blackbox heartbeat table (one GIL-atomic
        # dict store per drain, key precomputed here) — the shardkv half
        # of the postmortem's last-decided-seq evidence.
        self._bb_key = f"shardkv.applied.g{gid}.s{me}"
        self.directory = directory
        directory[self.name] = self
        self.smck = shardmaster.Clerk(sm_clerk_servers)
        # Budget contract: the RSM handler legitimately rides mu across
        # a full paxos agreement (see _sync), so the hold bound is the
        # op deadline plus drain slack — not the leaf-lock default.
        self.mu = new_rlock("shardkv.mu", hold_budget_s=op_timeout + 2.0)
        self.kv: dict[str, str] = {}
        self.dup: dict[str, tuple[int, object]] = {}
        # txnkv (ISSUE 13): replicated 2PC state, mutated ONLY in _apply
        # (deterministic across replicas).  txn_prepared: tid → entry
        # (coord gid/names, buffered sub-ops, reads, inherited flag,
        # monotonic stamp — the stamp only PACES the resolver, never
        # decides an outcome); txn_locks: key → tid; txn_decisions: the
        # coordinator-role commit records (write-once, first writer
        # wins); txn_done: finished-txn idempotency records (capped,
        # trimmed in apply order).  `_test_partial_commit` is the
        # PR 3-style atomicity fault hook: commit drops this group's
        # writes so the transactional checker can prove it catches a
        # real half-applied transaction; never set outside tests.
        self.txn_prepared: dict[str, dict] = {}
        self.txn_locks: dict[str, str] = {}
        self.txn_decisions: dict[str, str] = {}
        self.txn_done: dict[str, str] = {}
        # horizon (ISSUE 14) — all RSM state (mutated only in _apply /
        # the replicated compact entry, identical on every replica):
        # dup_seq: cid → applied seq of its newest op (the dup-table
        # retirement clock); txn_decision_seq/waits/resolved: the
        # resolution-tied decision-GC bookkeeping (see txnkv);
        # txn_done_seq: the done-row linger clock that replaced PR 12's
        # naive size cap.  `_txn_acks_owed`/`_trimmed_tids` are
        # VOLATILE (send-queue + observability ring, never RSM state).
        self.dup_seq: dict[str, int] = {}
        self.txn_decision_seq: dict[str, int] = {}
        self.txn_decision_waits: dict[str, set] = {}
        self.txn_resolved: dict[str, int] = {}
        self.txn_done_seq: dict[str, int] = {}
        self._txn_acks_owed: dict[tuple, tuple] = {}
        self._trimmed_tids: dict[str, bool] = {}
        self.dup_retire_ops = (_horizon.DUP_RETIRE_OPS
                               if dup_retire_ops is None
                               else int(dup_retire_ops))
        self.horizon = _horizon.Snapshotter(every=snapshot_every,
                                            persist_dir=persist_dir)
        self._behind_min = 0  # FORGOTTEN floor awaiting snapshot-install
        self._cmp_cseq = 0
        if self.horizon.enabled():
            _horizon.register_tracker(self, self._horizon_rows)
        self.txn_resolve_after = txnkv.RESOLVE_AFTER
        self.txn_resolve_inherited = 0.05
        self.txn_abort_after = txnkv.ABORT_AFTER
        self._test_partial_commit = False
        self.config: Config = Config.initial()
        self.applied = -1
        self.op_timeout = op_timeout
        self.sm_poll_interval = sm_poll_interval
        self._cfg_cache: dict[int, Config] = {}  # immutable once created
        self._cfg_target = 0  # highest config num seen from the sm group
        self.dead = False
        # Decided-delta feed (fabric backends): the tick/catch-up drain
        # consumes the fabric's once-per-group decided fan-out instead of
        # walking status() seq by seq; see kvpaxos for the full rationale.
        # Batched-submit seam (the clerk frontend reuses one frontend per
        # group over this): futures + queue + a LAZY group-commit driver —
        # nothing spawns and the blocking `_serve` path is untouched until
        # the first submit_batch() call.
        self._waiters: dict[tuple, _Fut] = {}  # (cid, cseq) -> fut
        self._subq: list[Op] = []
        self._inflight: dict[int, Op] = {}     # seq -> my undecided proposal
        self._next_seq = 0
        # opscope (ISSUE 15): per-drain accumulator of resolved-waiter
        # cids — a list only while _drain_decided's feed pass runs (the
        # ticker's _sync walk resolves outside the request hot path and
        # is deliberately not folded).
        self._scope_acc = None
        self._wake = threading.Event()
        self._client_driver = None
        sub_fn = getattr(self.px, "subscribe_decided", None)
        sub = sub_fn(wake=self._wake_submit) if sub_fn is not None else None
        self._tap = DecidedTap(sub) if sub is not None else None
        self._ticker = None
        if start_ticker:
            self._start_ticker()

    def _wake_submit(self):
        # Decided-feed wake hook: shared by the ticker cadence (which
        # ignores it) and the lazy submit driver (which parks on it).
        if not self._wake.is_set():
            self._wake.set()

    def _start_ticker(self):
        self._ticker = threading.Thread(
            target=crashsink.guarded(self._tick_loop, "shardkv-ticker"),
            daemon=True)
        self._ticker.start()

    # ----------------------------------------------------------- RSM apply

    def _owns(self, key: str) -> bool:
        return self.config.shards[key2shard(key)] == self.gid

    def _apply(self, op: Op):
        if op.kind == "reconf":
            cfg, xstate = op.extra
            if cfg.num != self.config.num + 1:
                return None  # stale/duplicate reconf entry
            for k, v in xstate.kv:
                self.kv[k] = v
            for cid, (cseq, reply) in xstate.dup:
                seen, _ = self.dup.get(cid, (-1, None))
                if cseq > seen:
                    self.dup[cid] = (cseq, reply)
                    # Imported rows restart their retirement clock at
                    # the reconf entry's own seq — deterministic.
                    self.dup_seq[cid] = self.applied + 1
            # Reconfiguration safety (ISSUE 13): for shards this group
            # IMPORTS, the incoming prepared-lock rows are the
            # authoritative surviving set — stale local portions from a
            # previous ownership stint are pruned FIRST (a migrate-away
            # → resolve-elsewhere → migrate-back cycle must not
            # re-apply old buffered writes), then the migrated-in
            # prepares re-lock their keys under this (new) owner; the
            # resolver consults their coordinator records.
            imported = {s for s in range(NSHARDS)
                        if cfg.shards[s] == self.gid
                        and self.config.shards[s] != self.gid}
            self.config = cfg
            if imported:
                txnkv.prune_for_import(self, imported)
            if getattr(xstate, "txn", ()):
                txnkv.install_inherited(self, xstate.txn)
            return None

        seen, reply = self.dup.get(op.cid, (-1, None))
        if op.cseq <= seen:
            return self._resolve(op, reply)
        if op.kind == "compact":
            # Replicated compaction entry (ISSUE 14): retire dup rows,
            # done rows, and fully-resolved decision records at ONE log
            # position so every replica trims identically.
            txnkv.apply_compact(self, self.applied + 1)
            reply = (OK, "")
            self.dup[op.cid] = (op.cseq, reply)
            self.dup_seq[op.cid] = self.applied + 1
            return self._resolve(op, reply)
        if op.kind in txnkv.TXN_KINDS:
            # 2PC ops: per-payload-key ownership (prepare) / tid-keyed
            # state (commit/abort/coord — the fix-en-route semantics:
            # a prepared transaction outlives the shard map, so its
            # finish ops never answer ErrWrongGroup from a routing
            # key).  Retryable outcomes stay OUT of the dup filter.
            reply, record = txnkv.apply_txn(self, op)
            if record:
                self.dup[op.cid] = (op.cseq, reply)
                self.dup_seq[op.cid] = self.applied + 1
            if op.tc is not None:
                _tracing.complete("service.apply", op.tc[0], op.tc[1],
                                  time.monotonic_ns(), comp="shardkv",
                                  gid=self.gid, me=self.me, kind=op.kind)
            return self._resolve(op, reply)
        if not self._owns(op.key):
            # NOT recorded in the dup filter: the client will retry at the
            # right group with the same cseq (shardkv/server.go:205-242).
            return self._resolve(op, (ErrWrongGroup, ""))
        if op.key in self.txn_locks:
            # Key locked by a prepared cross-group transaction: answer
            # the retryable lock error, NOT recorded — the client
            # re-sends the same cseq through its Backoff budget once
            # the lock releases (commit/abort/resolver).
            txnkv._M_LOCK_CONFLICTS.inc()
            return self._resolve(op, (ErrTxnLocked, ""))
        if op.kind == "get":
            # tpusan: ok(host-walk-in-decided-path) — shardkv ops
            # interleave with reconfig/migration/txn entries that
            # mutate arbitrary key ranges host-side (shard handoff
            # installs whole dicts); the devapply columnar contract
            # covers the kvpaxos hot path first (ROADMAP: extend once
            # shard state machines pin their stores).
            reply = (OK, self.kv[op.key]) if op.key in self.kv else (ErrNoKey, "")
        elif op.kind == "put":
            self.kv[op.key] = op.value
            reply = (OK, "")
        elif op.kind == "append":
            self.kv[op.key] = self.kv.get(op.key, "") + op.value
            reply = (OK, "")
        self.dup[op.cid] = (op.cseq, reply)
        self.dup_seq[op.cid] = self.applied + 1
        if op.tc is not None:  # tpuscope: apply-side span for traced ops
            _tracing.complete("service.apply", op.tc[0], op.tc[1],
                              time.monotonic_ns(), comp="shardkv",
                              gid=self.gid, me=self.me, key=op.key)
        return self._resolve(op, reply)

    def _resolve(self, op: Op, reply):
        """Resolve any frontend waiter parked on this (cid, cseq) —
        including the ErrWrongGroup/dup fast paths, which a frontend op
        must hear about (its clerk re-queries the config and retries)."""
        if self._waiters:
            fut = self._waiters.pop((op.cid, op.cseq), None)
            if fut is not None:
                fut.set(reply)
                if self._scope_acc is not None:
                    self._scope_acc.append(op.cid)
        return reply

    def _requeue_lost_locked(self, v) -> None:
        """Post-apply at self.applied: if my frontend proposal for this
        slot lost to `v`, re-queue it (its waiter is still parked) —
        kvpaxos._pop_lost_inflight_locked, shardkv flavor."""
        if not self._inflight:
            return
        mine = self._inflight.pop(self.applied, None)
        if (mine is not None
                and (not isinstance(v, Op)
                     or (mine.cid, mine.cseq) != (v.cid, v.cseq))
                and (mine.cid, mine.cseq) in self._waiters):
            self._subq.append(mine)

    def _drain_decided(self):
        tap = self._tap
        if tap is not None:
            # Feed path: apply the tap's contiguous run as a batch, one
            # Done() high-water call per drain.  _sync may have applied
            # seqs out from under the tap (it walks status() while
            # proposing) — discard those before reassembling.
            base0 = self.applied + 1
            tap.discard_through(self.applied)
            # opscope (ISSUE 15): same stage names as the kvpaxos
            # driver — decide-feed delivery / apply / reply stamps per
            # drain, resolved cids accumulated by _resolve and folded
            # once (shardkv resolves waiters inline during apply, so
            # its reply edge reads ~0 by construction — the waterfall
            # SHAPE differs, the stage-name set does not).
            scope = _opscope.enabled()
            t_decide = 0
            if scope:
                self._scope_acc = []
            while True:
                run = tap.pop_ready(self.applied)
                if not run:
                    if tap.should_probe_min(self.applied):
                        mn = self.px.min()
                        if mn > self.applied + 1:
                            if self._can_install():
                                # Behind the GC horizon with donors
                                # available: flag for the ticker's
                                # OUTSIDE-mu snapshot-install pass
                                # instead of skipping state (ISSUE 14).
                                self._behind_min = mn
                                break
                            # GC'd past us before we subscribed (warm
                            # boot); skip the forgotten span.
                            self.applied = mn - 1
                            tap.discard_through(self.applied)
                            continue
                    break
                if t_decide == 0:
                    t_decide = time.monotonic_ns()
                for v in run:
                    self._apply(v)
                    self.applied += 1
                    self._requeue_lost_locked(v)
            if scope:
                acc, self._scope_acc = self._scope_acc, None
                if acc:
                    t_now = time.monotonic_ns()
                    _opscope.fold(acc, t_decide or t_now, t_now, t_now,
                                  shard=self.shard)
            if self.applied >= base0:
                self.px.done(self.applied)
                _blackbox.stamp(self._bb_key, self.applied)
            return
        while True:
            fate, v = self.px.status(self.applied + 1)
            if fate == Fate.DECIDED:
                self._apply(v)
                self.applied += 1
                self._requeue_lost_locked(v)
                self.px.done(self.applied)
            elif fate == Fate.FORGOTTEN:
                if self._can_install():
                    self._behind_min = max(self.px.min(),
                                           self.applied + 2)
                    _blackbox.stamp(self._bb_key, self.applied)
                    return
                self.applied += 1
                self._inflight.pop(self.applied, None)
            else:
                _blackbox.stamp(self._bb_key, self.applied)
                return

    def _sync(self, want: Op):
        deadline = time.monotonic() + self.op_timeout
        started = False
        while True:
            if self.dead:
                raise RPCError("server killed")
            seq = self.applied + 1
            fate, v = self.px.status(seq)
            if fate == Fate.DECIDED:
                reply = self._apply(v)
                self.applied = seq
                self._requeue_lost_locked(v)
                self.px.done(seq)
                if (
                    isinstance(v, Op)
                    and v.kind == want.kind
                    and v.cid == want.cid
                    and v.cseq == want.cseq
                ):
                    return reply
                started = False
                continue
            if not started:
                try:
                    self.px.start(seq, want)
                    started = True
                except WindowFullError:
                    pass
            if time.monotonic() >= deadline:
                raise RPCError("op timeout (no majority?)")
            # tpusan: ok(lock-blocking-reachable) — the RSM handler
            # holds mu across paxos agreement by design (ops serialize
            # on the server mutex, reference lab semantics); the 2ms
            # nap paces the decide poll, bounded by the deadline above.
            time.sleep(0.002)

    # ------------------------------------------------- horizon (ISSUE 14)

    def _group_peers(self):
        """Live directory entries of this group's OTHER replicas —
        in-process servers or socket proxies alike (selected by name,
        the g<gid>-<p> convention; diskv inherits this)."""
        prefix = f"g{self.gid}-"
        for name, srv in list(self.directory.items()):
            if name != self.name and name.startswith(prefix):
                yield name, srv

    def _can_install(self) -> bool:
        # Like kvpaxos's peers guard: horizon on AND at least one
        # same-group sibling that can serve snapshots — otherwise keep
        # the legacy skip-forward so a donor-less replica never wedges
        # behind the horizon waiting for a pull that cannot happen.
        return self.horizon.enabled() and any(
            hasattr(srv, "snapshot_fetch")
            for _n, srv in self._group_peers())

    def _compact_due(self) -> bool:
        # tpusan: ok(unlocked-shared-state) — ticker-side cadence
        # probe: monotonic counters written under mu on the apply
        # path; a stale read only delays compaction one tick, and the
        # replicated compact op re-reads state under apply anyway.
        due = self.dup_retire_ops, self.txn_decision_seq, self.txn_done_seq
        return any(due)

    def _horizon_rows(self) -> dict:
        # Runs on the pulse sampler thread (tracker registry) while the
        # apply path mutates these tables under mu — len() of a dict
        # mid-resize is not safe without the GIL, and mu is cheap at
        # sampling cadence.
        with self.mu:
            d = {"kv_rows": len(self.kv), "dup_rows": len(self.dup),
                 "txn_prepared_rows": len(self.txn_prepared),
                 "txn_decision_rows": len(self.txn_decisions),
                 "txn_done_rows": len(self.txn_done)}
        fab = getattr(self.px, "fabric", None)
        if fab is not None:
            d["window_live_slots"] = fab.live_slots
            d["window_key"] = id(fab)
        return d

    def _snapshot_blob_locked(self) -> dict:
        """Deep-enough copy of the applied state (mutable leaves
        copied UNDER mu — serialization runs off it, and the live
        dicts keep mutating while pickle walks the blob otherwise)."""
        return {
            "applied": self.applied,
            "kv": dict(self.kv),
            "dup": dict(self.dup),
            "dup_seq": dict(self.dup_seq),
            "config": self.config,
            "txn_prepared": {
                tid: {**e, "reads": dict(e["reads"]),
                      "origins": set(e.get("origins") or (self.gid,))}
                for tid, e in self.txn_prepared.items()},
            "txn_locks": dict(self.txn_locks),
            "txn_decisions": dict(self.txn_decisions),
            "txn_decision_seq": dict(self.txn_decision_seq),
            "txn_decision_waits": {t: set(s) for t, s in
                                   self.txn_decision_waits.items()},
            "txn_resolved": dict(self.txn_resolved),
            "txn_done": dict(self.txn_done),
            "txn_done_seq": dict(self.txn_done_seq),
        }

    def _adopt_blob_locked(self, applied: int, blob: dict) -> None:
        self.kv = dict(blob["kv"])
        self.dup = dict(blob["dup"])
        self.dup_seq = dict(blob.get("dup_seq", {}))
        self.config = blob["config"]
        now = time.monotonic()
        self.txn_prepared = {
            tid: {**e, "t": now}  # re-arm resolver pacing, never fate
            for tid, e in blob.get("txn_prepared", {}).items()}
        self.txn_locks = dict(blob.get("txn_locks", {}))
        self.txn_decisions = dict(blob.get("txn_decisions", {}))
        self.txn_decision_seq = dict(blob.get("txn_decision_seq", {}))
        self.txn_decision_waits = {
            t: set(s) for t, s in blob.get("txn_decision_waits",
                                           {}).items()}
        self.txn_resolved = dict(blob.get("txn_resolved", {}))
        self.txn_done = dict(blob.get("txn_done", {}))
        self.txn_done_seq = dict(blob.get("txn_done_seq", {}))
        self.applied = applied
        for seq in [s for s in self._inflight if s <= applied]:
            del self._inflight[seq]
        # Waiters whose ops the snapshot already covers resolve from
        # the installed dup table.
        for key in list(self._waiters):
            cid, cseq = key
            seen, reply = self.dup.get(cid, (-1, None))
            if cseq <= seen:
                self._waiters.pop(key).set(reply)
        if self._tap is not None:
            self._tap.discard_through(applied)
        self._next_seq = max(self._next_seq, applied + 1)
        # Reseed the compact-proposal counter from the installed dup
        # table (see kvpaxos._adopt_blob_locked): a restored replica's
        # own cmp row must not dup-swallow its future compacts.
        seen, _ = self.dup.get(f"cmp-{self.gid}-{self.me}", (-1, None))
        self._cmp_cseq = max(self._cmp_cseq, seen)

    def _catchup_attempt_once(self) -> str:
        floor = self._behind_min - 1
        behind = False
        candidates = 0
        for _name, peer in self._group_peers():
            fetch = getattr(peer, "snapshot_fetch", None)
            if fetch is None or getattr(peer, "dead", False):
                continue
            candidates += 1
            st, applied, blob = _horizon.install_from_peer(fetch, floor)
            if st == "ok":
                with self.mu:
                    if not self.dead and applied > self.applied:
                        self._adopt_blob_locked(applied, blob)
                self.px.done(self.applied)
                return "ok"
            if st == "behind":
                behind = True
        if candidates == 0:
            # Every sibling vanished (or can't serve snapshots) since
            # the drain flagged us: nothing to pull, EVER — report
            # "behind" so the caller's legacy skip-forward keeps the
            # replica living instead of wedging on retries.
            return "behind"
        return "behind" if behind else "unreachable"

    def _catchup_pass(self) -> None:
        """Ticker-side snapshot-install (OUTSIDE mu; the tick cadence
        is the retry loop — the shared behind/unreachable discipline
        from services.common)."""
        st = pull_from_peers(self._catchup_attempt_once, deadline_s=0.0,
                             is_dead=lambda: self.dead)
        if st == "ok":
            self._behind_min = 0
            self._wake_submit()
        elif st == "behind":
            with self.mu:
                if self._behind_min > self.applied + 1:
                    self.applied = self._behind_min - 1
                    for seq in [s for s in self._inflight
                                if s <= self.applied]:
                        del self._inflight[seq]
                    if self._tap is not None:
                        self._tap.discard_through(self.applied)
            self._behind_min = 0

    def _maybe_snapshot(self) -> None:
        hz = self.horizon
        if not hz.due(self.applied):
            return
        with self.mu:
            if self.dead:
                return
            applied = self.applied
            if applied <= hz.last_applied:
                return
            blob = self._snapshot_blob_locked()
        hz.publish(applied, blob)
        if self._compact_due():
            # tpusan: ok(unlocked-shared-state) — _cmp_cseq is touched
            # only on this ticker thread, which is also the only
            # snapshot adopter (_catchup_pass → _adopt_blob_locked):
            # same-thread single-writer, mu would add nothing.
            self._cmp_cseq += 1
            try:
                self.submit_batch((Op(
                    "compact", "", "", f"cmp-{self.gid}-{self.me}",
                    self._cmp_cseq, None),))
            except RPCError:
                self._cmp_cseq -= 1

    def snapshot_fetch(self, floor: int, off: int = 0, n: int | None = None):
        """The snapshot-install RPC route — lock-free donor serving
        from the last published (immutable) snapshot; see kvpaxos."""
        if self.dead:
            raise RPCError("dead")
        return self.horizon.chunk(floor, off, n,
                                  donor_applied=self.applied)

    # ----------------------------------------------------------- reconfig

    def _tick_loop(self):
        """shardkv/server.go:488-493: periodic catch-up + config walk.

        Log drain (apply decided ops, advance Done so the window GC can
        recycle) runs every 50ms; the shardmaster poll — a LOGGED Query op
        on the sm group — only every `sm_poll_interval` (the reference
        polls at 250ms; large deployments raise it so G groups x R
        replicas of pollers don't saturate the sm log)."""
        last_sm = -float("inf")
        while not self.dead:
            time.sleep(0.05)
            try:
                now = time.monotonic()
                poll = now - last_sm >= self.sm_poll_interval
                if poll:
                    last_sm = now
                # poll=False still WALKS toward the last known target at
                # drain cadence (donor-not-ready retries stay fast) but
                # sends no new Query ops to the sm group — G x R pollers
                # must not saturate the sm log between poll intervals.
                self.tick(poll=poll)
                # txnkv resolver (ISSUE 13): settle aged/inherited
                # prepared transactions against their coordinator
                # records.  Runs OUTSIDE the mutex and outside _apply
                # by construction (the blocking-commit-wait rule).
                # tpusan: ok(unlocked-shared-state) — cadence probe:
                # a stale read skips one resolve pass; resolve_pass
                # does its real reads under the proper discipline.
                if self.txn_prepared:
                    txnkv.resolve_pass(self)
                # horizon (ISSUE 14): participant acks → coordinator,
                # snapshot-install catch-up when a drain found us
                # behind the GC horizon, and the snapshot cadence —
                # all OUTSIDE the mutex on this ticker.
                if self._txn_acks_owed:
                    txnkv.ack_pass(self)
                if self._behind_min:
                    self._catchup_pass()
                if self.horizon.enabled():
                    self._maybe_snapshot()
            except RPCError:
                continue  # shardmaster unreachable: retry next loop

    def _query_cfg(self, n: int) -> Config:
        """Config n, from the immutable-config cache when possible — walk
        retries (donor gating) must not re-Query the sm group per attempt."""
        cfg = self._cfg_cache.get(n)
        if cfg is None:
            cfg = self.smck.query(n, timeout=2.0)
            self._cfg_cache[n] = cfg
        return cfg

    def tick(self, poll: bool = True) -> bool:
        """One catch-up + config walk (shardkv/server.go:377-392).

        With poll=True, asks the sm group for the latest config number
        first; with poll=False, only walks toward the last known target
        (no sm Query traffic beyond uncached config bodies).  True iff
        the walk reached the target."""
        with self.mu:
            if self.dead:
                return True
            self._drain_decided()
            cur = self.config.num
        if self._behind_min:
            # Behind the GC horizon: the config walk would _sync at a
            # FORGOTTEN seq and spin out the whole op_timeout under mu
            # — let the ticker's catch-up pass install first.
            return False
        if poll:
            try:
                self._cfg_target = max(
                    self._cfg_target, self.smck.query(-1, timeout=2.0).num)
            except RPCError:
                return False
        for n in range(cur + 1, self._cfg_target + 1):
            with self.mu:
                if self.dead:
                    return True
                self._drain_decided()
                if self._behind_min:
                    return False  # install first; walk resumes after
                if self.config.num >= n:
                    self._cfg_cache.pop(n, None)
                    continue
                try:
                    # tpusan: ok(lock-blocking-reachable) — the config
                    # walk serializes against apply under mu by design
                    # (reconfiguration is a mutex-held state-machine
                    # step); the clerk query is deadline-bounded.
                    cfg = self._query_cfg(n)
                except RPCError:
                    return False
                if not self._reconfigure(cfg):
                    return False  # donor not ready; retry next tick
                self._cfg_cache.pop(n, None)
        return True

    def _reconfigure(self, cfg: Config) -> bool:
        """Pull newly-owned shards from their previous owners, then log the
        Reconf op carrying the merged snapshot (shardkv/server.go:301-322)."""
        old = self.config
        need: dict[int, list[int]] = {}  # old_gid -> [shard,...]
        for s in range(NSHARDS):
            if (
                cfg.shards[s] == self.gid
                and old.shards[s] != self.gid
                and old.shards[s] != shardmaster.UNASSIGNED
            ):
                need.setdefault(old.shards[s], []).append(s)

        kv_merge: dict[str, str] = {}
        dup_merge: dict[int, tuple[int, object]] = {}
        txn_merge: dict[str, tuple] = {}  # tid -> (coord, coord_srv, ops)
        for old_gid, shards_list in need.items():
            got = self._pull_shards(old, old_gid, cfg.num, shards_list)
            if got is None:
                return False
            for k, v in got.kv:
                kv_merge[k] = v
            for cid, (cseq, reply) in got.dup:
                seen, _ = dup_merge.get(cid, (-1, None))
                if cseq > seen:
                    dup_merge[cid] = (cseq, reply)
            for row in getattr(got, "txn", ()):
                tid, coord, coord_srv, tops = row[0], row[1], row[2], row[3]
                origins = txnkv._row_origins(row, old_gid)
                prev = txn_merge.get(tid)
                if prev is not None:  # portions from two donors: union
                    tops = tuple(dict.fromkeys(prev[2] + tuple(tops)))
                    origins |= prev[3]
                txn_merge[tid] = (coord, tuple(coord_srv), tuple(tops),
                                  origins)

        xstate = XState(
            kv=tuple(sorted(kv_merge.items())),
            # Type-robust deterministic order: frontend-submitted ops
            # carry INT cids (fresh_cid) while this wire's native clerks
            # use strings — a mixed dup table must still sort (a plain
            # sorted() raised TypeError and killed the ticker the first
            # time a frontend-fed group reconfigured; fix en route,
            # ISSUE 13).
            dup=tuple(sorted(dup_merge.items(),
                             key=lambda kv: (str(type(kv[0])),
                                             repr(kv[0])))),
            txn=tuple(sorted(
                (tid, c, cs, ops, tuple(sorted(origins)))
                for tid, (c, cs, ops, origins) in txn_merge.items())),
        )
        op = Op("reconf", "", "", f"reconf-{cfg.num}", cfg.num, (cfg, xstate))
        try:
            self._sync(op)
        except RPCError:
            return False
        return True

    def _pull_shards(self, old_cfg: Config, old_gid: int, confign: int, shards_list):
        """requestShard (shardkv/server.go:324-338): try every server of the
        donor group until one hands over the state."""
        names = old_cfg.groups_dict().get(old_gid, ())
        for name in names:
            srv = self.directory.get(name)
            if srv is None:
                continue
            try:
                return srv.transfer_state(confign, tuple(shards_list))
            except RPCError:
                continue
        return None

    def transfer_state(self, confign: int, shards_list: tuple) -> XState:
        """Donor side (shardkv/server.go:340-367).  ErrNotReady until this
        group has itself reached `confign` (so it no longer serves the
        shards)."""
        if self.dead:
            raise RPCError("dead")
        if not self.mu.acquire(timeout=1.0):
            raise RPCError("donor busy")  # breaks in-process pull cycles
        try:
            if self.config.num < confign:
                raise RPCError(ErrNotReady)
            kv = tuple(
                (k, v) for k, v in self.kv.items() if key2shard(k) in shards_list
            )
            dup = tuple(self.dup.items())
            # Prepared-lock-table rows for the migrating shards ride
            # along (ISSUE 13): the new owner re-locks and resolves
            # them against the coordinator record.  The donor KEEPS its
            # copy (like kv) — it no longer serves these keys, and its
            # own resolver settles the stale entry the same way.
            return XState(kv=kv, dup=dup,
                          txn=txnkv.export_prepared(self, shards_list))
        finally:
            self.mu.release()

    # ------------------------------------------------- batched submit seam
    # The clerk frontend's surface (services/frontend.py, op_factory=
    # shardkv_op): futures resolved by whichever drain applies the op
    # (ticker, _sync walk, or the lazy driver below).  The blocking _serve
    # path and its tests are untouched — nothing here runs until the
    # first submit_batch.

    def submit_batch(self, ops, sink=None) -> list:
        """Enqueue client ops under one lock acquisition; returns their
        futures (dup and wrong-group ops resolve immediately).  Same
        contract as KVPaxosServer.submit_batch."""
        futs = []
        parked = [] if _opscope.enabled() else None
        with self.mu:
            if self.dead:
                raise RPCError("dead")
            if self._client_driver is None:
                self._start_client_driver_locked()
            for op in ops:
                seen, reply = self.dup.get(op.cid, (-1, None))
                if op.cseq <= seen:
                    fut = _Fut()
                    if sink is not None:
                        fut.sink = sink
                    fut.set(reply)
                elif op.kind not in txnkv.TXN_KINDS \
                        and op.kind != "compact" \
                        and not self._owns(op.key):
                    # Ownership fast-path for PLAIN ops only: 2PC ops
                    # judge ownership per payload key (prepare) or by
                    # tid (commit/abort/coord) at apply — the
                    # fix-en-route semantics (ISSUE 13); compact
                    # entries are group-local maintenance with no key.
                    fut = _Fut()
                    if sink is not None:
                        fut.sink = sink
                    fut.set((ErrWrongGroup, ""))
                else:
                    key = (op.cid, op.cseq)
                    fut = self._waiters.get(key)
                    if fut is None:
                        fut = _Fut()
                        if sink is not None:
                            fut.sink = sink
                        self._waiters[key] = fut
                        self._subq.append(op)
                        if parked is not None:
                            parked.append(op.cid)
                    elif sink is not None and fut.sink is not sink:
                        # Re-point a parked waiter at the submitting
                        # frontend (last-writer-wins): a clerk retry that
                        # migrated to a different frontend of the fleet
                        # must be heard where the clerk listens now.
                        fut.sink = sink
                futs.append(fut)
            if parked:
                _opscope.note_park(parked, time.monotonic_ns())
        self._wake_submit()
        return futs

    def abandon(self, cid, cseq) -> None:
        """Drop the waiter for (cid, cseq): the frontend gave up on this
        replica (the dup filter keeps any retry at-most-once)."""
        with self.mu:
            self._waiters.pop((cid, cseq), None)

    def _start_client_driver_locked(self) -> None:
        self._client_driver = threading.Thread(
            target=crashsink.guarded(self._client_drive_loop,
                                     "shardkv-client-driver"),
            daemon=True)
        self._client_driver.start()

    def _collect_client_props_locked(self):
        props = []
        nxt = max(self._next_seq, self.applied + 1)
        for op in self._subq:
            if (op.cid, op.cseq) not in self._waiters:
                continue  # abandoned / resolved meanwhile
            seen, _ = self.dup.get(op.cid, (-1, None))
            if op.cseq <= seen:
                continue
            props.append((nxt, op))
            self._inflight[nxt] = op
            nxt += 1
        self._subq = []
        self._next_seq = nxt
        if props and _opscope.enabled():
            _opscope.note_materialize_many(
                [op.cid for _s, op in props], time.monotonic_ns())
        return props

    def _client_drive_loop(self):
        """Group-commit driver for frontend-submitted ops — the kvpaxos
        driver's shape on shardkv's RSM: drain the decided feed, propose
        everything queued as one consecutive seq block, let _apply
        resolve the waiters.  Reconf ops keep flowing through the
        ticker's _sync walk concurrently; losing a slot to one simply
        re-queues the client op."""
        px = self.px
        start_many = getattr(px, "start_many", None)
        bo = Backoff(fixed_sleep=0.02)
        while True:
            self._wake.wait(0.05)
            self._wake.clear()
            try:
                with self.mu:
                    if self.dead:
                        return
                    self._drain_decided()
                    props = self._collect_client_props_locked()
                if props:
                    try:
                        if start_many is not None:
                            start_many(props)
                        else:
                            for i, (s, v) in enumerate(props):
                                try:
                                    px.start(s, v)
                                except WindowFullError as e:
                                    e.index = i
                                    raise
                        if _opscope.enabled():
                            _opscope.note_dispatch_many(
                                [op.cid for _s, op in props],
                                time.monotonic_ns())
                    except WindowFullError as e:
                        with self.mu:
                            idx = len(props) if e.index is None else e.index
                            for seq, op in props[idx:]:
                                self._inflight.pop(seq, None)
                                self._subq.append(op)
                            if idx < len(props):
                                self._next_seq = props[idx][0]
                bo.reset()
            except RPCError:
                bo.sleep()
            except Exception as e:  # noqa: BLE001 — singleton thread
                crashsink.record("shardkv-client-driver", e, fatal=False)
                time.sleep(0.02)

    # ----------------------------------------------------------- RPC surface

    def get(self, key: str, cid: str, cseq: int):
        return self._serve(Op("get", key, "", cid, cseq, None))

    def put_append(self, key: str, kind: str, value: str, cid: str, cseq: int):
        return self._serve(Op(kind, key, value, cid, cseq, None))

    def txn_op(self, kind: str, key: str, value: str, cid: str, cseq: int):
        """2PC phase surface (ISSUE 13): kind ∈ txnkv.TXN_KINDS, `key`
        is the routing key (never an ownership claim), `value` the JSON
        payload.  Same blocking `_serve` path as every clerk op."""
        if kind not in txnkv.TXN_KINDS:
            raise RPCError(f"not a txn op kind: {kind!r}")
        return self._serve(Op(kind, key, value, cid, cseq, None))

    def txn_status(self, tid: str):
        """Coordinator-record read: the recorded decision for `tid`, or
        None.  Lock-free on purpose — decisions are write-once (a stale
        read can only under-report, never lie), and a resolver polling
        a BUSY coordinator must not convoy behind its mutex (the
        blocking-commit-wait shape)."""
        if self.dead:
            raise RPCError("dead")
        # tpusan: ok(unlocked-shared-state) — see docstring: decisions
        # are write-once, a stale read only under-reports, and the
        # trim sentinel below catches the one dangerous miss.
        d = self.txn_decisions.get(tid)
        if d is None and tid in self._trimmed_tids:
            txnkv._M_TRIMMED_CONSULTS.inc()  # trim-safety sentinel
        return d

    def _serve(self, op: Op):
        # tpuscope: stamp the caller's trace context into the proposed
        # value (the clerk/rpc leg set it current; see kvpaxos for the
        # full span chain — shardkv stamps + emits the apply span only).
        if _tracing.enabled():
            sp = _tracing.child("service.submit", comp="shardkv",
                                key=op.key, gid=self.gid)
            if sp is not None:
                op = op._replace(tc=(sp.trace_id, sp.span_id))
                sp.end()
        with self.mu:
            if self.dead:
                raise RPCError("dead")
            seen, reply = self.dup.get(op.cid, (-1, None))
            if op.cseq <= seen:
                return reply
            if op.kind not in txnkv.TXN_KINDS and not self._owns(op.key):
                return (ErrWrongGroup, "")
            return self._sync(op)

    def kill(self):
        with self.mu:
            self.dead = True
            for fut in self._waiters.values():
                fut.set(_DEAD)
            self._waiters.clear()
            if self._tap is not None:
                self._tap.close()
        _horizon.unregister_tracker(self)
        self._wake.set()
        self.px.kill()


class Clerk:
    """shardkv/client.go:89-163: route by key2shard through the latest config;
    on ErrWrongGroup or dead group, re-Query and retry with the same cseq."""

    def __init__(self, sm_servers, directory: dict, net: FlakyNet | None = None):
        self.smck = shardmaster.Clerk(sm_servers)
        self.directory = directory
        self.net = net or FlakyNet()
        # CID is a STRING on this wire (shardkv/common.go:23) — and string
        # cids keep the dup-filter/XState key type uniform across the gob
        # endpoints, the wire consensus backend, and in-process clerks.
        self.cid = str(fresh_cid())
        self.cseq = 0
        self.mu = threading.Lock()
        self.config = Config.initial()
        # Retry pacing: jittered exponential backoff (base 2ms, cap
        # 100ms); TPU6824_CLERK_BACKOFF=fixed keeps this clerk's original
        # flat 20ms between config re-queries.
        self._backoff = Backoff(fixed_sleep=0.02)

    def _next(self):
        with self.mu:
            self.cseq += 1
            return self.cseq

    def _loop(self, fn_name, key, *args, timeout=None):
        cseq = self._next()
        deadline = time.monotonic() + timeout if timeout else None
        self._backoff.reset()
        while True:
            shard = key2shard(key)
            gid = self.config.shards[shard]
            names = self.config.groups_dict().get(gid, ())
            for name in names:
                srv = self.directory.get(name)
                if srv is None:
                    continue
                try:
                    fn = getattr(srv, fn_name)
                    err, val = self.net.call(srv, fn, key, *args, self.cid, cseq)
                except RPCError:
                    continue
                if err == ErrWrongGroup:
                    break
                if err == ErrTxnLocked:
                    # Key locked by a prepared cross-group transaction:
                    # paced retry with the SAME cseq (the lock reply was
                    # never recorded in the dup filter) — falls through
                    # to the backoff below, like a wrong-group miss.
                    break
                return err, val
            now = time.monotonic()
            if deadline and now >= deadline:
                raise RPCError("clerk timeout")
            self._backoff.sleep(deadline - now if deadline else None)
            self.config = self.smck.query(-1)

    def get(self, key: str, timeout=None) -> str:
        err, val = self._loop("get", key, timeout=timeout)
        return val if err == OK else ""

    def put(self, key: str, value: str, timeout=None):
        self._loop("put_append", key, "put", value, timeout=timeout)

    def append(self, key: str, value: str, timeout=None):
        self._loop("put_append", key, "append", value, timeout=timeout)


class _ShardSystemOps:
    """Clerk/membership surface shared by the fabric-backed and
    decentralized system harnesses (they differ only in how the consensus
    groups are built and torn down)."""

    def sm_clerk(self):
        return shardmaster.Clerk(self.sm_servers)

    def clerk(self, net=None):
        return Clerk(self.sm_servers, self.directory, net=net)

    def join(self, gid: int):
        self.sm_clerk().join(gid, [s.name for s in self.groups[gid]])

    def leave(self, gid: int):
        self.sm_clerk().leave(gid)


class ShardSystem(_ShardSystemOps):
    """Test/deployment harness: one fabric hosting the shardmaster group and
    `ngroups` shardkv replica groups as fabric lanes."""

    def __init__(self, ngroups=2, nreplicas=3, ninstances=32, base_gid=100,
                 fabric_kw=None, **server_kw):
        """`fabric_kw` reaches the PaxosFabric constructor (mesh=...,
        io_mode=..., kernel=... — the sharded-fixture seam)."""
        self.fabric = PaxosFabric(
            ngroups=1 + ngroups, npeers=nreplicas, ninstances=ninstances,
            auto_step=True, **(fabric_kw or {}),
        )
        self.sm_servers = [
            shardmaster.ShardMasterServer(self.fabric, 0, p) for p in range(nreplicas)
        ]
        self.directory: dict[str, ShardKVServer] = {}
        self.groups: dict[int, list[ShardKVServer]] = {}
        self.gids = []
        for i in range(ngroups):
            gid = base_gid + i
            fg = 1 + i
            self.groups[gid] = [
                ShardKVServer(self.fabric, fg, gid, p, self.sm_servers,
                              self.directory, **server_kw)
                for p in range(nreplicas)
            ]
            self.gids.append(gid)

    def shutdown(self):
        for s in self.sm_servers:
            s.dead = True
        for grp in self.groups.values():
            for s in grp:
                s.dead = True
        self.fabric.stop_clock()


# ---------------------------------------------------------------------------
# Decentralized backend: shardkv groups with consensus as per-message gob
# RPC (cf. kvpaxos/shardmaster).  The reconf op's (Config, XState) payload
# travels as flattened gob maps; to_wire/from_wire are exact round-trips so
# the RSM's "mine?" equality check works on wire-decoded ops.

import json as _json

from tpu6824.services.host_backend import StructOpPeer
from tpu6824.shim.gob import INT, STRING, Array, Map, Slice, Struct

_SKV_CFG = Struct("Config", [
    ("Num", INT), ("Shards", Array(NSHARDS, INT)),
    ("Groups", Map(INT, Slice(STRING))),
])

SKVOP_NAME = "tpu6824.SKVOp"
SKVOP_WIRE = Struct("SKVOp", [
    ("Kind", STRING), ("Key", STRING), ("Value", STRING),
    ("CID", STRING), ("Seq", INT),
    ("Config", _SKV_CFG),
    ("XKV", Map(STRING, STRING)),
    ("XSeq", Map(STRING, INT)),
    ("XErr", Map(STRING, STRING)),
    ("XVal", Map(STRING, STRING)),
    ("XTxn", Slice(STRING)),
])


def _op_to_wire(op: Op) -> dict:
    # txn_* ops carry their whole payload in Kind/Value/CID/Seq (the
    # payload is already JSON) — the base fields cover them.  The only
    # txn-specific wire state is XState.txn riding a reconf, below.
    d = {"Kind": op.kind, "Key": op.key, "Value": op.value,
         "CID": op.cid, "Seq": op.cseq,
         "Config": {"Num": 0, "Shards": [0] * NSHARDS, "Groups": {}},
         "XKV": {}, "XSeq": {}, "XErr": {}, "XVal": {}, "XTxn": []}
    if op.kind == "reconf":
        cfg, xs = op.extra
        d["Config"] = {"Num": cfg.num, "Shards": list(cfg.shards),
                       "Groups": {g: list(s) for g, s in cfg.groups}}
        # Prepared-lock-table rows (export_prepared 5-tuples) as one
        # JSON document per row — gob stays schema-stable while the
        # row shape is free to grow trailing columns.
        d["XTxn"] = [_json.dumps(row) for row in getattr(xs, "txn", ())]
        d["XKV"] = dict(xs.kv)
        for cid, (cseq, reply) in xs.dup:
            err, val = reply
            d["XSeq"][cid] = cseq
            d["XErr"][cid] = err
            d["XVal"][cid] = val
    return d


def _op_from_wire(d: dict) -> Op:
    extra = None
    if d["Kind"] == "reconf":
        c = d["Config"]
        cfg = Config(
            num=c["Num"], shards=tuple(c["Shards"]),
            groups=tuple(sorted((g, tuple(s)) for g, s in c["Groups"].items())),
        )
        txn = []
        for doc in d.get("XTxn") or ():
            tid, coord, coord_srv, tops, origins = _json.loads(doc)
            txn.append((tid, int(coord), tuple(coord_srv),
                        tuple(tuple(t) for t in tops),
                        tuple(int(o) for o in origins)))
        xs = XState(
            kv=tuple(sorted(d["XKV"].items())),
            dup=tuple(sorted(
                (cid, (d["XSeq"][cid], (d["XErr"][cid], d["XVal"][cid])))
                for cid in d["XSeq"]
            )),
            txn=tuple(txn),
        )
        extra = (cfg, xs)
    return Op(d["Kind"], d["Key"], d["Value"], d["CID"], d["Seq"], extra)


def HostOpPeer(host_peer) -> StructOpPeer:
    return StructOpPeer(host_peer, SKVOP_NAME, SKVOP_WIRE,
                        to_wire=_op_to_wire, from_wire=_op_from_wire)


def make_host_group(sockdir: str, gid: int, nreplicas: int, sm_servers,
                    directory: dict, seed: int | None = None,
                    peer_kw: dict | None = None, **kw):
    """One shardkv replica group on decentralized wire consensus;
    `peer_kw` goes to HostPaxosPeer (pooled=, parallel_fanout=, ...)."""
    from tpu6824.services.host_backend import make_host_cluster as _mk

    def mk_server(p):
        return ShardKVServer(None, 0, gid, p.me, sm_servers, directory,
                             px=HostOpPeer(p), **kw)

    return _mk(sockdir, f"skv{gid}", SKVOP_NAME, SKVOP_WIRE, mk_server,
               nreplicas, seed=seed, **(peer_kw or {}))


class HostShardSystem(_ShardSystemOps):
    """The full sharded capstone with EVERY consensus group decentralized:
    shardmaster replicas and each shardkv group run per-message gob RPC
    Paxos — zero shared fabric, the reference's runtime model end to end."""

    def __init__(self, sockdir: str, ngroups: int = 2, nreplicas: int = 3,
                 base_gid: int = 100, seed: int = 0,
                 peer_kw: dict | None = None):
        self.directory: dict = {}
        _, self.sm_servers = shardmaster.make_host_cluster(
            sockdir, nservers=nreplicas, seed=seed, peer_kw=peer_kw)
        self.groups: dict[int, list[ShardKVServer]] = {}
        self.gids = []
        for i in range(ngroups):
            gid = base_gid + i
            _, servers = make_host_group(
                sockdir, gid, nreplicas, self.sm_servers, self.directory,
                seed=seed + 100 * (i + 1), peer_kw=peer_kw)
            self.groups[gid] = servers
            self.gids.append(gid)

    def shutdown(self):
        for s in self.sm_servers:
            s.kill()
        for grp in self.groups.values():
            for s in grp:
                s.kill()
