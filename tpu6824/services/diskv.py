"""diskv — persistent sharded KV store (shardkv + disk).

Capability parity with the reference Lab 5 (`diskv/server.go`,
`diskv/client.go`).  The reference fork left the server logic as empty stubs
(`diskv/server.go:31-33,142-159`); what it does define — and what is kept
bit-compatible here — is the on-disk contract:
  - per-shard directories under the server dir (shardDir, `:59-69`);
  - one file per key, filename = base32(key) (encodeKey, `:76-83`);
  - atomic write via temp-file + rename (filePut, `:92-105`);
  - whole-shard read/replace (fileReadShard/fileReplaceShard, `:108-139`);
  - `StartServer(..., restart bool)` distinguishing reboot-with-disk from
    fresh start (`:198-203`), with the harness treating directory removal as
    disk loss (`diskv/test_test.go:103-117`).

Implemented-for-real semantics on top of the shardkv RSM: every applied op is
persisted (key file + meta snapshot) BEFORE the paxos instance is Done()'d, so
a rebooted server resumes from its snapshot and replays only un-GC'd log
entries.  A disk-lossy replica that finds the log already garbage-collected
past its snapshot recovers via a full-state pull from a live peer of its
group (the Test5RejoinMix1/3 scenarios, `diskv/test_test.go:1139,1219`).

Disk footprint stays bounded (diskv/test_test.go:599-795) because only the
current value of each key is stored — the log lives in the (bounded) device
window, not on disk.
"""

from __future__ import annotations

import base64
import os
import pickle
import threading

from tpu6824.core.peer import Fate
from tpu6824.ops.hashing import NSHARDS, key2shard
from tpu6824.services.shardkv import Op, ShardKVServer
from tpu6824.utils.errors import RPCError


def encode_key(key: str) -> str:
    """base32 filename encoding (diskv/server.go:76-83)."""
    return base64.b32encode(key.encode("utf-8")).decode("ascii")


def decode_key(name: str) -> str:
    return base64.b32decode(name.encode("ascii")).decode("utf-8")


def _atomic_write(path: str, data: bytes):
    """Write-then-rename (diskv/server.go:92-105): readers never observe a
    torn file; a crash mid-write leaves only a .tmp that loading ignores."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


class DisKVServer(ShardKVServer):
    RPC_METHODS = ["get", "put_append", "transfer_state", "full_snapshot",
                   "disk_bytes"]  # wire surface (rpc.Server)

    def __init__(self, fabric, fg, gid, me, sm_clerk_servers, directory,
                 dir: str, restart: bool = False, **kw):
        self.dir = dir
        self._fs_lock = threading.Lock()
        os.makedirs(dir, exist_ok=True)
        super().__init__(fabric, fg, gid, me, sm_clerk_servers, directory,
                         start_ticker=False, **kw)
        self._blank_boot = False
        if restart:
            with self.mu:
                self._load_from_disk()
            # Restarted over a BLANK directory = total disk loss: both the
            # KV image and (in host-px mode) the acceptor ledger are gone.
            self._blank_boot = self.applied < 0 and not self.kv
            self._boot_recover()
        self._start_ticker()

    def _boot_recover(self):
        """Rejoin protocol for a restarted replica (Test5RejoinMix shape,
        diskv/test_test.go:1139-1280): before serving or proposing, adopt
        a full snapshot from any live peer that is AHEAD of our disk
        image.  This matters most after total disk loss: an amnesiac
        replica whose applied counter restarts at -1 would otherwise
        propose at seqs the cluster already applied and GC'd — and since
        acceptor state below Min is forgotten everywhere, those rounds
        would decide fresh values, forking the replica onto a divergent
        log.  If no peer answers (we are the freshest survivor, or the
        whole group is rebooting), proceed with the disk image — the
        drain's FORGOTTEN handler retries the pull later."""
        with self.mu:
            self._snapshot_from_peer()

    # ------------------------------------------------------------ file layout

    def _shard_dir(self, shard: int) -> str:
        d = os.path.join(self.dir, f"shard-{shard}")
        os.makedirs(d, exist_ok=True)
        return d

    def _file_put(self, key: str, value: str):
        _atomic_write(
            os.path.join(self._shard_dir(key2shard(key)), encode_key(key)),
            value.encode("utf-8"),
        )

    def _persist_meta(self):
        meta = {
            "applied": self.applied,
            "config": self.config,
            "dup": self.dup,
            "gid": self.gid,
        }
        _atomic_write(os.path.join(self.dir, "meta.bin"), pickle.dumps(meta))

    def _load_from_disk(self):
        metap = os.path.join(self.dir, "meta.bin")
        if os.path.exists(metap):
            with open(metap, "rb") as f:
                meta = pickle.load(f)
            self.applied = meta["applied"]
            self.config = meta["config"]
            self.dup = meta["dup"]
        for s in range(NSHARDS):
            d = os.path.join(self.dir, f"shard-{s}")
            if not os.path.isdir(d):
                continue
            for name in os.listdir(d):
                if name.endswith(".tmp"):
                    os.unlink(os.path.join(d, name))  # torn write debris
                    continue
                with open(os.path.join(d, name), "rb") as f:
                    self.kv[decode_key(name)] = f.read().decode("utf-8")

    # ------------------------------------------------------------ RSM hooks

    def _apply(self, op: Op):
        reply = super()._apply(op)
        # Persist BEFORE the caller Done()s the instance: the disk image is
        # always ≥ the log position we allow to be forgotten.
        with self._fs_lock:
            if op.kind in ("put", "append") and reply is not None and reply[0] == "OK":
                self._file_put(op.key, self.kv[op.key])
            elif op.kind == "reconf":
                cfg, xstate = op.extra
                if self.config is cfg or self.config.num >= cfg.num:
                    for k, _ in xstate.kv:
                        if k in self.kv:
                            self._file_put(k, self.kv[k])
            self._persist_meta()
        return reply

    def _drain_decided(self):
        """Like shardkv's, but a FORGOTTEN instance at applied+1 means the
        cluster GC'd past our snapshot (disk loss / long outage): recover via
        a full-state pull from a peer instead of silently skipping."""
        while True:
            fate, v = self.px.status(self.applied + 1)
            if fate == Fate.DECIDED:
                self._apply(v)
                self.applied += 1
                self.px.done(self.applied)
            elif fate == Fate.FORGOTTEN:
                if not self._snapshot_from_peer():
                    self.applied += 1  # no peer available; limp forward
            else:
                return

    def _snapshot_from_peer(self) -> bool:
        """Full-state recovery from a live replica of this group (the rejoin
        path the reference's Test5RejoinMix scenarios demand).  Peers are
        selected by directory NAME (g<gid>-<p>), not object attributes, so
        entries may be in-process servers or socket proxies alike."""
        prefix = f"g{self.gid}-"
        for name, srv in list(self.directory.items()):
            if name == self.name or not name.startswith(prefix):
                continue
            try:
                snap = srv.full_snapshot(self.applied + 1)
            except RPCError:
                continue
            if snap is None:
                continue
            kv, dup, config, applied, donor_max = snap
            if self._blank_boot:
                # Amnesiac acceptor guard: our (host-px) consensus peer
                # lost its promise/accept ledger with the disk.  Refuse
                # acceptor participation for every instance any live peer
                # has seen — the healthy majority finishes anything that
                # was in flight; re-granting against forgotten promises
                # could decide a second value for the same instance.
                # No-op on the fabric backend (acceptor state lives in
                # the fabric process and survived our crash).
                setf = getattr(self.px, "set_participation_floor", None)
                if setf is not None:
                    setf(donor_max)
                self._blank_boot = False
            self.kv = dict(kv)
            self.dup = dict(dup)
            self.config = config
            self.applied = applied
            with self._fs_lock:
                for k, val in self.kv.items():
                    self._file_put(k, val)
                self._persist_meta()
            self.px.done(self.applied)
            return True
        return False

    def full_snapshot(self, min_applied: int):
        """Donor side of crash recovery."""
        if self.dead:
            raise RPCError("dead")
        if not self.mu.acquire(timeout=1.0):
            raise RPCError("busy")
        try:
            if self.applied < min_applied:
                return None
            # The trailing max() is the donor's consensus horizon — the
            # amnesia floor a disk-lost replica must not accept below.
            return (dict(self.kv), dict(self.dup), self.config,
                    self.applied, self.px.max())
        finally:
            self.mu.release()

    def disk_bytes(self) -> int:
        """Total persistent footprint (the tc.space() probe,
        diskv/test_test.go:161-171)."""
        total = 0
        for root, _, files in os.walk(self.dir):
            for f in files:
                total += os.path.getsize(os.path.join(root, f))
        return total


class DisKVSystem:
    """Harness: shardmaster group + `ngroups` persistent KV groups, each
    server owning a directory under `base_dir`; crash/reboot/disk-loss knobs
    mirror the reference harness (`diskv/test_test.go:62-233`)."""

    def __init__(self, base_dir: str, ngroups=2, nreplicas=3, ninstances=32,
                 base_gid=500):
        from tpu6824.core.fabric import PaxosFabric
        from tpu6824.services import shardmaster

        self.base_dir = base_dir
        self.fabric = PaxosFabric(ngroups=1 + ngroups, npeers=nreplicas,
                                  ninstances=ninstances, auto_step=True)
        self.sm_servers = [
            shardmaster.ShardMasterServer(self.fabric, 0, p)
            for p in range(nreplicas)
        ]
        self.directory: dict[str, DisKVServer] = {}
        self.groups: dict[int, list[DisKVServer]] = {}
        self.gids = []
        self.nreplicas = nreplicas
        for i in range(ngroups):
            gid = base_gid + i
            fg = 1 + i
            self.groups[gid] = [
                self._boot(fg, gid, p, restart=False) for p in range(nreplicas)
            ]
            self.gids.append(gid)

    def _server_dir(self, gid, p):
        return os.path.join(self.base_dir, f"g{gid}-{p}")

    def _fg(self, gid):
        return 1 + self.gids.index(gid) if self.gids and gid in self.gids else 1

    def _boot(self, fg, gid, p, restart):
        return DisKVServer(
            self.fabric, fg, gid, p, self.sm_servers, self.directory,
            dir=self._server_dir(gid, p), restart=restart,
        )

    def crash(self, gid: int, p: int, lose_disk: bool = False):
        """kill1 (diskv/test_test.go:173-233): real crash — the server stops
        serving AND its paxos lane goes silent; optionally wipe the disk."""
        srv = self.groups[gid][p]
        srv.dead = True
        self.directory.pop(srv.name, None)
        fg = 1 + self.gids.index(gid)
        self.fabric.kill(fg, p)
        if lose_disk:
            import shutil

            shutil.rmtree(self._server_dir(gid, p), ignore_errors=True)

    def reboot(self, gid: int, p: int):
        """Restart the server process against whatever its dir holds."""
        fg = 1 + self.gids.index(gid)
        self.fabric.revive(fg, p)
        self.groups[gid][p] = self._boot(fg, gid, p, restart=True)

    def sm_clerk(self):
        from tpu6824.services import shardmaster

        return shardmaster.Clerk(self.sm_servers)

    def clerk(self):
        from tpu6824.services.shardkv import Clerk

        return Clerk(self.sm_servers, self.directory)

    def join(self, gid: int):
        self.sm_clerk().join(gid, [f"g{gid}-{p}" for p in range(self.nreplicas)])

    def leave(self, gid: int):
        self.sm_clerk().leave(gid)

    def shutdown(self):
        for s in self.sm_servers:
            s.dead = True
        for grp in self.groups.values():
            for s in grp:
                s.dead = True
        self.fabric.stop_clock()
