"""diskv — persistent sharded KV store (shardkv + disk).

Capability parity with the reference Lab 5 (`diskv/server.go`,
`diskv/client.go`).  The reference fork left the server logic as empty stubs
(`diskv/server.go:31-33,142-159`); what it does define — and what is kept
bit-compatible here — is the on-disk contract:
  - per-shard directories under the server dir (shardDir, `:59-69`);
  - one file per key, filename = base32(key) (encodeKey, `:76-83`);
  - atomic write via temp-file + rename (filePut, `:92-105`);
  - whole-shard read/replace (fileReadShard/fileReplaceShard, `:108-139`);
  - `StartServer(..., restart bool)` distinguishing reboot-with-disk from
    fresh start (`:198-203`), with the harness treating directory removal as
    disk loss (`diskv/test_test.go:103-117`).

Implemented-for-real semantics on top of the shardkv RSM: every applied op is
persisted (key file + meta snapshot) BEFORE the paxos instance is Done()'d, so
a rebooted server resumes from its snapshot and replays only un-GC'd log
entries.  A disk-lossy replica that finds the log already garbage-collected
past its snapshot recovers via a full-state pull from a live peer of its
group (the Test5RejoinMix1/3 scenarios, `diskv/test_test.go:1139,1219`).

Disk footprint stays bounded (diskv/test_test.go:599-795) because only the
current value of each key is stored — the log lives in the (bounded) device
window, not on disk.
"""

from __future__ import annotations

import base64
import os
import pickle
import threading
import time
import zlib

from tpu6824.core.hostpeer import FLOOR_ALL as _FLOOR_ALL
from tpu6824.core.peer import Fate
from tpu6824.ops.hashing import NSHARDS, key2shard
from tpu6824.services.shardkv import Op, ShardKVServer
from tpu6824.utils.errors import RPCError
from tpu6824.utils import crashsink, durafs


def encode_key(key: str) -> str:
    """base32 filename encoding (diskv/server.go:76-83)."""
    return base64.b32encode(key.encode("utf-8")).decode("ascii")


def decode_key(name: str) -> str:
    return base64.b32decode(name.encode("ascii")).decode("utf-8")


def _atomic_write(path: str, data: bytes):
    """Write-then-rename (diskv/server.go:92-105): readers never observe a
    torn file; a crash mid-write leaves only a .tmp that loading ignores.

    Routed through the one `utils/durafs.py` seam (the durable-write-
    discipline tpusan rule enforces this tree-wide), which HARDENS the
    old open+replace: the tmp file is fsync'd before the rename and the
    directory after it — without the tmp fsync, a crash shortly after
    the rename could publish a file whose payload never hit the platter
    (exactly the bug the durafault torn-write injector surfaces), and
    without the dir fsync the rename itself could be lost.  durafs also
    keeps the per-writer-unique tmp naming from PR 4 (pid + thread id;
    two writers sharing one `path + ".tmp"` raced rename-vs-rename) with
    the ".tmp" suffix `_load_from_disk`'s debris sweep matches."""
    durafs.atomic_write(path, data)


class DisKVServer(ShardKVServer):
    RPC_METHODS = ["get", "put_append", "transfer_state", "full_snapshot",
                   "consensus_horizon", "disk_bytes"]  # wire surface

    def __init__(self, fabric, fg, gid, me, sm_clerk_servers, directory,
                 dir: str, restart: bool = False, **kw):
        self.dir = dir
        self._fs_lock = threading.Lock()
        # Set by the harness's crash(lose_disk=True) BEFORE it wipes the
        # directory: a still-draining driver of the dead instance must
        # not resurrect the wiped dir with a partial image (makedirs in
        # _shard_dir) that a later reboot would mistake for a disk —
        # the zombie-writer race the durafault suffix accounting
        # surfaced once boot-time peer pulls became conditional.
        self._disk_gone = False
        # Content checksums of every key file AS WRITTEN, persisted in
        # the meta snapshot: the boot-time cross-check that catches a
        # power crash exposing an fsync lie on one half of the
        # file-then-meta pair (stale key file under a fresh meta, or a
        # fresh key file under a rolled-back meta — both otherwise
        # silently serve a lost/doubled update, since log replay dedups
        # seqs <= applied through the dup table).
        self._sums: dict[str, int] = {}
        self._image_inconsistent: list[str] = []
        os.makedirs(dir, exist_ok=True)
        super().__init__(fabric, fg, gid, me, sm_clerk_servers, directory,
                         start_ticker=False, **kw)
        if restart:
            with self.mu:
                self._load_from_disk()
            self._boot_recover()
        self._start_ticker()

    def _boot_recover(self):
        """Rejoin protocol for a restarted replica (Test5RejoinMix shape,
        diskv/test_test.go:1139-1280): before serving or proposing, adopt
        a full snapshot from any live peer that is AHEAD of our disk
        image.  This matters most after total disk loss: an amnesiac
        replica whose applied counter restarts at -1 would otherwise
        propose at seqs the cluster already applied and GC'd — and since
        acceptor state below Min is forgotten everywhere, those rounds
        would decide fresh values, forking the replica onto a divergent
        log.  If no peer answers (we are the freshest survivor, or the
        whole group is rebooting), proceed with the disk image — the
        drain's FORGOTTEN handler retries the pull later."""
        getf = getattr(self.px, "participation_floor", None)
        if getf is not None and getf() >= _FLOOR_ALL:
            # The consensus peer booted quarantined (diskvd passes
            # FLOOR_ALL when --restart finds no paxos ledger; the peer
            # persists it immediately, so a double-crash re-quarantines).
            # One quick poll, then a background retry — the ctor must not
            # block on peers that may themselves be mid-rejoin behind
            # unbound service sockets; staying quarantined meanwhile is
            # always safe (grants refused, serving/learning unaffected).
            if not self._try_lower_amnesia_floor(deadline_s=0.0):
                threading.Thread(
                    target=crashsink.guarded(self._floor_retry_loop,
                                             "diskv-floor-retry"),
                    daemon=True).start()
        with self.mu:
            # Pull a full snapshot ONLY when the disk image cannot be
            # trusted or the log cannot carry us: (a) the load-time
            # content-checksum cross-check found key files inconsistent
            # with the meta snapshot (a power crash exposed fsync lies
            # on ONE side of the file-then-meta write pair — in either
            # direction, the image at `applied` is wrong and log replay
            # cannot repair seqs <= applied because the dup table
            # dedups them); or (b) the cluster GC'd (Min()) past our
            # applied watermark — disk loss, or an outage longer than
            # the window.  A reboot over an intact, CONSISTENT disk
            # replays just the un-truncated suffix through the ordinary
            # drain instead (durafault asserts this via instance-count
            # accounting); anything truncated later surfaces as
            # FORGOTTEN in the drain, which retries this pull.
            if self._image_inconsistent:
                # require_ahead=False: repairing CONTENT at our own
                # watermark — a donor at exactly `applied` is a valid
                # source (the default applied+1 floor is for catch-up
                # pulls, where a same-level donor has nothing new).
                if self._snapshot_from_peer(require_ahead=False) != "ok":
                    crashsink.record(
                        f"diskv-dirty-image-{self.name}",
                        RuntimeError(
                            f"inconsistent disk image (keys "
                            f"{sorted(self._image_inconsistent)[:8]}) and "
                            "no donor reachable — serving the image as-is"),
                        fatal=False)
            elif self.px.min() > self.applied + 1:
                self._snapshot_from_peer()

    # _group_peers is inherited from ShardKVServer (hoisted there for
    # the horizon snapshot-install catch-up, ISSUE 14).

    def _try_lower_amnesia_floor(self, deadline_s: float) -> bool:
        """Blank-disk rejoin, floor half: lower the boot quarantine
        (FLOOR_ALL) to the group's consensus horizon.  The horizon must
        cover every instance that could carry one of OUR forgotten
        promises, and a prepare-majority that included us need not
        include any single responder — so horizons are required from
        enough peers that every possible majority-minus-us is
        intersected (P - floor(P/2) of the others).  Until that many
        answer, the quarantine stands: granting nothing is always safe;
        a whole-group blank restart is unrecoverable data anyway and
        fresh deployments never pass --restart."""
        setf = self.px.set_participation_floor
        nothers = sum(1 for _ in self._group_peers())
        P = nothers + 1
        needed = min(nothers, P - P // 2)
        deadline = time.monotonic() + deadline_s
        while not self.dead:
            horizons = []
            for _name, srv in self._group_peers():
                try:
                    horizons.append(srv.consensus_horizon())
                except RPCError:
                    continue
            if len(horizons) >= needed and horizons:
                setf(max(horizons), force=True)
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.25)
        return False

    def _floor_retry_loop(self):
        while not self.dead:
            if self._try_lower_amnesia_floor(deadline_s=5.0):
                return
            time.sleep(1.0)

    # ------------------------------------------------------------ file layout

    def _shard_dir(self, shard: int) -> str:
        d = os.path.join(self.dir, f"shard-{shard}")
        os.makedirs(d, exist_ok=True)
        return d

    def _file_put(self, key: str, value: str):
        data = value.encode("utf-8")
        _atomic_write(
            os.path.join(self._shard_dir(key2shard(key)), encode_key(key)),
            data,
        )
        # Maintained incrementally (never recomputed over the whole kv)
        # and persisted with the NEXT meta write, so the meta snapshot
        # always records what each key file must contain at `applied`.
        self._sums[key] = zlib.crc32(data) & 0xFFFFFFFF

    def _persist_meta(self, applied: int | None = None):
        """`applied` lets _apply persist the watermark of the op it just
        applied (self.applied + 1 — every RSM drain applies at exactly
        that seq and increments AFTER _apply returns).  Persisting the
        pre-increment counter understated the disk image by one op,
        which made every intact-disk reboot look one op behind Min() and
        take the full-state peer pull meant for disk LOSS — surfaced by
        the durafault suffix-replay accounting test."""
        meta = {
            "applied": self.applied if applied is None else applied,
            "config": self.config,
            "dup": self.dup,
            "gid": self.gid,
            "sums": self._sums,
        }
        _atomic_write(os.path.join(self.dir, "meta.bin"), pickle.dumps(meta))

    def _load_from_disk(self):
        metap = os.path.join(self.dir, "meta.bin")
        sums = None
        if os.path.exists(metap):
            with open(metap, "rb") as f:
                meta = pickle.load(f)
            self.applied = meta["applied"]
            self.config = meta["config"]
            self.dup = meta["dup"]
            sums = meta.get("sums")  # absent in pre-durafault metas
        # Root-level debris sweep (meta.bin's torn tmps — meta is
        # written on EVERY applied op, so it is the most likely torn-
        # fault victim); the per-shard sweep below covers key files.
        for name in os.listdir(self.dir):
            if name.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(self.dir, name))
                except FileNotFoundError:
                    pass
        loaded_crc: dict[str, int] = {}
        for s in range(NSHARDS):
            d = os.path.join(self.dir, f"shard-{s}")
            if not os.path.isdir(d):
                continue
            for name in os.listdir(d):
                if name.endswith(".tmp"):
                    # Torn-write debris — but a rebooted server shares the
                    # dir with the old instance's still-draining driver,
                    # whose in-flight tmp may complete (rename away) or
                    # lose its tmp to this unlink (its replace then fails,
                    # swallowed by _apply's dead-server catch).  Either
                    # way the sweep must not crash the reboot.
                    try:
                        os.unlink(os.path.join(d, name))
                    except FileNotFoundError:
                        pass
                    continue
                with open(os.path.join(d, name), "rb") as f:
                    data = f.read()
                key = decode_key(name)
                self.kv[key] = data.decode("utf-8")
                loaded_crc[key] = zlib.crc32(data) & 0xFFFFFFFF
        if sums is not None:
            # Cross-check: every key file must hold exactly what the
            # meta snapshot says was durably written at `applied` — a
            # mismatch (either direction) or a missing/extra key means
            # a power crash exposed an un-synced write on one side of
            # the file-then-meta pair, and the image must be repaired
            # from a peer, not served (_boot_recover).
            self._image_inconsistent = sorted(
                set(k for k, c in sums.items()
                    if loaded_crc.get(k) != c)
                | set(loaded_crc) - set(sums))
            self._sums = dict(sums)
        else:
            self._sums = dict(loaded_crc)

    # ------------------------------------------------------------ RSM hooks

    def _apply(self, op: Op):
        reply = super()._apply(op)
        # Persist BEFORE the caller Done()s the instance: the disk image is
        # always ≥ the log position we allow to be forgotten.
        with self._fs_lock:
            if self._disk_gone:
                # crash(lose_disk=True) wiped the dir (serialized on
                # this lock): the write is moot by design, and writing
                # anyway would RECREATE the wiped directory.
                return reply
            try:
                if op.kind in ("put", "append") and reply is not None and reply[0] == "OK":
                    self._file_put(op.key, self.kv[op.key])
                elif op.kind == "reconf":
                    cfg, xstate = op.extra
                    if self.config is cfg or self.config.num >= cfg.num:
                        for k, _ in xstate.kv:
                            if k in self.kv:
                                self._file_put(k, self.kv[k])
                # This op sits at seq self.applied + 1 (the caller
                # increments after we return): persist THAT watermark.
                self._persist_meta(self.applied + 1)
            except OSError as e:
                # crash(lose_disk=True) rmtree's our directory while this
                # (now-dead) server's driver is mid-persist; the write is
                # moot — the disk is gone by design.
                if isinstance(e, FileNotFoundError) and self.dead:
                    return reply
                # Any other failed persist (injected DiskFault, real
                # ENOSPC/EIO, a live server's directory vanishing):
                # durability demands we HALT before the caller can Done()
                # this instance — a replica that serves on after a failed
                # persist would let the cluster GC log entries its disk
                # image does not cover.  Die like a crashed process
                # (paxos lane silent, dropped from the directory); a
                # reboot re-syncs from disk + peers.  The exception
                # re-raises so _drain_decided never advances `applied`
                # past the unpersisted op.
                crashsink.record(f"diskv-persist-{self.name}", e,
                                 fatal=False)
                self._halt_for_disk_fault()
                raise
        return reply

    def _halt_for_disk_fault(self):
        """Self-crash on a failed persist (see _apply): equivalent to the
        harness's crash() but initiated by the replica itself — the same
        state a nemesis `crash_process` leaves, so the soak tail's
        reboot-everything pass revives it identically."""
        self.dead = True
        self.directory.pop(self.name, None)
        try:
            self.px.fabric.kill(self.px.g, self.px.me)
        except Exception as e:  # noqa: BLE001 — halting must not throw
            crashsink.record(f"diskv-halt-{self.name}", e, fatal=False)

    def _drain_decided(self):
        """Like shardkv's, but a FORGOTTEN instance at applied+1 means the
        cluster GC'd past our snapshot (disk loss / long outage): recover via
        a full-state pull from a peer instead of silently skipping."""
        while True:
            fate, v = self.px.status(self.applied + 1)
            if fate == Fate.DECIDED:
                self._apply(v)
                self.applied += 1
                self.px.done(self.applied)
            elif fate == Fate.FORGOTTEN:
                # Single-pass pull (deadline 0): this runs under mu on
                # every tick, so the TICK CADENCE is the retry loop —
                # sleeping here would block this replica's client ops
                # and its own donor duties for the whole deadline.  The
                # multi-second patience is reserved for boot
                # (_boot_recover), where nothing is being served yet.
                st = self._snapshot_from_peer(deadline_s=0.0)
                if st == "behind":
                    # Every REACHABLE peer is at/behind our watermark (a
                    # whole-group blank restart): nothing to pull, ever —
                    # skip the forgotten seq so the group keeps living.
                    self.applied += 1
                elif st != "ok":
                    # Peers exist but were busy/unreachable this pass:
                    # limping here would permanently skip GC'd data a
                    # donor could still supply — retry next tick instead.
                    return
            else:
                return

    def _snapshot_from_peer(self, deadline_s: float = 3.0,
                            require_ahead: bool = True) -> str:
        """Full-state recovery from a live replica of this group (the rejoin
        path the reference's Test5RejoinMix scenarios demand).  Peers are
        selected by directory NAME (g<gid>-<p>), not object attributes, so
        entries may be in-process servers or socket proxies alike.

        Returns "ok" (state adopted), "behind" (every REACHABLE peer is
        at/behind our watermark — nothing to pull), or "unreachable"
        (no peer answered within `deadline_s`).  The distinction is
        load-bearing: a donor whose mu is busy (its own drain mid-
        persist — fsync-heavy under the durafs discipline) answers
        "busy" transiently, and treating that like "no donor exists"
        used to let the caller's limp-forward path permanently skip the
        GC'd prefix (surfaced as a rare {'m0': '+more'} full-suite-
        contention flake in the disk-loss rejoin test).  The retry/
        report discipline itself is `services.common.pull_from_peers`
        (ISSUE 14 hoisted it so kvpaxos/shardkv snapshot-install and
        this path share the exact hardened loop); callers limp only
        when limping is actually safe."""
        from tpu6824.services.common import pull_from_peers

        return pull_from_peers(
            lambda: self._snapshot_from_peer_once(require_ahead),
            deadline_s=deadline_s, is_dead=lambda: self.dead)

    def _snapshot_from_peer_once(self, require_ahead: bool = True) -> str:
        behind = False
        floor = self.applied + (1 if require_ahead else 0)
        for name, srv in self._group_peers():
            try:
                snap = srv.full_snapshot(floor)
            except RPCError:
                continue
            if snap is None:
                behind = True
                continue
            kv, dup, config, applied = snap
            self.kv = dict(kv)
            self.dup = dict(dup)
            self.config = config
            self.applied = applied
            with self._fs_lock:
                if not self._disk_gone:
                    self._sums = {}  # rebuilt below; stale sums must go
                    for k, val in self.kv.items():
                        self._file_put(k, val)
                    self._persist_meta()
            self._image_inconsistent = []  # image now donor-consistent
            self.px.done(self.applied)
            return "ok"
        return "behind" if behind else "unreachable"

    def full_snapshot(self, min_applied: int):
        """Donor side of crash recovery."""
        if self.dead:
            raise RPCError("dead")
        if not self.mu.acquire(timeout=1.0):
            raise RPCError("busy")
        try:
            if self.applied < min_applied:
                return None
            return (dict(self.kv), dict(self.dup), self.config, self.applied)
        finally:
            self.mu.release()

    def consensus_horizon(self) -> int:
        """Donor half of the amnesia floor (`_lower_amnesia_floor`): the
        highest instance this replica's consensus peer has seen."""
        if self.dead:
            raise RPCError("dead")
        return self.px.max()

    def disk_bytes(self) -> int:
        """Total persistent footprint (the tc.space() probe,
        diskv/test_test.go:161-171).  In-flight ".tmp" files are skipped
        — they are rename-pending write buffers, not footprint — and a
        file vanishing between listdir and stat (a concurrent atomic
        rename completing) is tolerated: THIS was the other half of the
        pre-PR-4 test_diskv flake."""
        total = 0
        for root, _, files in os.walk(self.dir):
            for f in files:
                if f.endswith(".tmp"):
                    continue
                try:
                    total += os.path.getsize(os.path.join(root, f))
                except FileNotFoundError:
                    continue
        return total


class DisKVSystem:
    """Harness: shardmaster group + `ngroups` persistent KV groups, each
    server owning a directory under `base_dir`; crash/reboot/disk-loss knobs
    mirror the reference harness (`diskv/test_test.go:62-233`)."""

    def __init__(self, base_dir: str, ngroups=2, nreplicas=3, ninstances=32,
                 base_gid=500, fault_disks: bool = False,
                 fabric_kw: dict | None = None):
        """`fault_disks=True` registers a `durafs.DuraDisk` over every
        server directory, so the durafault nemesis (`DiskTarget`) can arm
        torn writes / fsync lies / ENOSPC per replica and `crash(...,
        power_crash=True)` can model losing the un-synced page cache.
        `fabric_kw` passes through to the PaxosFabric ctor (kernel
        engine, io mode, pipelining — the durafault soak runs on both
        engines)."""
        from tpu6824.core.fabric import PaxosFabric
        from tpu6824.services import shardmaster

        self.base_dir = base_dir
        self.disks: dict[str, durafs.DuraDisk] = {}
        if fault_disks:
            for i in range(ngroups):
                for p in range(nreplicas):
                    gid = base_gid + i
                    d = self._server_dir(gid, p)
                    os.makedirs(d, exist_ok=True)
                    self.disks[f"g{gid}-{p}"] = durafs.register(
                        durafs.DuraDisk(d))
        self.fabric = PaxosFabric(ngroups=1 + ngroups, npeers=nreplicas,
                                  ninstances=ninstances, auto_step=True,
                                  **(fabric_kw or {}))
        self.sm_servers = [
            shardmaster.ShardMasterServer(self.fabric, 0, p)
            for p in range(nreplicas)
        ]
        self.directory: dict[str, DisKVServer] = {}
        self.groups: dict[int, list[DisKVServer]] = {}
        self.gids = []
        self.nreplicas = nreplicas
        for i in range(ngroups):
            gid = base_gid + i
            fg = 1 + i
            self.groups[gid] = [
                self._boot(fg, gid, p, restart=False) for p in range(nreplicas)
            ]
            self.gids.append(gid)

    def _server_dir(self, gid, p):
        return os.path.join(self.base_dir, f"g{gid}-{p}")

    def _fg(self, gid):
        return 1 + self.gids.index(gid) if self.gids and gid in self.gids else 1

    def _boot(self, fg, gid, p, restart):
        return DisKVServer(
            self.fabric, fg, gid, p, self.sm_servers, self.directory,
            dir=self._server_dir(gid, p), restart=restart,
        )

    def crash(self, gid: int, p: int, lose_disk: bool = False,
              power_crash: bool = False):
        """kill1 (diskv/test_test.go:173-233): real crash — the server stops
        serving AND its paxos lane goes silent; optionally wipe the disk
        (`lose_disk`) or model a POWER loss (`power_crash`: every write
        whose fsync was a lie / whose rename was never dir-synced reverts
        to the last durable content — needs `fault_disks=True`)."""
        srv = self.groups[gid][p]
        srv.dead = True
        self.directory.pop(srv.name, None)
        fg = 1 + self.gids.index(gid)
        self.fabric.kill(fg, p)
        disk = self.disks.get(srv.name) or \
            durafs.lookup(self._server_dir(gid, p))
        if lose_disk:
            # Flag first, wipe under the server's fs lock: any persist
            # in flight completes BEFORE the wipe, and every later one
            # sees _disk_gone and skips — the dead instance can never
            # resurrect the directory (see DisKVServer.__init__).
            srv._disk_gone = True
            with srv._fs_lock:
                if disk is not None:
                    disk.lose()
                else:
                    import shutil

                    shutil.rmtree(self._server_dir(gid, p),
                                  ignore_errors=True)
        elif power_crash and disk is not None:
            with srv._fs_lock:
                disk.power_crash()

    def reboot(self, gid: int, p: int):
        """Restart the server process against whatever its dir holds."""
        fg = 1 + self.gids.index(gid)
        disk = self.disks.get(f"g{gid}-{p}")
        if disk is not None:
            # New process, (possibly replacement) disk: lost flag, armed
            # faults, and the volatile journal do not survive a reboot.
            disk.reset()
        self.fabric.revive(fg, p)
        self.groups[gid][p] = self._boot(fg, gid, p, restart=True)

    def sm_clerk(self):
        from tpu6824.services import shardmaster

        return shardmaster.Clerk(self.sm_servers)

    def clerk(self):
        from tpu6824.services.shardkv import Clerk

        return Clerk(self.sm_servers, self.directory)

    def join(self, gid: int):
        self.sm_clerk().join(gid, [f"g{gid}-{p}" for p in range(self.nreplicas)])

    def leave(self, gid: int):
        self.sm_clerk().leave(gid)

    def shutdown(self):
        for s in self.sm_servers:
            s.dead = True
        for grp in self.groups.values():
            for s in grp:
                s.dead = True
        for disk in self.disks.values():
            durafs.unregister(disk)
        self.fabric.stop_clock()
