"""diskv — persistent sharded KV store (shardkv + disk).

Capability parity with the reference Lab 5 (`diskv/server.go`,
`diskv/client.go`).  The reference fork left the server logic as empty stubs
(`diskv/server.go:31-33,142-159`); what it does define — and what is kept
bit-compatible here — is the on-disk contract:
  - per-shard directories under the server dir (shardDir, `:59-69`);
  - one file per key, filename = base32(key) (encodeKey, `:76-83`);
  - atomic write via temp-file + rename (filePut, `:92-105`);
  - whole-shard read/replace (fileReadShard/fileReplaceShard, `:108-139`);
  - `StartServer(..., restart bool)` distinguishing reboot-with-disk from
    fresh start (`:198-203`), with the harness treating directory removal as
    disk loss (`diskv/test_test.go:103-117`).

Implemented-for-real semantics on top of the shardkv RSM: every applied op is
persisted (key file + meta snapshot) BEFORE the paxos instance is Done()'d, so
a rebooted server resumes from its snapshot and replays only un-GC'd log
entries.  A disk-lossy replica that finds the log already garbage-collected
past its snapshot recovers via a full-state pull from a live peer of its
group (the Test5RejoinMix1/3 scenarios, `diskv/test_test.go:1139,1219`).

Disk footprint stays bounded (diskv/test_test.go:599-795) because only the
current value of each key is stored — the log lives in the (bounded) device
window, not on disk.
"""

from __future__ import annotations

import base64
import os
import pickle
import threading
import time

from tpu6824.core.hostpeer import FLOOR_ALL as _FLOOR_ALL
from tpu6824.core.peer import Fate
from tpu6824.ops.hashing import NSHARDS, key2shard
from tpu6824.services.shardkv import Op, ShardKVServer
from tpu6824.utils.errors import RPCError
from tpu6824.utils import crashsink


def encode_key(key: str) -> str:
    """base32 filename encoding (diskv/server.go:76-83)."""
    return base64.b32encode(key.encode("utf-8")).decode("ascii")


def decode_key(name: str) -> str:
    return base64.b32decode(name.encode("ascii")).decode("utf-8")


def _atomic_write(path: str, data: bytes):
    """Write-then-rename (diskv/server.go:92-105): readers never observe a
    torn file; a crash mid-write leaves only a .tmp that loading ignores.

    The tmp name is unique PER WRITER (pid + thread id): a reboot puts a
    fresh server object on the same directory while the old server's
    driver thread may still be mid-persist, and two writers sharing one
    `path + ".tmp"` race rename-vs-rename — the loser's os.replace dies
    with FileNotFoundError (the pre-PR-4 test_diskv flake).  Unique tmp
    names keep every replace self-contained; last rename wins, which is
    safe because both writers rename complete value images.  The suffix
    stays ".tmp" so _load_from_disk's debris sweep still matches."""
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


class DisKVServer(ShardKVServer):
    RPC_METHODS = ["get", "put_append", "transfer_state", "full_snapshot",
                   "consensus_horizon", "disk_bytes"]  # wire surface

    def __init__(self, fabric, fg, gid, me, sm_clerk_servers, directory,
                 dir: str, restart: bool = False, **kw):
        self.dir = dir
        self._fs_lock = threading.Lock()
        os.makedirs(dir, exist_ok=True)
        super().__init__(fabric, fg, gid, me, sm_clerk_servers, directory,
                         start_ticker=False, **kw)
        if restart:
            with self.mu:
                self._load_from_disk()
            self._boot_recover()
        self._start_ticker()

    def _boot_recover(self):
        """Rejoin protocol for a restarted replica (Test5RejoinMix shape,
        diskv/test_test.go:1139-1280): before serving or proposing, adopt
        a full snapshot from any live peer that is AHEAD of our disk
        image.  This matters most after total disk loss: an amnesiac
        replica whose applied counter restarts at -1 would otherwise
        propose at seqs the cluster already applied and GC'd — and since
        acceptor state below Min is forgotten everywhere, those rounds
        would decide fresh values, forking the replica onto a divergent
        log.  If no peer answers (we are the freshest survivor, or the
        whole group is rebooting), proceed with the disk image — the
        drain's FORGOTTEN handler retries the pull later."""
        getf = getattr(self.px, "participation_floor", None)
        if getf is not None and getf() >= _FLOOR_ALL:
            # The consensus peer booted quarantined (diskvd passes
            # FLOOR_ALL when --restart finds no paxos ledger; the peer
            # persists it immediately, so a double-crash re-quarantines).
            # One quick poll, then a background retry — the ctor must not
            # block on peers that may themselves be mid-rejoin behind
            # unbound service sockets; staying quarantined meanwhile is
            # always safe (grants refused, serving/learning unaffected).
            if not self._try_lower_amnesia_floor(deadline_s=0.0):
                threading.Thread(
                    target=crashsink.guarded(self._floor_retry_loop,
                                             "diskv-floor-retry"),
                    daemon=True).start()
        with self.mu:
            self._snapshot_from_peer()

    def _group_peers(self):
        """Live directory entries of this group's OTHER replicas —
        in-process servers or socket proxies alike (selected by name,
        the g<gid>-<p> convention)."""
        prefix = f"g{self.gid}-"
        for name, srv in list(self.directory.items()):
            if name != self.name and name.startswith(prefix):
                yield name, srv

    def _try_lower_amnesia_floor(self, deadline_s: float) -> bool:
        """Blank-disk rejoin, floor half: lower the boot quarantine
        (FLOOR_ALL) to the group's consensus horizon.  The horizon must
        cover every instance that could carry one of OUR forgotten
        promises, and a prepare-majority that included us need not
        include any single responder — so horizons are required from
        enough peers that every possible majority-minus-us is
        intersected (P - floor(P/2) of the others).  Until that many
        answer, the quarantine stands: granting nothing is always safe;
        a whole-group blank restart is unrecoverable data anyway and
        fresh deployments never pass --restart."""
        setf = self.px.set_participation_floor
        nothers = sum(1 for _ in self._group_peers())
        P = nothers + 1
        needed = min(nothers, P - P // 2)
        deadline = time.monotonic() + deadline_s
        while not self.dead:
            horizons = []
            for _name, srv in self._group_peers():
                try:
                    horizons.append(srv.consensus_horizon())
                except RPCError:
                    continue
            if len(horizons) >= needed and horizons:
                setf(max(horizons), force=True)
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.25)
        return False

    def _floor_retry_loop(self):
        while not self.dead:
            if self._try_lower_amnesia_floor(deadline_s=5.0):
                return
            time.sleep(1.0)

    # ------------------------------------------------------------ file layout

    def _shard_dir(self, shard: int) -> str:
        d = os.path.join(self.dir, f"shard-{shard}")
        os.makedirs(d, exist_ok=True)
        return d

    def _file_put(self, key: str, value: str):
        _atomic_write(
            os.path.join(self._shard_dir(key2shard(key)), encode_key(key)),
            value.encode("utf-8"),
        )

    def _persist_meta(self):
        meta = {
            "applied": self.applied,
            "config": self.config,
            "dup": self.dup,
            "gid": self.gid,
        }
        _atomic_write(os.path.join(self.dir, "meta.bin"), pickle.dumps(meta))

    def _load_from_disk(self):
        metap = os.path.join(self.dir, "meta.bin")
        if os.path.exists(metap):
            with open(metap, "rb") as f:
                meta = pickle.load(f)
            self.applied = meta["applied"]
            self.config = meta["config"]
            self.dup = meta["dup"]
        for s in range(NSHARDS):
            d = os.path.join(self.dir, f"shard-{s}")
            if not os.path.isdir(d):
                continue
            for name in os.listdir(d):
                if name.endswith(".tmp"):
                    # Torn-write debris — but a rebooted server shares the
                    # dir with the old instance's still-draining driver,
                    # whose in-flight tmp may complete (rename away) or
                    # lose its tmp to this unlink (its replace then fails,
                    # swallowed by _apply's dead-server catch).  Either
                    # way the sweep must not crash the reboot.
                    try:
                        os.unlink(os.path.join(d, name))
                    except FileNotFoundError:
                        pass
                    continue
                with open(os.path.join(d, name), "rb") as f:
                    self.kv[decode_key(name)] = f.read().decode("utf-8")

    # ------------------------------------------------------------ RSM hooks

    def _apply(self, op: Op):
        reply = super()._apply(op)
        # Persist BEFORE the caller Done()s the instance: the disk image is
        # always ≥ the log position we allow to be forgotten.
        with self._fs_lock:
            try:
                if op.kind in ("put", "append") and reply is not None and reply[0] == "OK":
                    self._file_put(op.key, self.kv[op.key])
                elif op.kind == "reconf":
                    cfg, xstate = op.extra
                    if self.config is cfg or self.config.num >= cfg.num:
                        for k, _ in xstate.kv:
                            if k in self.kv:
                                self._file_put(k, self.kv[k])
                self._persist_meta()
            except FileNotFoundError:
                # crash(lose_disk=True) rmtree's our directory while this
                # (now-dead) server's driver is mid-persist; the write is
                # moot — the disk is gone by design.  Any other writer
                # losing its directory is a real bug: re-raise.
                if not self.dead:
                    raise
        return reply

    def _drain_decided(self):
        """Like shardkv's, but a FORGOTTEN instance at applied+1 means the
        cluster GC'd past our snapshot (disk loss / long outage): recover via
        a full-state pull from a peer instead of silently skipping."""
        while True:
            fate, v = self.px.status(self.applied + 1)
            if fate == Fate.DECIDED:
                self._apply(v)
                self.applied += 1
                self.px.done(self.applied)
            elif fate == Fate.FORGOTTEN:
                if not self._snapshot_from_peer():
                    self.applied += 1  # no peer available; limp forward
            else:
                return

    def _snapshot_from_peer(self) -> bool:
        """Full-state recovery from a live replica of this group (the rejoin
        path the reference's Test5RejoinMix scenarios demand).  Peers are
        selected by directory NAME (g<gid>-<p>), not object attributes, so
        entries may be in-process servers or socket proxies alike."""
        for name, srv in self._group_peers():
            try:
                snap = srv.full_snapshot(self.applied + 1)
            except RPCError:
                continue
            if snap is None:
                continue
            kv, dup, config, applied = snap
            self.kv = dict(kv)
            self.dup = dict(dup)
            self.config = config
            self.applied = applied
            with self._fs_lock:
                for k, val in self.kv.items():
                    self._file_put(k, val)
                self._persist_meta()
            self.px.done(self.applied)
            return True
        return False

    def full_snapshot(self, min_applied: int):
        """Donor side of crash recovery."""
        if self.dead:
            raise RPCError("dead")
        if not self.mu.acquire(timeout=1.0):
            raise RPCError("busy")
        try:
            if self.applied < min_applied:
                return None
            return (dict(self.kv), dict(self.dup), self.config, self.applied)
        finally:
            self.mu.release()

    def consensus_horizon(self) -> int:
        """Donor half of the amnesia floor (`_lower_amnesia_floor`): the
        highest instance this replica's consensus peer has seen."""
        if self.dead:
            raise RPCError("dead")
        return self.px.max()

    def disk_bytes(self) -> int:
        """Total persistent footprint (the tc.space() probe,
        diskv/test_test.go:161-171).  In-flight ".tmp" files are skipped
        — they are rename-pending write buffers, not footprint — and a
        file vanishing between listdir and stat (a concurrent atomic
        rename completing) is tolerated: THIS was the other half of the
        pre-PR-4 test_diskv flake."""
        total = 0
        for root, _, files in os.walk(self.dir):
            for f in files:
                if f.endswith(".tmp"):
                    continue
                try:
                    total += os.path.getsize(os.path.join(root, f))
                except FileNotFoundError:
                    continue
        return total


class DisKVSystem:
    """Harness: shardmaster group + `ngroups` persistent KV groups, each
    server owning a directory under `base_dir`; crash/reboot/disk-loss knobs
    mirror the reference harness (`diskv/test_test.go:62-233`)."""

    def __init__(self, base_dir: str, ngroups=2, nreplicas=3, ninstances=32,
                 base_gid=500):
        from tpu6824.core.fabric import PaxosFabric
        from tpu6824.services import shardmaster

        self.base_dir = base_dir
        self.fabric = PaxosFabric(ngroups=1 + ngroups, npeers=nreplicas,
                                  ninstances=ninstances, auto_step=True)
        self.sm_servers = [
            shardmaster.ShardMasterServer(self.fabric, 0, p)
            for p in range(nreplicas)
        ]
        self.directory: dict[str, DisKVServer] = {}
        self.groups: dict[int, list[DisKVServer]] = {}
        self.gids = []
        self.nreplicas = nreplicas
        for i in range(ngroups):
            gid = base_gid + i
            fg = 1 + i
            self.groups[gid] = [
                self._boot(fg, gid, p, restart=False) for p in range(nreplicas)
            ]
            self.gids.append(gid)

    def _server_dir(self, gid, p):
        return os.path.join(self.base_dir, f"g{gid}-{p}")

    def _fg(self, gid):
        return 1 + self.gids.index(gid) if self.gids and gid in self.gids else 1

    def _boot(self, fg, gid, p, restart):
        return DisKVServer(
            self.fabric, fg, gid, p, self.sm_servers, self.directory,
            dir=self._server_dir(gid, p), restart=restart,
        )

    def crash(self, gid: int, p: int, lose_disk: bool = False):
        """kill1 (diskv/test_test.go:173-233): real crash — the server stops
        serving AND its paxos lane goes silent; optionally wipe the disk."""
        srv = self.groups[gid][p]
        srv.dead = True
        self.directory.pop(srv.name, None)
        fg = 1 + self.gids.index(gid)
        self.fabric.kill(fg, p)
        if lose_disk:
            import shutil

            shutil.rmtree(self._server_dir(gid, p), ignore_errors=True)

    def reboot(self, gid: int, p: int):
        """Restart the server process against whatever its dir holds."""
        fg = 1 + self.gids.index(gid)
        self.fabric.revive(fg, p)
        self.groups[gid][p] = self._boot(fg, gid, p, restart=True)

    def sm_clerk(self):
        from tpu6824.services import shardmaster

        return shardmaster.Clerk(self.sm_servers)

    def clerk(self):
        from tpu6824.services.shardkv import Clerk

        return Clerk(self.sm_servers, self.directory)

    def join(self, gid: int):
        self.sm_clerk().join(gid, [f"g{gid}-{p}" for p in range(self.nreplicas)])

    def leave(self, gid: int):
        self.sm_clerk().leave(gid)

    def shutdown(self):
        for s in self.sm_servers:
            s.dead = True
        for grp in self.groups.values():
            for s in grp:
                s.dead = True
        self.fabric.stop_clock()
