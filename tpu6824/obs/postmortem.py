"""postmortem — fleet incident reconstruction from blackbox rings.

    python -m tpu6824.obs.postmortem <dir> [--json] [--perfetto out.json]
                                           [--schedule artifact.json]

The read side of obs/blackbox.py: load every `*.bbx` ring in a directory
(tolerating torn tails from SIGKILL — that is the point), join them onto
one causal wall-clock timeline via each ring's (wall-ns, monotonic-ns)
anchor pair, fold in any watchdog evidence bundles found beside the
rings, and reconstruct each process's FINAL WINDOW: the last pulse
gauges, the last opscope waterfall, the last decided seq it applied
(kvpaxos/shardkv heartbeat stamps), and the ops it died holding
(frontend inflight stamp).  With `--schedule` the nemesis
`FaultSchedule` (or a failure artifact embedding one) is joined against
the ring-observed injections, so the report reads "fe_kill smoke-fe1 at
t=+2.31 → last decided seq 412, 7 ops in flight" — the question a
kill-storm victim used to take to the grave.

Offline and stdlib-only: this module never touches a live process, so
it runs on a workstation against a directory copied from the wreckage.
`--json` emits a stable machine document (sorted keys, schema-stamped —
the committed golden fixture pins it); `--perfetto` exports every ring's
flight spans plus injection/watchdog/crash instants as one Chrome trace,
process per track, on the joined wall timeline.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tpu6824.obs import blackbox as _blackbox
from tpu6824.obs import tracing as _tracing

__all__ = ["reconstruct", "main", "SCHEMA_VERSION"]

SCHEMA_VERSION = "postmortem-1.0.0"

# Heartbeat-stamp key substrings with derived meaning: decided-seq
# stamps (kvpaxos/shardkv drain high-waters) and in-flight counts
# (frontend engine passes).  Producers keep these substrings in their
# precomputed keys; everything else rides the heartbeat verbatim.
_DECIDED_SUBSTR = ("applied", "decided")
_INFLIGHT_SUBSTR = ("inflight",)


def _last_of(records: list[dict], kind: str) -> dict | None:
    for rec in reversed(records):
        if rec["kind"] == kind:
            return rec
    return None


def _final_window(ring: dict) -> dict:
    """One process's reconstructed last-known state: liveness counters,
    the final record of each telemetry kind, and the derived
    decided/in-flight evidence from the last heartbeat's stamp table."""
    recs = ring["records"]
    by_kind: dict[str, int] = {}
    for rec in recs:
        by_kind[rec["kind"]] = by_kind.get(rec["kind"], 0) + 1
    hb = _last_of(recs, "heartbeat")
    stamps = (hb or {}).get("data", {}).get("stamps", {})
    decided = {k: v for k, v in stamps.items()
               if any(s in k for s in _DECIDED_SUBSTR)}
    inflight = {k: v for k, v in stamps.items()
                if any(s in k for s in _INFLIGHT_SUBSTR)}
    seqs = [v for v in decided.values() if isinstance(v, (int, float))]
    flights = [v for v in inflight.values() if isinstance(v, (int, float))]
    last_pulse = _last_of(recs, "pulse")
    last_opscope = _last_of(recs, "opscope")
    return {
        "name": ring["name"], "pid": ring["pid"], "path": ring["path"],
        "valid": ring["valid"], "error": ring["error"],
        "last_seq": ring["last_seq"], "seals": ring["seals"],
        "bytes_written": ring["bytes_written"],
        "torn_slots": ring["torn_slots"],
        "torn_records": ring["torn_records"],
        "records_by_kind": by_kind,
        "first_t_wall_ns": recs[0]["t_wall_ns"] if recs else None,
        "last_t_wall_ns": recs[-1]["t_wall_ns"] if recs else None,
        "last_heartbeat": stamps or None,
        "last_pulse": (last_pulse or {}).get("data"),
        "last_opscope": (last_opscope or {}).get("data"),
        "decided": decided or None,
        "last_decided_seq": max(seqs) if seqs else None,
        "inflight": inflight or None,
        "inflight_ops": sum(flights) if inflight else None,
        "crashes": [r["data"] for r in recs if r["kind"] == "crash"],
        "watchdog": [r["data"] for r in recs if r["kind"] == "watchdog"],
        "nemesis_seen": sum(1 for r in recs if r["kind"] == "nemesis"),
    }


def _bundles(dirpath: str) -> list[dict]:
    """Watchdog evidence bundles written beside the rings (fabricd's
    `--watchdog-dir` pointed at the blackbox dir, or copied in) — the
    full-fat bundle joins the ring's fire-time core when both exist."""
    out = []
    try:
        names = sorted(n for n in os.listdir(dirpath)
                       if n.startswith("watchdog-") and n.endswith(".json"))
    except OSError:
        return out
    for n in names:
        try:
            with open(os.path.join(dirpath, n)) as f:
                d = json.load(f)
        except (OSError, ValueError) as e:
            out.append({"file": n, "error": repr(e)})
            continue
        wd = d.get("watchdog", {})
        out.append({"file": n, "rule": wd.get("rule"),
                    "reason": wd.get("reason"),
                    "evidence": wd.get("evidence"),
                    "t_mono": wd.get("t_mono")})
    return out


def _schedule_join(rings: list[dict], schedule) -> dict:
    """Ring-observed injections are authoritative (they carry the joined
    wall clock); the schedule says what SHOULD have fired, so events the
    rings never saw — the harness died first, or the run was cut short —
    are reported as not-observed instead of silently missing."""
    observed = []
    for ring in rings:
        for rec in ring["records"]:
            if rec["kind"] == "nemesis":
                observed.append({"t": rec["data"].get("t"),
                                 "action": rec["data"].get("action"),
                                 "args": rec["data"].get("args"),
                                 "t_wall_ns": rec["t_wall_ns"],
                                 "recorded_by": ring["name"]})
    observed.sort(key=lambda e: (e["t_wall_ns"], e["recorded_by"]))
    out = {"observed": observed, "scheduled": None, "not_observed": None}
    if schedule is not None:
        seen = {(round(float(e["t"]), 9), e["action"]) for e in observed
                if e["t"] is not None}
        missing = [e.to_dict() for e in schedule.events
                   if (round(e.t, 9), e.action) not in seen]
        out["scheduled"] = len(schedule.events)
        out["not_observed"] = missing
    return out


def reconstruct(dirpath: str, schedule=None) -> dict:
    """The whole postmortem as one JSON-safe document (the `--json`
    shape; `schedule` is an optional `FaultSchedule`)."""
    rings = _blackbox.load_dir(dirpath)
    timeline = []
    for ring in rings:
        for rec in ring["records"]:
            entry = {"t_wall_ns": rec["t_wall_ns"], "proc": ring["name"],
                     "seq": rec["seq"], "kind": rec["kind"]}
            if rec["kind"] in ("nemesis", "watchdog", "crash"):
                entry["data"] = rec["data"]
            timeline.append(entry)
    timeline.sort(key=lambda e: (e["t_wall_ns"], e["proc"], e["seq"]))
    return {
        "schema": SCHEMA_VERSION,
        "dir": dirpath,
        "rings": len(rings),
        "processes": {r["name"] or os.path.basename(r["path"]):
                      _final_window(r) for r in rings},
        "timeline": timeline,
        "watchdog_bundles": _bundles(dirpath),
        "nemesis": _schedule_join(rings, schedule),
    }


# ----------------------------------------------------------------- export


def _perfetto_events(rings: list[dict]) -> list[dict]:
    """Every ring's flight spans + one instant per non-flight record,
    REBASED onto the joined wall timeline: each ring's monotonic stamps
    shift by (anchor_wall - anchor_mono), then the fleet-minimum wall
    stamp becomes t=0 — Perfetto renders cross-process causality
    directly."""
    events: list[dict] = []
    walls = [r["records"][0]["t_wall_ns"] for r in rings if r["records"]]
    base = min(walls) if walls else 0
    for pid, ring in enumerate(rings, start=1):
        shift = ring["anchor_wall_ns"] - ring["anchor_mono_ns"] - base
        flight: list[dict] = []
        for rec in ring["records"]:
            if rec["kind"] == "flight":
                for fr in rec["data"].get("records", ()):
                    fr = dict(fr)
                    fr["ts"] = fr.get("ts", 0) + shift
                    flight.append(fr)
            else:
                flight.append({"ph": "i", "name": f"bb.{rec['kind']}",
                               "comp": "blackbox", "trace_id": 0,
                               "span_id": rec["seq"], "parent_id": 0,
                               "ts": rec["t_wall_ns"] - base, "dur": 0,
                               "args": {"kind": rec["kind"]}})
        events.extend(_tracing.chrome_events(
            flight, process=ring["name"], pid=pid))
    return events


# ----------------------------------------------------------------- report


def _fmt_ns(t_ns, base_ns) -> str:
    return f"+{(t_ns - base_ns) / 1e9:.3f}s"


def _render_report(doc: dict) -> str:
    lines = [f"postmortem over {doc['dir']} — {doc['rings']} ring(s)"]
    walls = [w["first_t_wall_ns"] for w in doc["processes"].values()
             if w["first_t_wall_ns"] is not None]
    base = min(walls) if walls else 0
    for name in sorted(doc["processes"]):
        w = doc["processes"][name]
        lines.append(f"\n== {name} (pid {w['pid']}) ==")
        if not w["valid"]:
            lines.append(f"  UNREADABLE ring: {w['error']}")
            continue
        kinds = ", ".join(f"{k}:{v}" for k, v in
                          sorted(w["records_by_kind"].items()))
        lines.append(f"  ring: seq {w['last_seq']}, {w['seals']} seal(s), "
                     f"{w['bytes_written']}B, torn {w['torn_slots']} "
                     f"slot(s)/{w['torn_records']} record(s)")
        lines.append(f"  records: {kinds or '(none)'}")
        if w["last_t_wall_ns"] is not None:
            lines.append("  last record at "
                         f"{_fmt_ns(w['last_t_wall_ns'], base)}")
        if w["last_decided_seq"] is not None:
            per = ", ".join(f"{k}={v}" for k, v in
                            sorted(w["decided"].items()))
            lines.append(f"  last decided seq: {w['last_decided_seq']} "
                         f"({per})")
        if w["inflight_ops"] is not None:
            lines.append(f"  in-flight ops at death: {w['inflight_ops']}")
        if w["last_pulse"]:
            latest = w["last_pulse"].get("latest", {})
            top = sorted(latest.items())[:8]
            lines.append(f"  last pulse tick ({w['last_pulse'].get('samples')}"
                         " samples): "
                         + ", ".join(f"{k}={v}" for k, v in top))
        if w["last_opscope"]:
            hist = w["last_opscope"].get("histograms", {})
            stages = [f"{st} p99={h.get('p99')}" for st, h in
                      sorted(hist.items()) if h.get("count")]
            lines.append("  last opscope waterfall: "
                         + ("; ".join(stages) or "(no folded ops)"))
        for c in w["crashes"]:
            lines.append(f"  crash: [{c.get('thread')}] {c.get('error')}"
                         f" (fatal={c.get('fatal')})")
        for wd in w["watchdog"]:
            lines.append(f"  watchdog fired: {wd.get('rule')} — "
                         f"{wd.get('reason')}")
    nem = doc["nemesis"]
    if nem["observed"]:
        lines.append(f"\n== nemesis timeline ({len(nem['observed'])} "
                     "observed) ==")
        for e in nem["observed"]:
            lines.append(f"  {_fmt_ns(e['t_wall_ns'], base)} "
                         f"t={e['t']:+.3f} {e['action']} {e['args']}")
    if nem["not_observed"]:
        lines.append(f"  NOT observed in any ring "
                     f"({len(nem['not_observed'])} of "
                     f"{nem['scheduled']} scheduled):")
        for e in nem["not_observed"]:
            lines.append(f"    t={e['t']:+.3f} {e['action']} {e['args']}")
    if doc["watchdog_bundles"]:
        lines.append("\n== watchdog bundles ==")
        for b in doc["watchdog_bundles"]:
            lines.append(f"  {b['file']}: {b.get('rule')} — "
                         f"{b.get('reason', b.get('error'))}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="postmortem",
        description="reconstruct a fleet incident from blackbox rings")
    ap.add_argument("dir", help="directory of *.bbx rings "
                                "(+ optional watchdog-*.json bundles)")
    ap.add_argument("--json", action="store_true",
                    help="emit the stable machine document")
    ap.add_argument("--perfetto", metavar="PATH", default=None,
                    help="export the joined timeline as a Chrome trace")
    ap.add_argument("--schedule", metavar="PATH", default=None,
                    help="nemesis FaultSchedule (or failure artifact) to "
                         "join against the observed injections")
    args = ap.parse_args(argv)
    schedule = None
    if args.schedule:
        from tpu6824.harness.nemesis import FaultSchedule

        schedule = FaultSchedule.from_json(args.schedule)
    doc = reconstruct(args.dir, schedule=schedule)
    if not doc["rings"]:
        print(f"postmortem: no rings under {args.dir}", file=sys.stderr)
        return 2
    if args.perfetto:
        rings = _blackbox.load_dir(args.dir)
        _tracing.write_chrome_trace(args.perfetto, _perfetto_events(rings))
        print(f"postmortem: wrote {args.perfetto}", file=sys.stderr)
    if args.json:
        print(json.dumps(doc, indent=1, sort_keys=True, default=repr))
    else:
        print(_render_report(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
