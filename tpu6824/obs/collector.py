"""kernelscope fleet collector — one view of a multi-process deployment.

tpuscope (ISSUE 5) gave every PROCESS a metrics registry, a stats()
health block, and a flight recorder, each served over the fabric_service
wire (`metrics`/`stats`/`flight` RPCs).  But a wire deployment is
several processes — fabricd owning the device, replica daemons, the
driving harness — and until now a nemesis soak over one produced only
per-process fragments: N metrics files that can't be summed, N Perfetto
exports whose span ids collide (every process counts ids from 1).

The `Collector` closes that gap:

  - `add(name, handle)` registers any fabric-shaped handle — a local
    `PaxosFabric`, a `remote_fabric()` proxy, or anything exposing some
    subset of `stats()/metrics()/flight()` (absent surfaces are skipped,
    dead processes are recorded as errors, never raised — mid-nemesis a
    collector member being down IS data);
  - `snapshot()` polls every member once into ONE namespaced dict
    `{processes: {name: {stats, metrics, flight}}, errors: {...}}` —
    the artifact every soak embeds and every fleet poller scrapes;
  - `export_perfetto(path)` merges every member's flight ring into ONE
    Chrome/Perfetto file, one process track per member (distinct pids,
    `name/component` thread labels via `tracing.chrome_events`) — all
    rings share `time.monotonic_ns()` so cross-process causality reads
    directly off the one timeline;
  - `protocol_totals()` sums the kernelscope per-group device counters
    (`stats()["protocol"]`) across every device-owning member — the
    fleet-wide rounds-per-decide the ROADMAP variants are judged by.

Stdlib-only like the rest of `obs/` (plus `utils/crashsink`, itself
stdlib-only): handles are duck-typed, so this module imports neither
JAX nor the rpc layer.
"""

from __future__ import annotations

import threading
import time

from tpu6824.obs import blackbox as obs_blackbox
from tpu6824.obs import metrics as obs_metrics
from tpu6824.obs import opscope as obs_opscope
from tpu6824.obs import pulse as obs_pulse
from tpu6824.obs import tracing as obs_tracing
from tpu6824.utils import crashsink

__all__ = ["Collector", "derive_protocol_ratios", "local_handle"]


def derive_protocol_ratios(totals: dict) -> dict:
    """The derived protocol ratios, in ONE place: rounds-per-decide (how
    many prepare rounds a decide actually cost) and the fast-path
    fraction (decides won at the proposer's first proposal number — the
    1-round cohort the ROADMAP flexible-quorum variants target).  Both
    `PaxosFabric.stats()["protocol"]` and the fleet-merged
    `Collector.merge_protocol` derive through here, so a variant PR that
    redefines a cohort cannot silently diverge the per-fabric numbers
    from the fleet numbers."""
    decides = totals.get("decides", 0)
    return {
        "rounds_per_decide": (
            round(totals.get("prepare_attempts", 0) / decides, 4)
            if decides else None),
        "fast_path_fraction": (
            round(totals.get("fast_path_decides", 0) / decides, 4)
            if decides else None),
    }


class _LocalProcess:
    """The calling process as a collector member: registry + flight ring
    directly, stats() only when a local fabric was given (the surface is
    simply absent otherwise — absent, not erroring, so a fabric-less
    harness process doesn't pollute the snapshot's error map)."""

    def __init__(self, fabric=None):
        if fabric is not None:
            self.stats = fabric.stats

    def metrics(self):
        return obs_metrics.snapshot()

    def flight(self):
        return obs_tracing.flight_snapshot()

    def pulse(self):
        return obs_pulse.series_snapshot()

    def opscope(self):
        return obs_opscope.snapshot()

    def blackbox(self):
        return obs_blackbox.status()


def local_handle(fabric=None) -> _LocalProcess:
    """A collector handle for THIS process (the harness/driver process is
    part of the fleet too — its clerk retries and rpc latencies belong in
    the merged snapshot)."""
    return _LocalProcess(fabric)


class Collector:
    """Named fabric-shaped handles → one merged observability artifact."""

    _SURFACES = ("stats", "metrics", "flight", "pulse", "opscope",
                 "blackbox")

    def __init__(self, poll_timeout: float = 15.0):
        # Per-MEMBER wall budget for one snapshot poll: a hung member
        # (partitioned/deafened mid-nemesis — exactly when snapshots
        # matter) must not stall the merged artifact for the full RPC
        # timeout × surfaces × members; members are polled concurrently
        # and a straggler is cut off at the budget with whatever
        # surfaces it already delivered.
        self._members: dict[str, object] = {}
        self._poll_timeout = poll_timeout

    def add(self, name: str, handle) -> "Collector":
        if name in self._members:
            raise ValueError(f"collector member {name!r} already added")
        self._members[name] = handle
        return self

    def add_local(self, name: str = "local", fabric=None) -> "Collector":
        return self.add(name, local_handle(fabric))

    def names(self) -> list[str]:
        return sorted(self._members)

    # ------------------------------------------------------------ snapshot

    def snapshot(self, timeout: float | None = None) -> dict:
        """Poll every member once, CONCURRENTLY, bounded by the per-
        member poll budget.  Per-member per-surface failures land in
        `errors["name.surface"]` as strings, and a member still hanging
        at the deadline lands in `errors["name.poll"]` with whatever
        surfaces it already delivered kept — a half-dead deployment
        still yields the surviving processes' view promptly (exactly
        the moment a merged snapshot matters most)."""
        budget = self._poll_timeout if timeout is None else timeout
        processes: dict[str, dict] = {}
        errors: dict[str, str] = {}
        mu = threading.Lock()

        def poll(name, h, out):
            for surface in self._SURFACES:
                fn = getattr(h, surface, None)
                if fn is None:
                    continue
                try:
                    val = fn()
                except Exception as e:  # noqa: BLE001 — a dead member is data
                    if surface == "pulse":
                        # Back-compat: a pre-pulse fabricd answers the
                        # pulse RPC with "no such rpc" while being
                        # fully healthy — that is the documented
                        # disabled shell, not an error (a member that
                        # is actually DEAD still errors on its other
                        # surfaces).
                        with mu:
                            out[surface] = {
                                "schema": obs_pulse.SCHEMA_VERSION,
                                "enabled": False, "interval": None,
                                "cap": None, "samples": 0,
                                "t_mono": None, "series": {},
                                "unavailable": repr(e)[:200]}
                        continue
                    if surface == "opscope":
                        # Same mixed-fleet rule for the opscope surface
                        # (ISSUE 15): a pre-opscope member answering
                        # "no such rpc" yields the STABLE disabled
                        # shell, never an error entry.
                        with mu:
                            out[surface] = obs_opscope.snapshot_shell(
                                reason=repr(e)[:200])
                        continue
                    if surface == "blackbox":
                        # Same mixed-fleet rule for the blackbox surface
                        # (ISSUE 20): a pre-blackbox member answering
                        # "no such rpc" yields the stable disabled
                        # shell, never an error entry.
                        with mu:
                            out[surface] = obs_blackbox.status_shell(
                                reason=repr(e)[:200])
                        continue
                    with mu:
                        errors[f"{name}.{surface}"] = repr(e)[:200]
                else:
                    with mu:
                        out[surface] = val

        threads = []
        for name in self.names():
            out: dict = {}
            processes[name] = out
            # Surface failures are caught per-call above; guarded() is
            # the daemon-death contract for anything that still escapes.
            t = threading.Thread(
                target=crashsink.guarded(poll, f"collector[{name}]"),
                args=(name, self._members[name], out), daemon=True)
            t.start()
            threads.append((name, t))
        deadline = time.monotonic() + budget
        for name, t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                with mu:
                    errors[f"{name}.poll"] = (
                        f"member still polling after {budget}s budget — "
                        "partial surfaces kept")
        # Copy under the lock: a straggler thread cut off at the budget
        # is still alive and will keep writing into its `out` dict (and
        # `errors`) — returning the live dicts would let json.dumps over
        # the artifact race those writes ("dict changed size during
        # iteration" at exactly the failure moment the artifact exists
        # for).  Surface values are never mutated after assignment, so
        # shallow copies of the containers suffice.
        with mu:
            return {"schema": obs_tracing.SCHEMA_VERSION,
                    "t_mono_ns": time.monotonic_ns(),
                    "processes": {n: dict(o) for n, o in processes.items()},
                    "errors": dict(errors)}

    # ------------------------------------------------------------- derived

    @staticmethod
    def merge_protocol(snapshot: dict) -> dict | None:
        """Sum `stats()["protocol"]` totals across every device-owning
        member of a snapshot (None when no member reported protocol
        counters).  Derived ratios are recomputed from the merged totals
        — averaging per-process ratios would weight idle fabrics equally
        with loaded ones."""
        totals: dict[str, int] = {}
        fields: list[str] | None = None
        for proc in snapshot["processes"].values():
            proto = proc.get("stats", {}).get("protocol")
            if not proto:
                continue
            fields = fields or list(proto["fields"])
            for k, v in proto["totals"].items():
                totals[k] = totals.get(k, 0) + int(v)
        if fields is None:
            return None
        return {"fields": fields, "totals": totals,
                **derive_protocol_ratios(totals)}

    def protocol_totals(self) -> dict | None:
        return self.merge_protocol(self.snapshot())

    @staticmethod
    def merge_pulse(snapshot: dict) -> dict | None:
        """Fleet view over every member's pulse series (None when no
        member runs a pulse): per series, the per-process LATEST value
        plus, for rate-kind series, their sum — fleet throughput is a
        sum of rates; summing gauge levels or latency percentiles would
        be meaningless, so non-rate series carry per-process values
        only."""
        out: dict[str, dict] = {}
        any_enabled = False
        for name, proc in sorted(snapshot["processes"].items()):
            pu = proc.get("pulse")
            if not pu or not pu.get("enabled"):
                continue
            any_enabled = True
            for sname, s in pu.get("series", {}).items():
                if not s["v"]:
                    continue
                e = out.setdefault(sname, {"kind": s["kind"],
                                           "per_process": {}})
                e["per_process"][name] = s["v"][-1]
                if s["kind"] == "rate":
                    e["latest_sum"] = round(
                        e.get("latest_sum", 0.0) + s["v"][-1], 6)
        return out if any_enabled else None

    @staticmethod
    def merge_opscope(snapshot: dict) -> dict | None:
        """Fleet waterfall (ISSUE 15): per stage, the raw log2 buckets
        summed across every opscope-enabled member, with p50/p95/p99
        recomputed from the MERGED buckets — averaging per-process
        percentiles would weight an idle frontend equally with a loaded
        one, the same rule merge_protocol applies to ratios.  None when
        no member serves an enabled opscope."""
        from tpu6824.obs.metrics import _NBUCKETS, _bucket_quantile

        merged: dict[str, list] = {}
        counts: dict[str, int] = {}
        sums: dict[str, int] = {}
        stages: list[str] = []
        any_enabled = False
        for proc in snapshot["processes"].values():
            osc = proc.get("opscope")
            if not osc or not osc.get("enabled"):
                continue
            any_enabled = True
            for st in osc.get("stages", ()):
                if st not in stages:
                    stages.append(st)
            for st, h in osc.get("histograms", {}).items():
                buckets = merged.setdefault(st, [0] * _NBUCKETS)
                for k, c in h.get("pow2", {}).items():
                    buckets[min(int(k), _NBUCKETS - 1)] += int(c)
                counts[st] = counts.get(st, 0) + int(h.get("count", 0))
                sums[st] = sums.get(st, 0) + int(h.get("sum", 0))
        if not any_enabled:
            return None
        out = {}
        # Beyond the stage list proper: per-shard dispatch splits
        # (ISSUE 17 meshfab) merge by the same bucket sum — any
        # histogram a member serves survives into the fleet waterfall.
        extra = sorted(k for k in merged if k not in stages)
        for st in list(stages) + extra:
            b = merged.get(st, [0] * _NBUCKETS)
            n = counts.get(st, 0)
            out[st] = {
                "count": n, "sum": sums.get(st, 0),
                "p50": _bucket_quantile(b, n, 0.50) if n else None,
                "p95": _bucket_quantile(b, n, 0.95) if n else None,
                "p99": _bucket_quantile(b, n, 0.99) if n else None,
            }
        return {"schema": obs_opscope.SCHEMA_VERSION, "stages": stages,
                "histograms": out}

    # ------------------------------------------------------------- perfetto

    @staticmethod
    def merge_perfetto(snapshot: dict, path: str) -> str:
        """One Perfetto file from a snapshot's flight rings: member k
        renders as process track pid=k+1 (stable name order) labeled with
        the member name — span/trace ids that collide across processes
        stay distinguishable because every event carries its process name
        and lives under its own pid."""
        events: list[dict] = []
        for pid, (name, proc) in enumerate(
                sorted(snapshot["processes"].items()), start=1):
            flight = proc.get("flight")
            if not flight:
                continue
            events.extend(obs_tracing.chrome_events(
                flight["records"], process=name, pid=pid))
        return obs_tracing.write_chrome_trace(path, events)

    def export_perfetto(self, path: str) -> str:
        return self.merge_perfetto(self.snapshot(), path)
