"""obs.top — the live terminal dashboard over one process or a fleet.

    python -m tpu6824.obs.top --addr /var/tmp/x/fab [--addr ...]
                              [--interval S] [--once] [--json]

Polls each `--addr` fabric_service socket (stats/metrics/flight/pulse —
the same surfaces the kernelscope Collector merges) plus, with
`--local`, the calling process's own registry, and renders one screen
per interval: decided throughput, protocol ratios, stalled groups with
their kernelscope diagnosis, feed depth, RPC pool traffic, latency
percentiles, and drop counters.  `--once --json` emits a single
machine-readable snapshot instead — the CI smoke contract: STABLE keys
(every process block always carries the same key set) and NO NaN/Inf
anywhere (non-finite values are scrubbed to null before serializing).

Imports only stdlib + the socket transport (`tpu6824.rpc`); never JAX —
runnable against a live fabricd from any box.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import time

from tpu6824.obs.collector import Collector

SCHEMA_VERSION = "top-1.0.0"

# Every process block carries EXACTLY these keys (the --json stability
# contract); absent data is None/empty, never a missing key.  ISSUE 15
# added `waterfall` — the per-stage opscope p99 pane; a pre-opscope
# member renders it disabled-with-empty-stages, never missing.
_PROC_KEYS = ("decided_cells", "decided_per_sec", "steps_per_sec",
              "stalled_groups", "stall_diagnosis", "feed_depth_max",
              "thread_crashes", "events_dropped", "flight_dropped",
              "protocol", "rpc_pool", "latency_us", "pulse", "waterfall",
              "error")


def scrub(obj):
    """Replace non-finite floats with None, recursively — the JSON smoke
    gate rejects NaN/Inf (json.dumps(allow_nan=False) downstream)."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: scrub(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [scrub(v) for v in obj]
    return obj


_RATE_WINDOW_S = 10.0


def _series_rate(pulse_snap: dict, name: str):
    """LIVE rate from a pulse rate-series: the mean over its trailing
    ~10s of points, None when the series is absent.  Windowed relative
    to the series' own last timestamp (producer-side monotonic — a
    remote process's clock is not ours), never over the whole ring: a
    600-point ring is 10 minutes of history, and a dashboard averaging
    it would still read "healthy" minutes into a collapse."""
    s = (pulse_snap or {}).get("series", {}).get(name)
    if not s or not s["v"]:
        return None
    cutoff = s["t"][-1] - _RATE_WINDOW_S
    tail = [v for t, v in zip(s["t"], s["v"]) if t >= cutoff]
    return round(sum(tail) / len(tail), 1)


def _proc_view(proc: dict, err: str | None) -> dict:
    st = proc.get("stats") or {}
    met = proc.get("metrics") or {}
    fl = proc.get("flight") or {}
    pu = proc.get("pulse") or {}
    osc = proc.get("opscope") or {}
    health = st.get("health") or {}
    rates = st.get("rates") or {}
    proto = st.get("protocol") or {}
    counters = met.get("counters") or {}
    hists = met.get("histograms") or {}
    lat = hists.get("clerk.op_latency_us") or {}
    view = {
        "decided_cells": st.get("decided_cells"),
        "decided_per_sec": (
            _series_rate(pu, "fabric.decided_cells.rate")
            if pu.get("enabled")
            else (round(rates.get("decided_cells", 0.0), 1)
                  if rates else None)),
        "steps_per_sec": (round(rates.get("steps", 0.0), 1)
                          if rates else None),
        "stalled_groups": health.get("stalled_groups", []),
        "stall_diagnosis": health.get("stall_diagnosis", {}),
        "feed_depth_max": health.get("feed_depth_max"),
        "thread_crashes": (health.get("thread_crashes") or {}).get("count"),
        "events_dropped": st.get("events_dropped"),
        "flight_dropped": fl.get("dropped"),
        "protocol": {
            "decides": (proto.get("totals") or {}).get("decides"),
            "rounds_per_decide": proto.get("rounds_per_decide"),
            "fast_path_fraction": proto.get("fast_path_fraction"),
        },
        "rpc_pool": {
            "hits": (counters.get("rpc.pool.hits") or {}).get("total"),
            "misses": (counters.get("rpc.pool.misses") or {}).get("total"),
            "evictions": (counters.get("rpc.pool.evictions")
                          or {}).get("total"),
        },
        "latency_us": {"p50": lat.get("p50"), "p95": lat.get("p95"),
                       "p99": lat.get("p99")},
        "pulse": {"enabled": bool(pu.get("enabled")),
                  "samples": pu.get("samples", 0),
                  "series": len(pu.get("series") or {})},
        # The opscope waterfall pane (ISSUE 15): per-stage p99 µs of the
        # request path, in pipeline order — where an op's latency lives.
        "waterfall": {
            "enabled": bool(osc.get("enabled")),
            "op_p99_us": (osc.get("op") or {}).get("p99"),
            "p99_us": {st: h.get("p99")
                       for st, h in (osc.get("histograms") or {}).items()
                       if h.get("count")},
        },
        "error": err,
    }
    assert set(view) == set(_PROC_KEYS)
    return view


def build_view(snap: dict) -> dict:
    procs = {}
    for name in sorted(snap["processes"]):
        # Error keys are f"{name}.{surface}" with dot-free surfaces;
        # member names themselves may contain dots (socket basenames
        # like fab.sock), so match on the LAST dot, not the first.
        errs = [v for k, v in snap["errors"].items()
                if k.rsplit(".", 1)[0] == name]
        procs[name] = _proc_view(snap["processes"][name],
                                 errs[0] if errs else None)
    merged = Collector.merge_protocol(snap)
    if merged is not None:
        merged = {k: v for k, v in merged.items() if k != "fields"}
    decided = [p["decided_cells"] for p in procs.values()
               if p["decided_cells"] is not None]
    rates = [p["decided_per_sec"] for p in procs.values()
             if p["decided_per_sec"] is not None]
    return scrub({
        "schema": SCHEMA_VERSION,
        "t_mono": round(time.monotonic(), 6),
        "processes": procs,
        "errors": dict(snap["errors"]),
        "fleet": {
            "decided_cells": sum(decided) if decided else None,
            "decided_per_sec": (round(sum(rates), 1) if rates else None),
            "protocol": merged,
            "pulse": Collector.merge_pulse(snap),
            "waterfall": Collector.merge_opscope(snap),
        },
    })


# ------------------------------------------------------------- rendering


def _fmt(v, width=10):
    if v is None:
        return "-".rjust(width)
    if isinstance(v, float):
        return f"{v:,.1f}".rjust(width)
    return f"{v:,}".rjust(width)


def render(view: dict) -> str:
    lines = [f"tpu6824 top  ({len(view['processes'])} process(es), "
             f"t={view['t_mono']:.1f})",
             f"{'process':<12}{'decided':>12}{'dec/s':>10}{'steps/s':>10}"
             f"{'feed':>6}{'stall':>6}{'crash':>6}{'drop':>6}"
             f"{'rnds/dec':>9}{'p99us':>9}"]
    for name, p in view["processes"].items():
        drops = (p["events_dropped"] or 0) + (p["flight_dropped"] or 0)
        lines.append(
            f"{name:<12}{_fmt(p['decided_cells'], 12)}"
            f"{_fmt(p['decided_per_sec'])}{_fmt(p['steps_per_sec'])}"
            f"{_fmt(p['feed_depth_max'], 6)}"
            f"{_fmt(len(p['stalled_groups']), 6)}"
            f"{_fmt(p['thread_crashes'], 6)}{_fmt(drops, 6)}"
            f"{_fmt(p['protocol']['rounds_per_decide'], 9)}"
            f"{_fmt(p['latency_us']['p99'], 9)}")
        for g, why in sorted(p["stall_diagnosis"].items()):
            lines.append(f"  !! g{g}: {why}")
        wf = p.get("waterfall") or {}
        if wf.get("enabled") and wf.get("p99_us"):
            # Waterfall pane: stage p99s in pipeline order — the op's
            # latency, decomposed (ISSUE 15).
            cells = "  ".join(f"{st}:{_fmt(us, 1).strip()}"
                              for st, us in wf["p99_us"].items())
            lines.append(f"  waterfall p99us  {cells}")
        if p["error"]:
            lines.append(f"  !! poll: {p['error']}")
    fleet = view["fleet"]
    if len(view["processes"]) > 1 and fleet["protocol"]:
        lines.append(
            f"{'FLEET':<12}{_fmt(fleet['decided_cells'], 12)}"
            f"{_fmt(fleet['decided_per_sec'])}"
            f"{'':>10}{'':>6}{'':>6}{'':>6}{'':>6}"
            f"{_fmt(fleet['protocol'].get('rounds_per_decide'), 9)}")
    for k, e in view["errors"].items():
        lines.append(f"error {k}: {e}")
    return "\n".join(lines)


# ------------------------------------------------------------------ main


def member_name(i: int, addr: str, stats: dict | None) -> str:
    """A fleet-unique collector member name.  Frontends stamp a
    fleet-unique `frontend.id` in stats() (fleetfe, ISSUE 18) — use it
    when present, because two frontends both serving `fe.sock` in
    different directories would otherwise merge ambiguously under the
    socket-basename scheme.  Everything else (fabricd, replica daemons,
    pre-fleetfe frontends) keeps `proc{i}@{basename}`."""
    fe = (stats or {}).get("frontend")
    if isinstance(fe, dict) and fe.get("id"):
        return str(fe["id"])
    return f"proc{i}@{addr.rsplit('/', 1)[-1]}"


def build_collector(addrs, local: bool, timeout: float) -> Collector:
    col = Collector(poll_timeout=timeout)
    for i, addr in enumerate(addrs):
        from tpu6824.rpc import connect  # socket transport only, no JAX
        h = connect(addr, timeout=timeout)
        try:
            st = h.stats()
        except Exception:  # noqa: BLE001 — a member down at add time is
            st = None      # data; snapshot() records it under the
            #                fallback name like any other dead member.
        col.add(member_name(i, addr, st), h)
    if local or not addrs:
        col.add_local("local")
    return col


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu6824.obs.top",
        description="Live dashboard over fabric_service processes "
                    "(--once --json for scripting/CI).")
    ap.add_argument("--addr", action="append", default=[],
                    help="fabric_service socket (repeatable); with none, "
                         "the local process registry is shown")
    ap.add_argument("--local", action="store_true",
                    help="include the calling process alongside --addr")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="one snapshot, no screen clearing")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the snapshot as one JSON object")
    ap.add_argument("--timeout", type=float, default=10.0,
                    help="per-member poll budget (seconds)")
    args = ap.parse_args(argv)
    col = build_collector(args.addr, args.local, args.timeout)
    try:
        while True:
            view = build_view(col.snapshot())
            if args.as_json:
                print(json.dumps(view, allow_nan=False), flush=True)
            else:
                if not args.once:
                    sys.stdout.write("\x1b[2J\x1b[H")
                print(render(view), flush=True)
            if args.once:
                # Machine gate: any dead/errored member fails the smoke.
                return 1 if view["errors"] else 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
