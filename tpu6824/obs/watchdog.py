"""watchdog — rule evaluation over pulse series, with evidence capture.

The nemesis harness made INJECTED failures debuggable: every failing
soak ships a ReplayArtifact with the fault timeline, the flight ring,
and the fleet snapshot.  A LIVE incident had nothing — by the time a
human polls stats(), the stall is minutes old and the flight ring has
rotated past the interesting part.  The watchdog closes that asymmetry:
it rides the pulse sampling clock (observer, no thread of its own), and
the moment a rule trips it freezes the evidence — flight-recorder dump,
`stats()` with the stall diagnosis, the triggering series window, the
environment — into the SAME artifact format nemesis failures use
(`ReplayArtifact.to_dict` shell), written under `TPU6824_WATCHDOG_DIR`.
A live incident replays like an injected one.

Rules (thresholds via env, see TUNING):

  - ``stalled-groups``      — stats()["health"]["stalled_groups"] is
    non-empty; the bundle carries the kernelscope per-group diagnosis.
  - ``throughput-collapse`` — the fabric.decided_cells rate fell below
    `TPU6824_WD_COLLAPSE_FRAC` of its earlier-window rate while that
    earlier rate was above `TPU6824_WD_MIN_RATE` (an idle fabric is not
    a collapse).
  - ``latency-spike``       — any per-interval latency p99 series rose
    `TPU6824_WD_SPIKE_FACTOR`× (default 4 = two log2 buckets — one
    bucket is quantization noise) over its window median.  The bundle
    names the CULPRIT STAGE (ISSUE 15): the opscope waterfall series
    with the widest p99 delta in the triggering window rides
    `watchdog.evidence.culprit_stage`, so a spike says `apply` (or
    `dispatch`, or `flush`), not just "something got slow".
  - ``queue-growth``        — feed_depth_max grew monotonically across
    the window and ended above `TPU6824_WD_FEED_DEPTH`.
  - ``thread-crashes``      — crashsink reported a NEW daemon-thread
    death since the watchdog armed.
  - ``dropped-climbing``    — fabric.events.dropped / obs.flight.dropped
    climbing faster than `TPU6824_WD_DROP_RATE`/s (telemetry is eating
    its own evidence).
  - ``jit-recompile``       — jitguard.compiles climbing AFTER the rule
    observed a warmed state (a busy, compile-free window past the
    `TPU6824_WD_JIT_GRACE` arming delay): steady state must be
    zero-compile, but first-touch compiles from traffic arriving at any
    time are warmup, not an incident.
  - ``retry-storm``         — frontend retries/timeouts climbing while
    goodput (frontend.ops rate) falls: the self-amplifying overload
    signature netfault's overload protection exists to prevent
    (`TPU6824_WD_RETRY_RATE` floor keeps ordinary failover retries
    quiet).
  - ``abort-storm``         — txn aborts climbing while commits fall
    (ISSUE 13): the 2PC layer burning its work on lock conflicts /
    recovery aborts instead of committing (`TPU6824_WD_ABORT_RATE`
    floor keeps ordinary optimistic-CAS retries quiet).
  - ``memory-growth``       — process RSS with a sustained positive
    slope over `TPU6824_WD_MEM_WINDOW` while traffic stays flat
    (ISSUE 14): host state outrunning the horizon compaction machinery
    — the leak signature, not a warming working set
    (`TPU6824_WD_MEM_MIN_BYTES` keeps allocator jitter quiet).

Default-off like tracing: a watchdog only exists when constructed, and
evaluation is sampling-clock granular — no per-op cost anywhere.
Stdlib-only; ReplayArtifact is imported lazily at fire time (harness
imports obs, not the other way around).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from tpu6824.obs import blackbox as _blackbox
from tpu6824.obs import pulse as _pulse
from tpu6824.utils import crashsink

__all__ = ["Watchdog", "Rule", "default_rules", "SCHEMA_VERSION"]

SCHEMA_VERSION = "watchdog-1.0.0"


def _envf(name: str, default: float) -> float:
    return float(os.environ.get(name, default))


class Rule:
    """One watchdog rule: `check(wd)` returns a human-readable reason
    string when triggered, else None.  Subclasses read series through
    `wd.points/last` and the freshest stats through `wd.stats()`.
    A rule may set `self.evidence` (a JSON-safe dict) during a
    triggering check — it rides the bundle's `watchdog.evidence` field
    (the latency-spike rule's per-stage culprit attribution)."""

    name = "rule"
    evidence: dict | None = None

    def check(self, wd: "Watchdog") -> str | None:
        raise NotImplementedError


class StalledGroups(Rule):
    name = "stalled-groups"

    def check(self, wd):
        h = (wd.stats() or {}).get("health") or {}
        stalled = h.get("stalled_groups") or []
        if not stalled:
            return None
        diag = h.get("stall_diagnosis") or {}
        first = diag.get(str(stalled[0]), "no diagnosis")
        return (f"groups {stalled} stalled "
                f"(g{stalled[0]}: {first})")


class ThroughputCollapse(Rule):
    name = "throughput-collapse"
    series = "fabric.decided_cells.rate"

    def __init__(self,
                 frac: float | None = None, min_rate: float | None = None):
        self.frac = _envf("TPU6824_WD_COLLAPSE_FRAC", 0.1) \
            if frac is None else frac
        self.min_rate = _envf("TPU6824_WD_MIN_RATE", 50.0) \
            if min_rate is None else min_rate

    def check(self, wd):
        pts = wd.points(self.series)
        if len(pts) < 4:
            return None
        half = len(pts) // 2
        before = sum(v for _, v in pts[:half]) / half
        after = sum(v for _, v in pts[half:]) / (len(pts) - half)
        if before > self.min_rate and after < before * self.frac:
            return (f"decided/s collapsed {before:.1f} -> {after:.1f} "
                    f"(< {self.frac:.0%} of the earlier window)")
        return None


class LatencySpike(Rule):
    name = "latency-spike"

    def __init__(self, factor: float | None = None,
                 min_us: float | None = None):
        self.factor = _envf("TPU6824_WD_SPIKE_FACTOR", 4.0) \
            if factor is None else factor
        # Absolute floor on the spiked value (the min_rate pattern),
        # applied to the OPSCOPE series (stage edges AND whole-op):
        # they sit at tens-of-µs scale where an ordinary scheduler
        # hiccup on a cgroup-capped box is 1-4ms — several log2
        # buckets and an easy 4x over a healthy median.  8192µs is
        # the first bucket safely above that noise band; a spike that
        # matters for the waterfall (the seeded 80ms apply stall, a
        # wedged flush) clears it by decades.  Other latency series
        # keep the pre-opscope contract (a 50µs service regressing
        # 16× must still fire).
        self.min_us = _envf("TPU6824_WD_SPIKE_MIN_US", 8192.0) \
            if min_us is None else min_us

    def _stage_evidence(self, wd) -> dict | None:
        """Name the CULPRIT STAGE (ISSUE 15): across the opscope
        waterfall's per-stage p99 series, the widest last-point-vs-
        window-median delta in the triggering window — so a latency
        spike's bundle says `apply` (or `dispatch`, or `flush`), not
        just "something got slow".  A culprit is only NAMED when some
        stage itself spiked (last ≥ median × factor, positive delta,
        AND clearing the min_us floor — the floor guards attribution
        exactly like it guards triggering, else a non-floored series'
        off-path incident could blame sub-floor stage jitter): a spike
        whose cause lives outside the staged request path (a
        clerk-side network stall) must not send the operator chasing
        whichever stage jittered widest."""
        deltas: dict[str, float] = {}
        spiked: set[str] = set()
        for name in wd.series_names():
            if not (name.startswith("opscope.stage.")
                    and name.endswith(".p99")):
                continue
            pts = wd.points(name)
            if len(pts) < 2:
                continue
            vals = sorted(v for _, v in pts[:-1])
            median = vals[len(vals) // 2]
            stage = name[len("opscope.stage."):].split(".", 1)[0]
            last = pts[-1][1]
            d = last - median
            if d > deltas.get(stage, float("-inf")):
                deltas[stage] = round(d, 3)
            if d > 0 and median > 0 and last >= median * self.factor \
                    and last >= self.min_us:
                spiked.add(stage)
        if not deltas:
            return None
        candidates = {s: deltas[s] for s in spiked}
        culprit = max(candidates, key=candidates.get) if candidates \
            else None
        return {"culprit_stage": culprit,
                "stage_p99_delta_us": deltas}

    def check(self, wd):
        for name in wd.series_names():
            if "latency" not in name or not name.endswith(".p99"):
                continue
            pts = wd.points(name)
            if len(pts) < 4:
                continue
            vals = sorted(v for _, v in pts[:-1])
            median = vals[len(vals) // 2]
            last = pts[-1][1]
            if median > 0 and last >= median * self.factor and (
                    last >= self.min_us
                    or not name.startswith("opscope.")):
                self.evidence = self._stage_evidence(wd)
                reason = (f"{name} spiked to {last:.0f} "
                          f"(median {median:.0f}, x{last / median:.1f})")
                if self.evidence is not None \
                        and self.evidence["culprit_stage"] is not None:
                    reason += (f"; culprit stage: "
                               f"{self.evidence['culprit_stage']}")
                return reason
        return None


class ShardDispatchSkew(Rule):
    """One mesh shard's dispatch tail diverging from the fleet (meshfab):
    the per-shard opscope dispatch histograms
    (`opscope.stage.dispatch.shard<k>.latency_us.p99`) should track each
    other on a healthy mesh — the fused dispatch is one device program.
    A shard whose p99 runs ≥ `factor`× the FLEET MEDIAN of the same tick
    means that shard's groups are being served slower: a hot shard
    (placement imbalance the group ladder should have spread), a slices'
    DCN link degrading, or one device throttling.  Needs at least 3
    shard series (a median of 2 is just the other shard) and the same
    absolute µs floor as the spike rule, so scheduler jitter on nearly-
    idle shards never pages anyone."""

    name = "shard-dispatch-skew"
    _prefix = "opscope.stage.dispatch.shard"

    def __init__(self, factor: float | None = None,
                 min_us: float | None = None):
        self.factor = _envf("TPU6824_WD_SHARD_SKEW_FACTOR", 4.0) \
            if factor is None else factor
        self.min_us = _envf("TPU6824_WD_SPIKE_MIN_US", 8192.0) \
            if min_us is None else min_us

    def check(self, wd):
        last: dict[str, float] = {}
        for name in wd.series_names():
            if not (name.startswith(self._prefix)
                    and name.endswith(".latency_us.p99")):
                continue
            pts = wd.points(name)
            if pts:
                shard = name[len(self._prefix):].split(".", 1)[0]
                last[shard] = pts[-1][1]
        if len(last) < 3:
            return None
        vals = sorted(last.values())
        fleet = vals[len(vals) // 2]
        if fleet <= 0:
            return None
        worst = max(last, key=last.get)
        w = last[worst]
        if w >= fleet * self.factor and w >= self.min_us:
            self.evidence = {"shard": worst,
                             "shard_p99_us": {k: round(v, 1)
                                              for k, v in last.items()},
                             "fleet_median_us": round(fleet, 1)}
            return (f"shard {worst} dispatch p99 {w:.0f}us is "
                    f"x{w / fleet:.1f} the fleet median ({fleet:.0f}us)")
        return None


class QueueGrowth(Rule):
    name = "queue-growth"
    # Consumer-side depth gauges: the fabric's decided-feed depth, the
    # native ingest path's in-flight op count (ISSUE 11 — a stuck reply
    # ring shows as inflight_ops climbing monotonically while the engine
    # keeps mirroring the gauge), and the in-flight transaction gauge
    # (ISSUE 13 — transactions piling up means prepares are outliving
    # their resolvers: a wedged coordinator or a lock convoy).
    series = ("fabric.health.feed_depth_max",
              "frontend.native_ingest.inflight_ops",
              "txn.inflight")
    # Occupancy FRACTIONS ride the same monotone-growth check with
    # their own threshold: the devapply key-table load (ISSUE 16) names
    # a near-full device table before the hard capacity raise — past
    # ~0.85 the engine rebases, so sustained growth toward the limit
    # means the keyspace is outgrowing TPU6824_DEVAPPLY_SLOTS.
    frac_series = ("devapply.table_load_frac",)

    def __init__(self, limit: float | None = None,
                 frac_limit: float | None = None):
        self.limit = _envf("TPU6824_WD_FEED_DEPTH", 1024.0) \
            if limit is None else limit
        self.frac_limit = _envf("TPU6824_WD_TABLE_LOAD", 0.7) \
            if frac_limit is None else frac_limit

    def check(self, wd):
        for name, limit in [(n, self.limit) for n in self.series] \
                + [(n, self.frac_limit) for n in self.frac_series]:
            pts = wd.points(name)
            if len(pts) < 3 or pts[-1][1] < limit:
                continue
            vs = [v for _, v in pts]
            if all(b >= a for a, b in zip(vs, vs[1:])) and vs[-1] > vs[0]:
                return (f"{name} grew {vs[0]:.3g} -> {vs[-1]:.3g} over "
                        f"the window (consumer falling behind)")
        return None


class ThreadCrashes(Rule):
    name = "thread-crashes"

    def check(self, wd):
        cur = crashsink.summary().get("count", 0)
        if cur > wd.crash_base:
            return (f"{cur - wd.crash_base} daemon thread(s) died since "
                    "the watchdog armed")
        return None


class DroppedClimbing(Rule):
    name = "dropped-climbing"
    series = ("fabric.events.dropped", "obs.flight.dropped")

    def __init__(self, rate: float | None = None):
        self.rate = _envf("TPU6824_WD_DROP_RATE", 100.0) \
            if rate is None else rate

    def check(self, wd):
        for name in self.series:
            pts = wd.points(name)
            if len(pts) < 2:
                continue
            (t0, v0), (t1, v1) = pts[0], pts[-1]
            dt = max(t1 - t0, 1e-9)
            r = (v1 - v0) / dt
            if r > self.rate:
                return (f"{name} climbing at {r:.0f}/s "
                        f"(> {self.rate:.0f}/s): the ring is eating "
                        "evidence faster than it is read")
        return None


class JitRecompile(Rule):
    name = "jit-recompile"
    series = "jitguard.compiles.rate"
    busy_series = "fabric.decided_cells.rate"

    def __init__(self, grace: float | None = None):
        self.grace = _envf("TPU6824_WD_JIT_GRACE", 10.0) \
            if grace is None else grace
        # Steady state is OBSERVED, not assumed: the rule arms only
        # after a busy (deciding), compile-free window — first-touch
        # compiles from traffic arriving at any time are warmup, and a
        # wall-clock grace alone cannot know when warmup happened (a
        # fabricd idling 30s before its first clerk would false-fire).
        self._steady = False

    def check(self, wd):
        if wd.uptime() < self.grace:
            return None  # early compiles are expected regardless
        compiles = sum(v for _, v in wd.points(self.series,
                                               window=wd.window))
        busy = sum(v for _, v in wd.points(self.busy_series,
                                           window=wd.window)) > 0
        if compiles == 0:
            if busy:
                self._steady = True  # warmed: busy window, no compiles
            return None
        if not self._steady:
            return None  # still warming (cold shapes arriving)
        return ("backend recompiles in steady state (jitguard counter "
                "climbing after a warmed, compile-free busy window) — "
                "a shape/static-arg is varying per dispatch")


class RetryStorm(Rule):
    """Retry amplification on the clerk path (ISSUE 12): the retry (or
    timeout) rate climbing across the window while goodput falls.  Both
    halves matter — retries alone spike benignly on any failover, and
    falling goodput alone is throughput-collapse's job; the STORM
    signature is work shifting from serving ops to re-proposing them."""

    name = "retry-storm"
    retries = "frontend.retries.rate"
    timeouts = "frontend.timeouts.rate"
    goodput = "frontend.ops.rate"

    def __init__(self, min_rate: float | None = None,
                 climb: float = 1.5, fall: float = 0.5):
        # Floor on the late-window retry+timeout rate: ordinary
        # failover retries (a killed replica, one partition) stay quiet.
        self.min_rate = _envf("TPU6824_WD_RETRY_RATE", 50.0) \
            if min_rate is None else min_rate
        self.climb = climb
        self.fall = fall

    @staticmethod
    def _halves(pts):
        half = len(pts) // 2
        before = sum(v for _, v in pts[:half]) / max(half, 1)
        after = sum(v for _, v in pts[half:]) / max(len(pts) - half, 1)
        return before, after

    def check(self, wd):
        good = wd.points(self.goodput)
        if len(good) < 4:
            return None
        g_before, g_after = self._halves(good)
        if g_before <= 0 or g_after >= g_before * self.fall:
            return None  # goodput holding: churn, not a storm
        for name in (self.retries, self.timeouts):
            pts = wd.points(name)
            if len(pts) < 4:
                continue
            r_before, r_after = self._halves(pts)
            if r_after >= self.min_rate and \
                    r_after >= max(r_before, 1e-9) * self.climb:
                return (f"{name} climbed {r_before:.1f} -> "
                        f"{r_after:.1f}/s while goodput fell "
                        f"{g_before:.1f} -> {g_after:.1f}/s "
                        "(retries amplifying, not recovering)")
        return None


class AbortStorm(Rule):
    """Transactional churn amplification (ISSUE 13): the txn abort rate
    climbing across the window while the commit rate falls.  Both
    halves matter — aborts alone spike benignly on any contention burst
    (the CAS-retry loop is SUPPOSED to abort and retry), and falling
    commits alone is throughput-collapse's job; the STORM signature is
    the 2PC layer burning its work on lock conflicts and recovery
    aborts instead of committing (a deadlocked key convoy, a wedged
    coordinator group, or a reconfiguration livelock)."""

    name = "abort-storm"
    aborts = "txn.abort.rate"
    commits = "txn.commit.rate"

    def __init__(self, min_rate: float | None = None,
                 climb: float = 1.5, fall: float = 0.5):
        # Floor on the late-window abort rate: ordinary optimistic-CAS
        # retries under mild contention stay quiet.
        self.min_rate = _envf("TPU6824_WD_ABORT_RATE", 20.0) \
            if min_rate is None else min_rate
        self.climb = climb
        self.fall = fall

    def check(self, wd):
        commits = wd.points(self.commits)
        if len(commits) < 4:
            return None
        c_before, c_after = RetryStorm._halves(commits)
        if c_before <= 0 or c_after >= c_before * self.fall:
            return None  # commits holding: contention, not a storm
        aborts = wd.points(self.aborts)
        if len(aborts) < 4:
            return None
        a_before, a_after = RetryStorm._halves(aborts)
        if a_after >= self.min_rate and \
                a_after >= max(a_before, 1e-9) * self.climb:
            return (f"txn aborts climbed {a_before:.1f} -> "
                    f"{a_after:.1f}/s while commits fell "
                    f"{c_before:.1f} -> {c_after:.1f}/s "
                    "(2PC work burning on aborts, not committing)")
        return None


class MemoryGrowth(Rule):
    """Host-memory leak signature (ISSUE 14, horizon): process RSS with
    a SUSTAINED positive slope across `TPU6824_WD_MEM_WINDOW` while
    traffic stays flat.  Both halves matter — RSS climbing WITH traffic
    is a workload growing its working set (caches warming, batches
    widening), and flat RSS under any traffic is exactly what the
    compaction machinery exists to guarantee; the LEAK signature is
    memory growing when the offered load is not.  The growth floor
    (`TPU6824_WD_MEM_MIN_BYTES`) keeps allocator jitter and gc cycles
    quiet."""

    name = "memory-growth"
    rss = "proc.rss_bytes"
    traffic = "fabric.decided_cells.rate"

    def __init__(self, window: float | None = None,
                 min_growth: float | None = None,
                 flat_band: float = 1.25, rise_frac: float = 0.8):
        self.window = _envf("TPU6824_WD_MEM_WINDOW", 30.0) \
            if window is None else float(window)
        self.min_growth = _envf("TPU6824_WD_MEM_MIN_BYTES",
                                float(32 << 20)) \
            if min_growth is None else float(min_growth)
        self.flat_band = flat_band
        self.rise_frac = rise_frac

    def check(self, wd):
        pts = wd.points(self.rss, window=self.window)
        if len(pts) < 6:
            return None
        vs = [v for _, v in pts]
        half = len(vs) // 2
        before = sum(vs[:half]) / half
        after = sum(vs[half:]) / (len(vs) - half)
        if after - before < self.min_growth:
            return None
        # SUSTAINED: most consecutive deltas STRICTLY positive (RSS is
        # near-monotone, so counting flats would make this a no-op and
        # a one-off allocation step — one big delta, then flat — would
        # read as a slope).
        rises = sum(1 for a, b in zip(vs, vs[1:]) if b > a)
        if rises < self.rise_frac * (len(vs) - 1):
            return None
        tr = wd.points(self.traffic, window=self.window)
        if len(tr) >= 4:
            t_before, t_after = RetryStorm._halves(tr)
            if t_before > 0 and t_after > t_before * self.flat_band:
                return None  # traffic growing: working set, not a leak
        return (f"rss grew {before / 1e6:.1f}MB -> {after / 1e6:.1f}MB "
                f"over the window with traffic flat "
                "(host state outrunning compaction)")


def default_rules() -> list[Rule]:
    return [StalledGroups(), ThroughputCollapse(), LatencySpike(),
            ShardDispatchSkew(), QueueGrowth(), ThreadCrashes(),
            DroppedClimbing(), JitRecompile(), RetryStorm(), AbortStorm(),
            MemoryGrowth()]


class Watchdog:
    """Evaluates rules after every pulse sample; on trigger writes an
    evidence bundle and remembers the incident.  Per-rule cooldown
    (`TPU6824_WD_COOLDOWN`) stops a sustained condition from emitting a
    bundle per tick; the incident ring is bounded."""

    def __init__(self, pulse, outdir: str | None = None,
                 rules: list[Rule] | None = None,
                 window: float | None = None,
                 cooldown: float | None = None, max_incidents: int = 64):
        self.pulse = pulse
        self.outdir = outdir or os.environ.get("TPU6824_WATCHDOG_DIR",
                                               "/tmp")
        self.rules = default_rules() if rules is None else list(rules)
        self.window = (_envf("TPU6824_WD_WINDOW", 0.0)
                       or max(2.0, 5 * pulse.interval)) \
            if window is None else float(window)
        self.cooldown = _envf("TPU6824_WD_COOLDOWN", 30.0) \
            if cooldown is None else float(cooldown)
        self.incidents: deque = deque(maxlen=max_incidents)
        self._mu = threading.Lock()
        self._last_fire: dict[str, float] = {}
        self._seq = 0
        self._armed_at: float | None = None
        self.crash_base = 0

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "Watchdog":
        self._armed_at = time.monotonic()
        self.crash_base = crashsink.summary().get("count", 0)
        # Best effort: make sure the jitguard compile listener is
        # counting (needs jax.monitoring; absent on a JAX-less poller,
        # in which case the jit rule simply never sees a series).
        try:
            from tpu6824.analysis import jitguard
            jitguard._ensure_listener()
        except Exception:  # noqa: BLE001 — optional evidence source
            pass
        self.pulse.add_observer(self._on_sample)
        return self

    def stop(self) -> None:
        self.pulse.remove_observer(self._on_sample)

    def uptime(self) -> float:
        return 0.0 if self._armed_at is None \
            else time.monotonic() - self._armed_at

    # --------------------------------------------------- rule-side reads

    def points(self, name: str, window: float | None = None) -> list:
        return self.pulse.points(name,
                                 window=self.window if window is None
                                 else window)

    def last(self, name: str):
        return self.pulse.last(name)

    def series_names(self) -> list[str]:
        return self.pulse.names()

    def stats(self) -> dict | None:
        return self.pulse.last_stats

    # ----------------------------------------------------------- evaluate

    def _on_sample(self, pulse, now: float) -> None:
        for rule in self.rules:
            last = self._last_fire.get(rule.name)
            if last is not None and now - last < self.cooldown:
                continue
            try:
                reason = rule.check(self)
            except Exception as e:  # noqa: BLE001 — one broken rule must
                # not blind the others; recorded, not fatal.
                crashsink.record(f"watchdog[{rule.name}]", e, fatal=False)
                continue
            if reason:
                self._last_fire[rule.name] = now
                self._fire(rule, reason, now)

    def _fire(self, rule: Rule, reason: str, now: float) -> None:
        with self._mu:
            self._seq += 1
            seq = self._seq
        incident = {"rule": rule.name, "reason": reason,
                    "t_mono": round(now, 6),
                    "detected_after_s": round(self.uptime(), 3),
                    "seq": seq, "path": None}
        # Fire-time evidence into the LOCAL blackbox ring (ISSUE 20),
        # BEFORE the bundle write: the full bundle only exists when the
        # disk cooperates, but the incident core must survive the
        # process — synced immediately so it is durable at detection
        # time, not one cadence later.
        _blackbox.record("watchdog", {
            "rule": rule.name, "reason": reason,
            "evidence": getattr(rule, "evidence", None),
            "t_mono": round(now, 6),
            "detected_after_s": round(self.uptime(), 3), "seq": seq})
        _blackbox.sync()
        try:
            incident["path"] = self._write_bundle(rule, reason, now, seq)
        except Exception as e:  # noqa: BLE001 — evidence capture must
            # never kill the sampling clock; the incident still records.
            incident["error"] = repr(e)[:200]
            crashsink.record("watchdog-bundle", e, fatal=False)
        self.incidents.append(incident)

    def _write_bundle(self, rule: Rule, reason: str, now: float,
                      seq: int) -> str:
        # Lazy import: obs stays importable standalone; the artifact
        # SHELL (flight ring, schema stamps) is the nemesis one, so a
        # live incident and an injected failure read identically.
        from tpu6824.harness.nemesis import ReplayArtifact

        art = ReplayArtifact(test=f"watchdog:{rule.name}")
        art.attach(watchdog_rule=rule.name, reason=reason)
        d = art.to_dict()
        stats = self.stats()
        health = (stats or {}).get("health") or {}
        d["watchdog"] = {
            "schema": SCHEMA_VERSION,
            "rule": rule.name,
            "reason": reason,
            # Rule-specific structured evidence (the latency-spike
            # rule's culprit-stage attribution, ISSUE 15); None for
            # rules that carry everything in the reason string.
            "evidence": getattr(rule, "evidence", None),
            "t_mono": round(now, 6),
            "detected_after_s": round(self.uptime(), 3),
            "window_s": self.window,
            # The triggering series window: every series' points over
            # the detection window, timestamp-joinable to the flight
            # ring (ts/1e9) and the nemesis timeline (t0 + wall).
            "series_window": self.pulse.series(
                window=self.window)["series"],
            "stats": stats,
            "stall_diagnosis": health.get("stall_diagnosis") or {},
            "environment": _pulse.environment_snapshot(),
        }
        path = os.path.join(self.outdir,
                            f"watchdog-{rule.name}-{seq}.json")
        # tpusan: ok(blocking-io-in-telemetry-path) — fire-time evidence
        # capture: at most one bundle per rule per cooldown (30s), and
        # by the time a rule fires the clock's cadence is already the
        # least interesting thing about the process
        with open(path, "w") as f:
            json.dump(d, f, indent=1, default=str)
        return path

    # ------------------------------------------------------------- status

    def status(self) -> dict:
        return {"schema": SCHEMA_VERSION,
                "rules": [r.name for r in self.rules],
                "window_s": self.window, "cooldown_s": self.cooldown,
                "uptime_s": round(self.uptime(), 3),
                "incidents": list(self.incidents)}
