"""benchdiff — the BENCH_r*.json regression gate (kernelscope, ISSUE 6).

    python -m tpu6824.obs.benchdiff OLD.json NEW.json [--tol-scale S]
                                    [--json] [--allow-missing] [--force]

Compares two bench artifacts per leg/metric with per-metric noise
thresholds and exits non-zero iff any metric regressed past its
threshold — the one command that makes ROADMAP item 1's "≥5×" claim
(and every future perf PR) checkable against the recorded trajectory.

Artifact formats: the bare bench line (BENCH_r06+) and the older
driver wrapper `{n, cmd, rc, tail, parsed}` (r01–r05) — wrapped
artifacts are unwrapped via `parsed`, falling back to the last JSON
line of `tail` (the same salvage rule bench.py's parent applies).

Thresholds are PER METRIC, calibrated on the recorded trajectory of
THIS box rather than wished-for precision: between the real r06 and
r07 artifacts the wire legs swung −40…−53% and thread-per-clerk −55%
under full-suite CPU contention (CHANGES PR 2/5), while the device
legs held within ~10%.  A gate tighter than a leg's demonstrated noise
floor would cry wolf on every PR, so noisy host-bound legs get wide
tolerances and the device-path legs get tight ones; `--tol-scale`
widens/narrows all of them together (e.g. 0.5 for a quiet dedicated
box).  Histogram-derived latencies (the per-leg tpuscope sections'
p50/p95/p99) come from log2 buckets, so a single bucket-boundary
wobble reads as exactly 2×: their thresholds sit above 2× and below
the 4× a real two-bucket regression costs.

Verdicts per metric: ok / improved / REGRESSED / suspect-environment /
skipped(<why>).  A metric the old artifact reported but the new one
lost (leg errored or vanished) is a regression by default — a leg that
stops reporting is how a perf break hides — `--allow-missing` demotes
that to a skip.  Artifacts from different platforms (or different
headline shapes, for the shape-dependent metrics) are not comparable;
incomparable metrics are skipped loudly, and `--force` compares them
anyway.

Environment awareness (pulse, ISSUE 10): bench artifacts carry an
`environment` block — cgroup cpu quota/shares, load averages, and
fixed-work calibration spins taken at every leg boundary
(obs/pulse.py).  When the NEW run's box demonstrably degraded against
the baseline's (calibration spins ≥1.5× slower, quota shrunk, or the
spins unstable within the run — the r08 failure mode, where
service.value "regressed" −55% with zero code change), a would-be
REGRESSED verdict on a HOST-BOUND metric is demoted to
`suspect-environment`: annotated with the evidence, excluded from the
exit-1 count, and re-judgeable on a quiet box.  Device-path metrics
are never demoted (the kernel doesn't share the box's Python
scheduler), so an injected real regression on the headline still exits
1; `--strict-env` restores hard gating everywhere.

Stdlib-only like the rest of obs/ — runnable on artifacts from any
machine without JAX installed.
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = ["METRICS", "Metric", "compare", "env_suspicion",
           "load_artifact", "main"]


class Metric:
    """One comparable artifact entry.

    path: key segments into the artifact dict (segments, not a dotted
    string — tpuscope metric names contain dots themselves).
    higher_is_better: regression direction.
    tol: allowed relative slip in the bad direction before the verdict
    is REGRESSED (0.30 = new may be up to 30% worse than old).
    shape_dependent: only comparable when the two artifacts ran the
    same headline shape (the `metric` string embeds G/I/window).
    leg_shape: paths to the LEG's own recorded shape keys (e.g. the
    service leg's `shape` dict, the clerk leg's groups/width) — the
    metric is only comparable when every one matches, so a trimmed
    BENCH_SERVICE_GROUPS run never false-alarms against a full-shape
    recorded artifact.
    host_bound: the metric's bottleneck is the host Python/socket path,
    not the device kernel — exactly the legs the box's scheduler share
    moves 2-5× (r08).  Only host-bound regressions are demotable to
    `suspect-environment` when the environment blocks disagree.
    """

    def __init__(self, path, tol, higher_is_better=True,
                 shape_dependent=False, leg_shape=(), host_bound=False):
        self.path = tuple(path)
        self.tol = tol
        self.higher_is_better = higher_is_better
        self.shape_dependent = shape_dependent
        self.leg_shape = tuple(tuple(p) for p in leg_shape)
        self.host_bound = host_bound

    @property
    def name(self) -> str:
        return "/".join(self.path)


# Calibration notes inline: tolerances are the observed run-to-run swing
# on the recorded trajectory plus margin, per leg class.
METRICS = [
    # Device-path throughput: steady within ~10% run-to-run (r06→r07:
    # +9.7% / −5.0% / +5.2%).
    Metric(("value",), 0.25, shape_dependent=True),
    Metric(("contended", "value"), 0.25, shape_dependent=True),
    Metric(("contended_lossy", "value"), 0.30, shape_dependent=True),
    Metric(("roofline_memres", "decided_per_sec"), 0.35),
    # Livelock price: steps-to-decide under loss (lower is better;
    # p50/p95 have sat at 1.0/2.0 for three artifacts).
    Metric(("contended_lossy", "steps_to_decide", "p50"), 0.5,
           higher_is_better=False, shape_dependent=True),
    Metric(("contended_lossy", "steps_to_decide", "p95"), 0.5,
           higher_is_better=False, shape_dependent=True),
    # Service/clerk legs: host-bound, contention-noisy (clerk −22.8%
    # r06→r07 with no code regression).  Each gates on its OWN leg
    # shape — env-trimmed runs (BENCH_SERVICE_GROUPS=16 in the bench
    # contract test) must skip, not false-alarm.
    Metric(("service", "value"), 0.35, host_bound=True,
           leg_shape=[("service", "shape")]),
    Metric(("service", "clerk", "value"), 0.45, host_bound=True,
           leg_shape=[("service", "clerk", "groups"),
                      ("service", "clerk", "width")]),
    # Batched frontend leg (ISSUE 8): host-edge noisy like the clerk leg
    # (the box's effective CPU swings 2-3× run to run — measured during
    # r08 bring-up), gated on its OWN sweep shape so env-trimmed
    # contract runs (BENCH_FE_GROUPS=2, 2x32 sweep) skip loudly.  First
    # recorded artifact (r08) baselines it: r07 has no leg → this entry
    # reports skipped(no-baseline) once, then gates every round after.
    Metric(("service", "clerk_frontend", "value"), 0.65, host_bound=True,
           leg_shape=[("service", "clerk_frontend", "groups"),
                      ("service", "clerk_frontend", "conns"),
                      ("service", "clerk_frontend", "batch_width")]),
    # Native zero-GIL ingest (ISSUE 11): the pickle-decode control point
    # and the native/pickle speedup ratio.  Both host-edge; the ratio is
    # measured on ONE box in ONE window, so it is steadier than either
    # absolute number but still scheduler-share-sensitive under load.
    # First recorded artifact (r09) baselines; gates thereafter.
    Metric(("service", "clerk_frontend", "native_ingest",
            "control_pickle", "value"), 0.65, host_bound=True,
           leg_shape=[("service", "clerk_frontend", "groups"),
                      ("service", "clerk_frontend", "conns"),
                      ("service", "clerk_frontend", "batch_width")]),
    Metric(("service", "clerk_frontend", "native_ingest", "speedup"),
           0.50, host_bound=True,
           leg_shape=[("service", "clerk_frontend", "groups"),
                      ("service", "clerk_frontend", "conns"),
                      ("service", "clerk_frontend", "batch_width")]),
    Metric(("service", "clerk_frontend", "latency", "p50_ms"), 0.65,
           higher_is_better=False, host_bound=True,
           leg_shape=[("service", "clerk_frontend", "groups"),
                      ("service", "clerk_frontend", "conns"),
                      ("service", "clerk_frontend", "batch_width")]),
    # opscope waterfall (ISSUE 15): the leg's whole-op p99 and the apply
    # stage's p99 — host-edge noisy like every clerk-path number, and
    # log2-bucket quantized like the tpuscope percentile entries (one
    # bucket = 2× is noise, two buckets = 4× is real — gate between).
    # Leg-shape-gated on the fe sweep shape; first recorded artifact
    # baselines them, gated thereafter.
    Metric(("service", "clerk_frontend", "waterfall", "total_p99_us"),
           2.0, higher_is_better=False, host_bound=True,
           leg_shape=[("service", "clerk_frontend", "groups"),
                      ("service", "clerk_frontend", "conns"),
                      ("service", "clerk_frontend", "batch_width")]),
    Metric(("service", "clerk_frontend", "waterfall", "stages", "apply",
            "p99_us"), 2.0, higher_is_better=False, host_bound=True,
           leg_shape=[("service", "clerk_frontend", "groups"),
                      ("service", "clerk_frontend", "conns"),
                      ("service", "clerk_frontend", "batch_width")]),
    # devapply (ISSUE 16): the host-dict control arm at the best shape
    # and the on/off speedup ratio — host-edge noisy like every
    # clerk-path number; the ratio is one-box one-window like the
    # ingest speedup, so steadier than either absolute value.
    # Leg-shape-gated on the fe sweep shape; first recorded artifact
    # (r10) baselines them, gated thereafter.
    Metric(("service", "clerk_frontend", "devapply", "control_off",
            "value"), 0.65, host_bound=True,
           leg_shape=[("service", "clerk_frontend", "groups"),
                      ("service", "clerk_frontend", "conns"),
                      ("service", "clerk_frontend", "batch_width")]),
    Metric(("service", "clerk_frontend", "devapply", "speedup"), 0.50,
           host_bound=True,
           leg_shape=[("service", "clerk_frontend", "groups"),
                      ("service", "clerk_frontend", "conns"),
                      ("service", "clerk_frontend", "batch_width")]),
    # blackbox recorder A/B (ISSUE 20): throughput at the best shape
    # WITH the flight-data recorder live — the arm whose collapse would
    # mean the recorder leaked blocking work onto the request path.
    # Host-edge noisy like every clerk-path number (0.65).  The
    # overhead_frac itself is NOT gated: it hovers at ~0 by design, and
    # a relative tolerance on a near-zero difference of two noisy
    # numbers is pure alarm — the on-arm absolute throughput is the
    # meaningful gate.  First recorded artifact (r12) baselines it.
    Metric(("service", "clerk_frontend", "blackbox", "overhead_ab",
            "on_ops_s"), 0.65, host_bound=True,
           leg_shape=[("service", "clerk_frontend", "groups"),
                      ("service", "clerk_frontend", "conns"),
                      ("service", "clerk_frontend", "batch_width")]),
    # Overload leg (ISSUE 12, netfault): goodput under 4× offered load
    # and the measured closed-loop capacity it is relative to.  Both
    # host-edge noisy like every clerk-path leg; gated on the leg's OWN
    # shape (env-trimmed contract runs skip loudly).  First recorded
    # artifact baselines them; gated thereafter.
    Metric(("service", "overload", "value"), 0.65, host_bound=True,
           leg_shape=[("service", "overload", "shape")]),
    Metric(("service", "overload", "capacity_ops_s"), 0.65,
           host_bound=True,
           leg_shape=[("service", "overload", "shape")]),
    # Fleet storm leg (ISSUE 18, fleetfe): goodput through the
    # kill/revive storm and the fleet's measured closed-loop capacity.
    # Host-edge noisy like every clerk-path leg AND nemesis-phased (a
    # third of the leg runs one frontend down), so the widest service
    # tolerance; gated on the leg's OWN shape (env-trimmed contract
    # runs skip loudly).  First recorded artifact baselines them;
    # gated thereafter.
    Metric(("service", "fleet", "value"), 0.65, host_bound=True,
           leg_shape=[("service", "fleet", "shape")]),
    Metric(("service", "fleet", "capacity_ops_s"), 0.65,
           host_bound=True,
           leg_shape=[("service", "fleet", "shape")]),
    # Transaction leg (ISSUE 13, txnkv): cross-shard 2PC commit
    # throughput + commit-latency tail — host-edge noisy like every
    # clerk-path leg (contention makes it swing further), gated on the
    # leg's OWN shape (a BENCH_TXN_ACCOUNTS-trimmed contract run must
    # skip loudly, not false-alarm).  First recorded artifact baselines
    # them; gated thereafter.
    Metric(("service", "txn", "value"), 0.65, host_bound=True,
           leg_shape=[("service", "txn", "shape")]),
    Metric(("service", "txn", "latency", "p99_ms"), 0.65,
           higher_is_better=False, host_bound=True,
           leg_shape=[("service", "txn", "shape")]),
    # horizon catch-up micro-leg (ISSUE 14): missed-ops/s recovered via
    # snapshot-install at the deepest depth, and the deepest install
    # wall time — host-edge tolerance, gated on the leg's own recorded
    # depth shape, baselined at the first artifact that carries them.
    Metric(("service", "catchup", "value"), 0.65, host_bound=True,
           leg_shape=[("service", "catchup", "shape")]),
    Metric(("service", "catchup", "install_ms_deepest"), 0.65,
           higher_is_better=False, host_bound=True,
           leg_shape=[("service", "catchup", "shape")]),
    # meshfab (ISSUE 17): sharded real-path decided/s from the
    # MULTICHIP_r07+ artifacts — the live fabric (pump loop, compact io,
    # GC) hosted on the fabric_mesh quorum-sharded shapes at forced host
    # device counts ({g:4,p:3}=12, {g:8,p:3}=24).  Forced-host "devices"
    # are CPU threads sharing one box, so these are host-bound-noisy
    # like every clerk-path leg; gated on the leg's own recorded mesh +
    # group shape so a trimmed run skips, not false-alarms.  First
    # recorded artifact (r07) baselines them; gated thereafter.
    Metric(("meshfab", "g4p3", "decided_per_sec"), 0.65, host_bound=True,
           leg_shape=[("meshfab", "g4p3", "mesh"),
                      ("meshfab", "g4p3", "groups"),
                      ("meshfab", "g4p3", "window")]),
    Metric(("meshfab", "g8p3", "decided_per_sec"), 0.65, host_bound=True,
           leg_shape=[("meshfab", "g8p3", "mesh"),
                      ("meshfab", "g8p3", "groups"),
                      ("meshfab", "g8p3", "window")]),
    # Host-edge legs: the demonstrated noise floor is −55% (wire
    # −40%/−53%, thread-per-clerk −55% between real artifacts).
    Metric(("wire", "value"), 0.65, host_bound=True),
    Metric(("wire", "pooled"), 0.65, host_bound=True),
    Metric(("service", "clerk", "thread_per_clerk", "value"), 0.65,
           host_bound=True, leg_shape=[("service", "clerk", "groups")]),
    # Clerk op latency (lower is better; ms percentiles from the timed
    # window — host-bound like the throughput above).
    Metric(("service", "clerk", "latency", "p50_ms"), 0.65,
           higher_is_better=False, host_bound=True,
           leg_shape=[("service", "clerk", "groups"),
                      ("service", "clerk", "width")]),
    Metric(("service", "clerk", "latency", "p95_ms"), 0.65,
           higher_is_better=False, host_bound=True,
           leg_shape=[("service", "clerk", "groups"),
                      ("service", "clerk", "width")]),
    # Recovery leg (durafault): restore-from-snapshot wall time — host
    # + disk bound, so it gets the host-edge noise floor; gates on its
    # own shape like the service legs (a BENCH_RECOVERY_GROUPS-trimmed
    # run must skip, not false-alarm).
    Metric(("recovery", "recovery_time_ms", "p50"), 0.65,
           higher_is_better=False, host_bound=True,
           leg_shape=[("recovery", "shape")]),
    Metric(("recovery", "recovery_time_ms", "p95"), 0.65,
           higher_is_better=False, host_bound=True,
           leg_shape=[("recovery", "shape")]),
    # Per-leg tpuscope histogram percentiles (new in kernelscope): log2
    # buckets quantize to powers of two, so anything under one bucket
    # (2×) is noise and two buckets (4×) is real — gate between them.
    Metric(("service", "clerk", "tpuscope", "histograms",
            "clerk.op_latency_us", "p95"), 2.0, higher_is_better=False,
           host_bound=True,
           leg_shape=[("service", "clerk", "groups"),
                      ("service", "clerk", "width")]),
    Metric(("service", "clerk", "tpuscope", "histograms",
            "clerk.op_latency_us", "p99"), 2.0, higher_is_better=False,
           host_bound=True,
           leg_shape=[("service", "clerk", "groups"),
                      ("service", "clerk", "width")]),
]

# ------------------------------------------------- environment judgment

# The new run's calibration spins must be this much slower (median) than
# the baseline's before the box itself is suspect.  1.5× sits above the
# spin's own jitter on a quiet box (< ±15% measured) and below the 2-5×
# degradation the r08 bring-up recorded.
SPIN_DRIFT = 1.5
# Within one run, max/min spin beyond this spread means the box changed
# UNDER the bench (a leg bracketed by a slow spin ran degraded).
SPIN_SPREAD = 2.0


def _median(vals):
    s = sorted(vals)
    return s[len(s) // 2]


def env_suspicion(old: dict, new: dict) -> list[str]:
    """Evidence that the NEW run's box degraded vs the baseline's —
    empty when either artifact lacks an environment block (nothing to
    judge: the gate stays hard) or the boxes look equivalent.  Each
    reason is human-readable and lands verbatim in the report."""
    oe, ne = old.get("environment"), new.get("environment")
    if not isinstance(oe, dict) or not isinstance(ne, dict):
        return []
    reasons = []
    ocal = (oe.get("calibration") or {}).get("spins") or []
    ncal = (ne.get("calibration") or {}).get("spins") or []
    oms = [s["ms"] for s in ocal if isinstance(s.get("ms"), (int, float))]
    nms = [s["ms"] for s in ncal if isinstance(s.get("ms"), (int, float))]
    if oms and nms:
        om, nm = _median(oms), _median(nms)
        if om > 0 and nm > om * SPIN_DRIFT:
            reasons.append(
                f"calibration spin {nm:.1f}ms vs {om:.1f}ms baseline "
                f"(x{nm / om:.1f} slower: less effective CPU)")
        if min(nms) > 0 and max(nms) > min(nms) * SPIN_SPREAD:
            reasons.append(
                f"calibration unstable within the new run "
                f"({min(nms):.1f}-{max(nms):.1f}ms across leg "
                "boundaries: box degraded mid-bench)")
    oq = oe.get("effective_cpus")
    nq = ne.get("effective_cpus")
    if isinstance(oq, (int, float)) and isinstance(nq, (int, float)) \
            and nq < oq * 0.8:
        reasons.append(f"cgroup cpu budget shrank {oq:g} -> {nq:g} "
                       "effective cpus")
    nl = ne.get("loadavg")
    if isinstance(nl, list) and nl and isinstance(nq, (int, float)) \
            and nq > 0 and nl[0] / nq > 1.5:
        ol = oe.get("loadavg")
        if not (isinstance(ol, list) and ol) or nl[0] > 2 * ol[0]:
            reasons.append(
                f"load average {nl[0]:g} over {nq:g} effective cpus at "
                "run start (external contention)")
    return reasons


def _get_any(d, path):
    """Any JSON value at `path` (shape dicts included), None if absent."""
    for p in path:
        if not isinstance(d, dict) or p not in d:
            return None
        d = d[p]
    return d


def _get(d, path):
    for p in path:
        if not isinstance(d, dict) or p not in d:
            return None
        d = d[p]
    return d if isinstance(d, (int, float)) and not isinstance(d, bool) \
        else None


def load_artifact(path: str) -> dict:
    """Load a BENCH artifact, unwrapping the r01–r05 driver format."""
    with open(path) as f:
        d = json.load(f)
    if isinstance(d, dict) and "metric" in d:
        return d
    if isinstance(d, dict) and "meshfab" in d:
        # MULTICHIP_r07+ artifact: dryrun verdict wrapper plus the
        # meshfab real-path legs — the legs ARE the comparable payload.
        return d
    if isinstance(d, dict) and ("parsed" in d or "tail" in d):
        if isinstance(d.get("parsed"), dict):
            return d["parsed"]
        # bench.py's own salvage rule: last parseable JSON line of tail.
        for ln in reversed((d.get("tail") or "").splitlines()):
            ln = ln.strip()
            if ln.startswith("{"):
                try:
                    return json.loads(ln)
                except json.JSONDecodeError:
                    continue
        # An unsalvageable baseline must NOT silently gate green (an
        # empty artifact skips every metric) — it is unreadable, exit 2.
        raise ValueError(
            f"{path}: wrapped artifact with no parseable bench line")
    raise ValueError(f"{path}: not a bench artifact")


def compare(old: dict, new: dict, tol_scale: float = 1.0,
            allow_missing: bool = False, force: bool = False,
            strict_env: bool = False) -> dict:
    """Diff two (unwrapped) artifacts over METRICS.

    Returns {"results": [...], "regressions": n, "suspect": n,
    "compared": n, "notes": [...], "environment": [...reasons]};
    callers gate on `regressions` — `suspect` entries are host-bound
    would-be regressions demoted because the environment blocks show
    the box itself degraded (`strict_env` disables the demotion)."""
    results = []
    notes = []
    suspicion = [] if strict_env else env_suspicion(old, new)
    if suspicion:
        notes.append("environment suspect: " + "; ".join(suspicion) +
                     " — host-bound regressions demoted to "
                     "suspect-environment (re-run on a quiet box, or "
                     "--strict-env to gate hard)")
    same_platform = old.get("platform") == new.get("platform")
    same_shape = old.get("metric") == new.get("metric") \
        and old.get("kernel") == new.get("kernel")
    if not same_platform and not force:
        notes.append(
            f"platform mismatch ({old.get('platform')!r} vs "
            f"{new.get('platform')!r}): nothing is comparable "
            "(--force overrides)")
    elif not same_shape and not force:
        notes.append(
            f"headline shape/kernel mismatch ({old.get('metric')!r}/"
            f"{old.get('kernel')!r} vs {new.get('metric')!r}/"
            f"{new.get('kernel')!r}): shape-dependent metrics skipped "
            "(--force overrides)")
    if new.get("provisional"):
        notes.append("new artifact is PROVISIONAL (bench wedged mid-run): "
                     "missing legs are skipped, not regressions")
    regressions = compared = suspect = 0
    for m in METRICS:
        ov, nv = _get(old, m.path), _get(new, m.path)
        entry = {"metric": m.name, "old": ov, "new": nv, "tol": m.tol}
        if ov is None or ov == 0:
            entry["verdict"] = "skipped(no-baseline)"
        elif not same_platform and not force:
            entry["verdict"] = "skipped(platform-mismatch)"
        elif m.shape_dependent and not same_shape and not force:
            entry["verdict"] = "skipped(shape-mismatch)"
        elif m.leg_shape and not force and nv is not None and nv != 0 \
                and any(_get_any(old, p) != _get_any(new, p)
                        for p in m.leg_shape):
            # The leg ran a different configuration (env-trimmed groups/
            # width): its numbers are not comparable, loudly skipped.
            # Only when the metric still reports a real value — a leg
            # that VANISHED or ERRORED (bench writes value 0.0 and no
            # shape keys) stays a regression below, never a shape skip.
            entry["verdict"] = "skipped(leg-shape-mismatch)"
        elif nv is None or nv == 0:
            # nv == 0: bench records an ERRORED leg as value 0.0 (never
            # a real throughput/latency), so it takes the same
            # vanished-leg path — without this, --allow-missing and the
            # provisional demotion would never apply to errored legs
            # (0.0 compares as a -100% regression regardless).
            if allow_missing or new.get("provisional"):
                entry["verdict"] = "skipped(missing-in-new)"
            else:
                # A leg that stops reporting is how a perf break hides.
                entry["verdict"] = "REGRESSED"
                entry["why"] = ("metric vanished from the new artifact "
                                "(leg errored or removed); "
                                "--allow-missing to skip")
                regressions += 1
        else:
            compared += 1
            delta = (nv - ov) / ov
            entry["delta"] = round(delta, 4)
            bad = -delta if m.higher_is_better else delta
            if bad > m.tol * tol_scale:
                if m.host_bound and suspicion:
                    # The box demonstrably degraded between the runs and
                    # this leg's bottleneck IS the box: annotate, don't
                    # alarm.  Device-path legs never take this branch —
                    # a real kernel regression still exits 1.
                    entry["verdict"] = "suspect-environment"
                    entry["why"] = "; ".join(suspicion)
                    suspect += 1
                else:
                    entry["verdict"] = "REGRESSED"
                    regressions += 1
            elif bad < -0.05:
                entry["verdict"] = "improved"
            else:
                entry["verdict"] = "ok"
        results.append(entry)
    return {"results": results, "regressions": regressions,
            "suspect": suspect, "compared": compared, "notes": notes,
            "environment": suspicion}


def render(report: dict) -> str:
    lines = []
    for n in report["notes"]:
        lines.append(f"note: {n}")
    w = max((len(r["metric"]) for r in report["results"]), default=10)
    for r in report["results"]:
        delta = (f"{r['delta']:+8.1%}" if "delta" in r else " " * 8)
        old = "-" if r["old"] is None else f"{r['old']:g}"
        new = "-" if r["new"] is None else f"{r['new']:g}"
        line = (f"{r['metric']:<{w}}  {old:>12} -> {new:>12}  {delta}  "
                f"[tol {r['tol']:.0%}] {r['verdict']}")
        if "why" in r:
            line += f" — {r['why']}"
        lines.append(line)
    lines.append(
        f"benchdiff: {report['compared']} compared, "
        f"{report['regressions']} regressed"
        + (f", {report['suspect']} suspect-environment"
           if report.get("suspect") else ""))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu6824.obs.benchdiff",
        description="Gate a new BENCH artifact against a recorded one; "
                    "exit 1 on regression.")
    ap.add_argument("old", help="baseline artifact (e.g. BENCH_r07.json)")
    ap.add_argument("new", help="candidate artifact")
    ap.add_argument("--tol-scale", type=float, default=1.0,
                    help="scale every metric's tolerance (0.5 = stricter)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the full report as JSON")
    ap.add_argument("--allow-missing", action="store_true",
                    help="metrics missing from NEW are skips, not "
                         "regressions")
    ap.add_argument("--force", action="store_true",
                    help="compare across platform/shape mismatches")
    ap.add_argument("--strict-env", action="store_true",
                    help="never demote host-bound regressions to "
                         "suspect-environment (gate hard even when the "
                         "environment blocks show the box degraded)")
    args = ap.parse_args(argv)
    try:
        old, new = load_artifact(args.old), load_artifact(args.new)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"benchdiff: {e}", file=sys.stderr)
        return 2
    report = compare(old, new, tol_scale=args.tol_scale,
                     allow_missing=args.allow_missing, force=args.force,
                     strict_env=args.strict_env)
    print(json.dumps(report, indent=1) if args.as_json else render(report))
    return 1 if report["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
