"""opscope — always-on columnar per-stage latency attribution (ISSUE 15).

PR 10's honest bench note left the sharpest open question on the board:
after native ingest, the residual host profile is "spread over client
stream, proposal materialization, and fabric dispatch" — a conclusion
reached by ad-hoc bring-up probes, not by the system itself.  Every
remaining perf item (device-resident apply, fast-path quorum variants,
multi-chip sharding) needs to know WHICH STAGE of an op's life it is
buying back, continuously and under load.  tpuscope tracing answers that
per op but is head-sampled, allocation-costly, and off in steady state
by contract; opscope inverts it:

  - **Stage timestamps ride as parallel int64 monotonic-ns columns**
    next to the existing request-path columns: frame-parse (stamped on
    the C++ loop thread, `FeFrame.ts_ns` → the poll1 hdr), engine poll,
    `submit_columnar` park, proposal materialization
    (`_collect_proposals_locked`), fabric dispatch (start_many),
    decide-feed delivery, apply, and the notify-sweep reply push.  The
    stamps live in plain cid→int dicts (ints are not gc-tracked; two
    dict entries per op is the established columnar-waiter cost) and
    batch-level instants are taken ONCE per pass, never per op.
  - **Folded per drain** into per-stage-edge log2 histograms in the
    metrics registry: one numpy stack/diff/bincount per drained batch —
    the histogram update is columnar, never per op.  The pure-Python
    fallback server and in-process clerks stamp the same stage names,
    so both engines produce the same waterfall shape.
  - **Tail exemplars**: the K slowest ops per pulse interval
    (`TPU6824_OPSCOPE_EXEMPLARS`, default 8) get their full stage
    vector promoted into the flight recorder as synthetic tpuscope span
    chains — a p99 spike ships with concrete offending ops WITHOUT
    `TPU6824_TRACE=1`, inverting head-sampling into tail-based capture.
    Exemplar timestamps are `time.monotonic_ns()`, joinable to nemesis
    timelines via the artifact's t0 exactly like every flight record.
  - The C++ reply path contributes the **flush** stage (reply-ring
    completion → serialized frame flushed by the epoll loop) as a
    native-side log2 histogram merged per engine pass
    (`Histogram.add_pow2`), one FFI call per pass.

Stage-edge semantics (edge named by its DESTINATION stage; each edge's
histogram observes destination_stamp − previous_stamp in µs):

    poll         frame parsed (C++/event loop) → engine picked it up
    park         engine poll → columnar park under the server mutex
    materialize  park → Op log entries built at proposal collection
    dispatch     materialize → proposal handed to the fabric
    decide       dispatch → decided value delivered by the feed
    apply        decide-feed delivery → RSM apply done — with devapply
                 (ISSUE 16) this is the per-drain columnar DEVICE step
                 (column build + one jitted apply + one readback), so a
                 collapsed apply stage vs the r09 waterfall is the
                 optimization landing, not a measurement gap
    reply        apply → notify-sweep push into the reply path
    flush        reply push → frame serialized + flushed (per frame)

Missing stages (an op that skipped a stamp — in-process clerks have no
wire parse; a dup answer never materializes) back-fill from the next
known stamp, so their edges observe 0 and the stage-name SET is
identical on every path.

Always-on contract: default ON (`TPU6824_OPSCOPE=0` disables, and every
producer guards on `enabled()` so off means zero added work); the
steady-state cost is dict stamps + one columnar fold per drain —
regression-pinned by the PR 10 gc alloc probe and the bench leg's
opscope on/off A/B.  The stamp tables are capacity-bounded
(`_TRIM_CAP`): abandoned ops' residue is cleared wholesale and counted
(`opscope.trimmed`), never leaked.

MONOTONIC-ONLY invariant: every stamp here is `time.monotonic_ns()` (or
the C++ steady clock, same POSIX clock).  Durations from `time.time()`
jump under NTP slew and the clock-pause nemesis — the tpusan
`wallclock-duration` rule enforces this repo-wide.
"""

from __future__ import annotations

import os
import threading
import time

from tpu6824.obs import metrics as _metrics
from tpu6824.obs import pulse as _pulse
from tpu6824.obs import tracing as _tracing

__all__ = ["STAGES", "EDGES", "SCHEMA_VERSION", "enabled", "enable",
           "disable", "note_ingest_poll", "note_columnar_park",
           "note_park", "note_materialize_many", "note_dispatch_many",
           "drop", "fold", "observe_flush", "merge_flush",
           "flush_exemplars", "snapshot", "snapshot_shell", "reset"]

SCHEMA_VERSION = "opscope-1.0.0"

# The op-life stages, in pipeline order.  `ingest` is the origin stamp
# (frame parse); every later stage names the EDGE ending at it.
STAGES = ("ingest", "poll", "park", "materialize", "dispatch",
          "decide", "apply", "reply")
# Edge (= per-stage histogram) names: the seven fold-produced edges plus
# the native reply path's flush stage.
EDGES = STAGES[1:] + ("flush",)

_ENABLED = os.environ.get("TPU6824_OPSCOPE", "1") not in ("0", "false")
EXEMPLAR_K = max(1, int(os.environ.get("TPU6824_OPSCOPE_EXEMPLARS", "8")))

# Stamp-table bound: beyond this many live entries the tables are
# cleared wholesale (abandoned/dup-retried residue — ops in flight
# simply back-fill their next fold).  Telemetry is allowed to be lossy;
# it is NOT allowed to leak (the unbounded-obs-buffer philosophy).
_TRIM_CAP = int(os.environ.get("TPU6824_OPSCOPE_CAP", str(1 << 16)))

# Per-edge latency histograms + the whole-op total, module scope per the
# metric-unregistered rule.  Names embed the stage so pulse's automatic
# per-interval percentile series (`opscope.stage.<edge>.latency_us.p99`)
# carry the stage for the watchdog's culprit attribution.
_H_EDGE = {e: _metrics.histogram(f"opscope.stage.{e}.latency_us")
           for e in EDGES}
# Per-shard dispatch-edge histograms (meshfab): a fold tagged with the
# folding group's owning mesh shard ALSO observes its dispatch edge
# under `opscope.stage.dispatch.shard<k>.latency_us`, giving pulse a
# per-shard p99 series the watchdog's shard-skew rule compares against
# the fleet median.  Lazy per shard (the shard universe is the mesh 'g'
# extent, known only at service attach; the registry returns the
# already-created object, so the race-free fast path is one dict get).
# Untagged folds (single-device fabrics, non-fabric servers) cost
# nothing — the name parses as stage "dispatch" for the existing
# watchdog culprit attribution.
_H_SHARD_DISPATCH: dict = {}
# Fleet-wide twin of the per-shard histograms: every shard-tagged
# dispatch edge also lands here, so pulse carries ONE
# `meshfab.shard_dispatch_us` p99 series for dashboards that want the
# mesh-serving picture without per-shard cardinality.
_H_MESH_DISPATCH = _metrics.histogram("meshfab.shard_dispatch_us")
_H_TOTAL = _metrics.histogram("opscope.op.latency_us")
_C_FOLDED = _metrics.counter("opscope.folded")
_C_TRIM = _metrics.counter("opscope.trimmed")

# Stage stamp columns: cid → monotonic ns.  Plain dicts — single-key
# get/set/pop are GIL-atomic, values are ints (not gc-tracked), and the
# fold pops its batch's entries so steady state holds one row per op in
# flight.  cids are globally unique (fresh_cid; shardkv's are strings).
_t0: dict = {}
_tpoll: dict = {}
_tpark: dict = {}
_tmat: dict = {}
_tdisp: dict = {}
_STAMPS = (_t0, _tpoll, _tpark, _tmat, _tdisp)

# Exemplar reservoir: the K slowest ops since the last flush, kept as
# preallocated parallel columns (numpy lazily — obs stays importable
# without it; the reservoir only exists once a fold ran).
_ex_mu = threading.Lock()
_ex_tot = None    # np.int64[K] total µs, -1 = empty slot
_ex_vec = None    # np.int64[K, len(STAGES)] stage stamp vectors (ns)
_ex_cid: list = []  # parallel cid labels (any hashable; rendered str)


def enabled() -> bool:
    return _ENABLED


def enable() -> None:
    """Turn stamping/folding on (tests / the bench A/B)."""
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


# ------------------------------------------------------------- stamping
# All producers guard on enabled() at THEIR end so a disabled opscope
# costs nothing; these helpers do not re-check.


def note_ingest_poll(cids, t0s, poll_ns: int) -> None:
    """Frame decoded → engine pass picked it up.  `t0s` is either one
    frame-parse instant for the whole batch or a per-op sequence
    parallel to `cids` (the native path's ts column)."""
    d0 = _t0
    dp = _tpoll
    if isinstance(t0s, int):
        for cid in cids:
            d0[cid] = t0s
            dp[cid] = poll_ns
    else:
        for i, cid in enumerate(cids):
            d0[cid] = t0s[i]
            dp[cid] = poll_ns
    _maybe_trim()


def note_columnar_park(cids, t0s, polls, park_ns: int) -> None:
    """submit_columnar's park: the native block carries per-op ts
    columns (frame parse + engine poll), the park instant is one stamp
    for the whole accepted set."""
    d0 = _t0
    dp = _tpoll
    dk = _tpark
    for i, cid in enumerate(cids):
        d0[cid] = t0s[i]
        dp[cid] = polls[i]
        dk[cid] = park_ns
    _maybe_trim()


def note_park(cids, park_ns: int) -> None:
    """submit_batch's park (Python frames, in-process clerks)."""
    dk = _tpark
    for cid in cids:
        dk[cid] = park_ns
    _maybe_trim()


def note_materialize_many(cids, ns: int) -> None:
    dm = _tmat
    for cid in cids:
        dm[cid] = ns


def note_dispatch_many(cids, ns: int) -> None:
    dd = _tdisp
    for cid in cids:
        dd[cid] = ns


def drop(cid) -> None:
    """Forget an op's stamps — the TERMINAL paths (frame timeout: the
    op is answered with an error and will never fold).  Failover
    abandons deliberately do NOT drop: the retry re-parks the same cid
    and its fold still wants the original parse origin.  Residue from
    anything else is bounded by the trim cap."""
    for d in _STAMPS:
        d.pop(cid, None)


def _maybe_trim() -> None:
    # Park and ingest tables both bound the sweep: ops that stamp but
    # never park (a frame dropped between decode and admission) must
    # not leak either.
    n = max(len(_tpark), len(_t0))
    if n > _TRIM_CAP:
        for d in _STAMPS:
            d.clear()
        _C_TRIM.inc(n)


# ------------------------------------------------------------- the fold


def fold(cids, t_decide: int, t_apply: int, t_reply: int,
         shard: int | None = None) -> None:
    """One drained batch → per-stage-edge histograms + the exemplar
    reservoir.  `cids` are the ops this drain resolved; the three
    drain-level stamps are batch scalars (delivery / applied / pushed).
    The histogram update is one numpy stack + diff + bincount per batch
    — never a per-op observe.  `shard` (when the folding server's group
    lives on a mesh shard) additionally routes the dispatch edge into
    that shard's histogram — the opscope shard dimension."""
    if not cids:
        return
    import numpy as np

    n = len(cids)
    cols = []
    for d in _STAMPS:
        pop = d.pop
        cols.append([pop(cid, 0) for cid in cids])
    m = np.empty((len(STAGES), n), dtype=np.int64)
    for i, col in enumerate(cols):
        m[i] = col
    m[5] = t_decide
    m[6] = t_apply
    m[7] = t_reply
    # Missing early stamps (0) back-fill from the next known stage so
    # their edges observe 0; then enforce monotone non-decreasing (a
    # retried op's re-stamp can land out of order by a hair).
    for i in range(len(STAGES) - 2, -1, -1):
        np.copyto(m[i], m[i + 1], where=(m[i] == 0))
    np.maximum.accumulate(m, axis=0, out=m)
    d_ns = np.diff(m, axis=0)
    us = d_ns // 1000
    # bit_length(x) == ceil(log2(x + 1)) for x >= 0 — exact in float64
    # at every power of two below 2^53.
    bl = np.ceil(np.log2(us + 1.0)).astype(np.int64)
    np.clip(bl, 0, 63, out=bl)
    for i, edge in enumerate(EDGES[:-1]):
        counts = np.bincount(bl[i], minlength=64)
        _H_EDGE[edge].add_pow2(counts, n, int(us[i].sum()))
        if shard is not None and edge == "dispatch":
            h = _H_SHARD_DISPATCH.get(shard)
            if h is None:
                h = _metrics.histogram(
                    f"opscope.stage.dispatch.shard{int(shard)}.latency_us")
                _H_SHARD_DISPATCH[shard] = h
            h.add_pow2(counts, n, int(us[i].sum()))
            _H_MESH_DISPATCH.add_pow2(counts, n, int(us[i].sum()))
    tot = (m[-1] - m[0]) // 1000
    tbl = np.clip(np.ceil(np.log2(tot + 1.0)).astype(np.int64), 0, 63)
    _H_TOTAL.add_pow2(np.bincount(tbl, minlength=64), n, int(tot.sum()))
    _C_FOLDED.inc(n)
    _reservoir_update(np, cids, tot, m)


def _reservoir_update(np, cids, tot, m) -> None:
    """Keep the K slowest ops' full stage vectors since the last flush
    (preallocated columns — no per-op objects; candidate selection is
    one argpartition per batch)."""
    global _ex_tot, _ex_vec
    k = EXEMPLAR_K
    with _ex_mu:
        if _ex_tot is None:
            _ex_tot = np.full(k, -1, dtype=np.int64)
            _ex_vec = np.zeros((k, len(STAGES)), dtype=np.int64)
            _ex_cid.extend([None] * k)
        n = len(cids)
        if n > k:
            cand = np.argpartition(tot, n - k)[n - k:]
        else:
            cand = np.arange(n)
        for j in cand.tolist():
            slot = int(np.argmin(_ex_tot))
            if tot[j] > _ex_tot[slot]:
                _ex_tot[slot] = tot[j]
                _ex_vec[slot] = m[:, j]
                _ex_cid[slot] = cids[j]


def flush_exemplars() -> int:
    """Promote the reservoir into the flight recorder as synthetic
    tpuscope span chains — one root `opscope.op` span per exemplar
    (args: cid, total µs, the widest stage) with one child span per
    stage edge — then reset the reservoir for the next interval.
    Runs on the pulse sampling clock (global sampler) and on demand;
    works with tracing OFF (flight records are always-on).  Returns the
    number of exemplars emitted."""
    with _ex_mu:
        if _ex_tot is None:
            # Nothing ever folded — ALSO the numpy-less-process guard:
            # this runs on every pulse tick via the global sampler, and
            # the reservoir only exists once a fold (which itself needs
            # numpy) created it, so the import stays below this
            # early-out and a stdlib-only poller never crash-loops the
            # sampler.
            return 0
        import numpy as np

        live = np.nonzero(_ex_tot >= 0)[0]
        if not len(live):
            return 0
        tots = _ex_tot[live].tolist()
        vecs = _ex_vec[live].copy()
        labels = [_ex_cid[int(i)] for i in live]
        _ex_tot.fill(-1)
    emitted = 0
    for row, tot_us, cid in zip(vecs, tots, labels):
        v = row.tolist()
        durs = [v[i + 1] - v[i] for i in range(len(STAGES) - 1)]
        widest = EDGES[max(range(len(durs)), key=durs.__getitem__)]
        tid = _tracing.fresh_id()
        root = _tracing.complete(
            "opscope.op", tid, 0, v[0], v[-1], comp="opscope",
            cid=str(cid), total_us=int(tot_us), stage=widest)
        for i, edge in enumerate(EDGES[:-1]):
            _tracing.complete(f"opscope.{edge}", tid, root, v[i],
                              v[i + 1], comp="opscope", stage=edge,
                              us=durs[i] // 1000)
        emitted += 1
    return emitted


# Exemplars flush on the pulse sampling clock: per interval, the K
# slowest ops land in the flight ring.  Registered globally so whichever
# pulse runs (fabricd --pulse, a test's manual Pulse) drives it without
# opscope importing any runtime layer.
_pulse.add_global_sampler(flush_exemplars)


# ----------------------------------------------------- native flush leg


def observe_flush(ns: int) -> None:
    """Python reply paths' flush stage: one observation per FRAME (the
    reply serialize+send the engine just performed) — frame-granular by
    design, matching the C++ side's per-reply accounting."""
    _H_EDGE["flush"].observe(ns // 1000)


def merge_flush(buckets, count: int, total_us: int) -> None:
    """Merge the C++ reply ring's cumulative flush histogram DELTA (64
    log2 µs buckets + count + µs sum) — one call per engine pass."""
    if count > 0:
        _H_EDGE["flush"].add_pow2(buckets, count, total_us)


# -------------------------------------------------------------- surface


def snapshot() -> dict:
    """The opscope wire surface (served as the `opscope` RPC next to
    stats/metrics/flight/pulse): per-stage histogram summaries with raw
    pow2 buckets so the fleet Collector can merge across processes."""
    hists = {}
    for e in EDGES:
        s = _H_EDGE[e].snapshot()
        hists[e] = {"count": s["count"], "sum": s["sum"],
                    "p50": s["p50"], "p95": s["p95"], "p99": s["p99"],
                    "pow2": s["pow2"]}
    # Per-shard dispatch splits (ISSUE 17 meshfab) ride the same surface
    # so the fleet Collector merges per-shard waterfalls like any other
    # stage; single-shard deployments never populate these.
    for shard in sorted(_H_SHARD_DISPATCH):
        s = _H_SHARD_DISPATCH[shard].snapshot()
        hists[f"dispatch.shard{shard}"] = {
            "count": s["count"], "sum": s["sum"],
            "p50": s["p50"], "p95": s["p95"], "p99": s["p99"],
            "pow2": s["pow2"]}
    t = _H_TOTAL.snapshot()
    return {"schema": SCHEMA_VERSION, "enabled": _ENABLED,
            "stages": list(EDGES),
            "exemplar_k": EXEMPLAR_K,
            "t_mono": round(time.monotonic(), 6),
            "op": {"count": t["count"], "sum": t["sum"], "p50": t["p50"],
                   "p95": t["p95"], "p99": t["p99"]},
            "histograms": hists}


def snapshot_shell(reason: str | None = None) -> dict:
    """The stable disabled shell — what a poller reports for a member
    that does not serve opscope (pre-opscope fleet member, PR 9's
    mixed-fleet rule): same key set, enabled False, never an error."""
    out = {"schema": SCHEMA_VERSION, "enabled": False, "stages": [],
           "exemplar_k": None, "t_mono": round(time.monotonic(), 6),
           "op": {"count": 0, "sum": 0, "p50": None, "p95": None,
                  "p99": None},
           "histograms": {}}
    if reason is not None:
        out["unavailable"] = reason
    return out


def reset() -> None:
    """Test isolation: drop stamps and the reservoir (registry metrics
    are owned by obs.metrics.reset)."""
    global _ex_tot, _ex_vec
    for d in _STAMPS:
        d.clear()
    with _ex_mu:
        _ex_tot = None
        _ex_vec = None
        _ex_cid.clear()
