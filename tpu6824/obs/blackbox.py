"""blackbox — a crash-surviving flight-data recorder (ISSUE 20).

Every telemetry surface built so far — pulse series, opscope waterfalls,
flight-recorder spans, watchdog evidence — lives in the process heap and
dies with it.  The harness kills processes as a matter of course
(fleetfe's kill storm SIGKILLs live frontends; nemesis crashes replicas
mid-commit), so the ops we most need to explain are exactly the ones we
cannot.  blackbox closes that gap: a per-process, always-on, mmap-backed
ring file into which telemetry producers append fixed-size checksummed
records, so a postmortem (`python -m tpu6824.obs.postmortem <dir>`)
reconstructs the victim's final window from disk alone.

Crash model, in order of strength:

  - **SIGKILL / crash** (the common harness case): every mmap store
    already lives in the page cache — the kernel keeps the pages when
    the process dies, so the ring holds everything written up to the
    killing instruction, msync'd or not.
  - **Machine/power loss**: only data through the last `sync()` (one
    msync per cadence, `TPU6824_BLACKBOX_SYNC`) is guaranteed.

Hot-path contract (the jitguard/bench invariant): nothing here runs
per-op.  Producers on request paths call `stamp(key, value)` — a single
GIL-atomic dict store, one per drain/engine pass with a precomputed key
— and the cadence `sync()` persists the stamp table as one heartbeat
record.  Ring appends happen only at telemetry cadence (pulse ticks,
watchdog firings, nemesis injections, crash records, the sync seam's
flight-ring delta); slot reservation is `itertools.count().__next__`
(GIL-atomic, the tracing-id idiom) so the writer takes ZERO locks, and
`sync()` is THE sanctioned blocking-IO seam — the
`blocking-io-in-telemetry-path` tpusan rule holds every other telemetry
path to memory stores only.

Ring format (`<name>.bbx`): one 4096-byte header page — magic, version,
slot geometry, a (wall-ns, monotonic-ns) anchor pair stamped at create
time (the cross-process join key: rings from different processes map
their monotonic records onto one causal wall timeline via
`wall = anchor_wall + (t_mono - anchor_mono)`), pid, process name, plus
sync-stamped liveness counters — followed by `nslots` fixed-size slots.
Each record chunk carries a CRC32 over its used bytes: a slot torn by
SIGKILL mid-store fails the checksum and the loader skips it, exactly
the PR 7 `frame_checkpoint` torn-frame discipline applied per slot.
Oversize payloads span slots as (rec, part, nparts) continuation chunks;
the loader reassembles whole records and counts partial ones as torn.

Stdlib-only like the rest of obs/.
"""

from __future__ import annotations

import itertools
import json
import mmap
import os
import struct
import threading
import time
import zlib

from tpu6824.obs import pulse as _pulse
from tpu6824.obs import tracing as _tracing
from tpu6824.utils import crashsink

__all__ = ["Ring", "Recorder", "enable", "enable_from_env", "disable",
           "enabled", "record", "stamp", "sync", "status", "status_shell",
           "load_ring", "load_dir", "wall_of", "SCHEMA_VERSION", "MAGIC",
           "KINDS", "KIND_NAMES"]

SCHEMA_VERSION = "blackbox-1.0.0"

MAGIC = b"TPU6824BBX1"
HEADER_SIZE = 4096
RING_SUFFIX = ".bbx"

# Fixed header at offset 0: magic, version, slot_size, nslots,
# anchor_wall_ns, anchor_mono_ns, pid, process name (NUL-padded).
_HDR = struct.Struct("!12sIIQQQI64s")
# Sync-stamped liveness counters at a fixed offset past the static
# header: last reserved seq, seal (sync) count, payload bytes written.
# Best-effort for the loader (a SIGKILL between stamps just means the
# counters lag the slots — the loader scans slots regardless).
_HDR_LIVE = struct.Struct("!QQQ")
_HDR_LIVE_OFF = 256

# Per-slot header: crc32 (over the remaining used bytes), used payload
# length, slot seq, record id (= first chunk's seq), monotonic ns,
# kind code, chunk index, chunk count, pad.
_SLOT = struct.Struct("!IIQQQBBBx")

KINDS = {"heartbeat": 1, "pulse": 2, "opscope": 3, "flight": 4,
         "watchdog": 5, "nemesis": 6, "crash": 7, "event": 8}
KIND_NAMES = {v: k for k, v in KINDS.items()}

_DEF_SLOT_SIZE = int(os.environ.get("TPU6824_BLACKBOX_SLOT", "1024"))
_DEF_NSLOTS = int(os.environ.get("TPU6824_BLACKBOX_SLOTS", "4096"))
_DEF_SYNC = float(os.environ.get("TPU6824_BLACKBOX_SYNC", "0.25"))
# Flight-ring records drained per sync: bounds the slot share one busy
# interval can claim; the overflow is counted in the flight record
# itself (no silent caps).
_FLIGHT_PER_SYNC = int(os.environ.get("TPU6824_BLACKBOX_FLIGHT", "512"))


class Ring:
    """One mmap-backed ring file.  Appends are lock-free: slot index is
    a GIL-atomic counter modulo `nslots`, and each chunk is one mmap
    slice store.  Concurrent writers can only collide on a slot after a
    full wrap between their reservations — the same already-overwritten
    regime the ring lives in by design, and the per-slot CRC keeps any
    torn slot detectable."""

    def __init__(self, path: str, name: str,
                 slot_size: int | None = None, nslots: int | None = None,
                 anchor_wall_ns: int | None = None,
                 anchor_mono_ns: int | None = None):
        self.path = path
        self.name = name
        self.slot_size = _DEF_SLOT_SIZE if slot_size is None \
            else int(slot_size)
        self.nslots = _DEF_NSLOTS if nslots is None else int(nslots)
        if self.slot_size <= _SLOT.size:
            raise ValueError(f"slot_size must exceed {_SLOT.size}")
        self.payload_max = self.slot_size - _SLOT.size
        # The clock-anchor pair: stamped ONCE at create time, never
        # updated — both clocks read back-to-back so the pair's skew is
        # bounded by one scheduling quantum (TUNING round 24).
        # Overridable for deterministic test fixtures.
        self.anchor_wall_ns = time.time_ns() if anchor_wall_ns is None \
            else int(anchor_wall_ns)
        self.anchor_mono_ns = time.monotonic_ns() if anchor_mono_ns is None \
            else int(anchor_mono_ns)
        size = HEADER_SIZE + self.slot_size * self.nslots
        fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_TRUNC, 0o644)
        try:
            os.ftruncate(fd, size)
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self._mm[0:_HDR.size] = _HDR.pack(
            MAGIC.ljust(12, b"\0"), 1, self.slot_size, self.nslots,
            self.anchor_wall_ns, self.anchor_mono_ns,
            os.getpid() & 0xFFFFFFFF,
            name.encode("utf-8", "replace")[:64].ljust(64, b"\0"))
        # GIL-atomic slot reservation (the tracing `_ids` idiom); the
        # shadow counters are telemetry-grade (racing += may undercount
        # by a few — the slots themselves are the ground truth).
        self._seq = itertools.count(1)
        self.last_seq = 0
        self.bytes_written = 0
        self.seals = 0
        self.closed = False

    def append(self, kind: int, payload: bytes,
               t_mono_ns: int | None = None) -> int:
        """Write one record (chunking oversize payloads across slots).
        Returns the record id.  Memory stores only — never blocks."""
        if self.closed:
            return 0
        if t_mono_ns is None:
            t_mono_ns = time.monotonic_ns()
        pm = self.payload_max
        nparts = max(1, -(-len(payload) // pm))
        if nparts > 255:
            # A >255-slot record cannot be encoded; keep the head (the
            # loader sees a complete, smaller record — better than a
            # permanently-partial giant).
            nparts = 255
            payload = payload[:255 * pm]
        rec_id = 0
        mm = self._mm
        for part in range(nparts):
            chunk = payload[part * pm:(part + 1) * pm]
            seq = next(self._seq)
            if part == 0:
                rec_id = seq
            rest = _SLOT.pack(0, len(chunk), seq, rec_id, t_mono_ns,
                              kind, part, nparts)[4:] + chunk
            off = HEADER_SIZE + (seq % self.nslots) * self.slot_size
            mm[off:off + 4 + len(rest)] = \
                struct.pack("!I", zlib.crc32(rest)) + rest
            self.last_seq = seq
            self.bytes_written += len(chunk)
        return rec_id

    def sync(self) -> None:
        """Stamp the liveness counters and msync — the ONE blocking-IO
        seam (the `blocking-io-in-telemetry-path` sanction)."""
        if self.closed:
            return
        self.seals += 1
        self._mm[_HDR_LIVE_OFF:_HDR_LIVE_OFF + _HDR_LIVE.size] = \
            _HDR_LIVE.pack(self.last_seq, self.seals, self.bytes_written)
        self._mm.flush()

    def close(self) -> None:
        if self.closed:
            return
        self.sync()
        self.closed = True
        self._mm.close()


class Recorder:
    """The per-process recorder: one Ring + the stamp table + the
    cadence sync daemon + the producer registrations (pulse observer,
    crashsink flush hook)."""

    def __init__(self, dirpath: str, name: str,
                 slot_size: int | None = None, nslots: int | None = None,
                 sync_interval: float | None = None):
        os.makedirs(dirpath, exist_ok=True)
        self.name = name
        self.dir = dirpath
        self.ring = Ring(os.path.join(dirpath, name + RING_SUFFIX), name,
                         slot_size=slot_size, nslots=nslots)
        self.interval = _DEF_SYNC if sync_interval is None \
            else float(sync_interval)
        # Telemetry stamp table: single-key stores are GIL-atomic (the
        # opscope stamp-dict idiom) — producers on request paths touch
        # ONLY this dict, with keys precomputed at init.
        self.stamps: dict = {}
        self._flight_cursor = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "Recorder":
        if self._thread is None:
            self._thread = threading.Thread(
                target=crashsink.guarded(self._sync_loop, "blackbox-sync"),
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def record(self, kind: str, payload: dict,
               t_mono_ns: int | None = None) -> int:
        """JSON-encode one record into the ring (telemetry-cadence
        call sites only — never per-op)."""
        blob = json.dumps(payload, separators=(",", ":"),
                          default=repr).encode("utf-8", "replace")
        return self.ring.append(KINDS.get(kind, KINDS["event"]), blob,
                                t_mono_ns=t_mono_ns)

    def sync(self) -> None:
        """THE cadence seam: persist the stamp table as one heartbeat
        record, drain the flight ring's delta, stamp the header, msync
        once.  Every blocking syscall blackbox ever issues happens
        here."""
        self.record("heartbeat", {"stamps": dict(self.stamps)})
        recs, self._flight_cursor, missed = \
            _tracing.FLIGHT.snapshot_delta(self._flight_cursor)
        if len(recs) > _FLIGHT_PER_SYNC:
            missed += len(recs) - _FLIGHT_PER_SYNC
            recs = recs[-_FLIGHT_PER_SYNC:]
        if recs or missed:
            self.record("flight", {"records": recs, "missed": missed})
        self.ring.sync()

    def _sync_loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sync()
            except Exception as e:  # noqa: BLE001 — a full/vanished disk
                # must not kill the recorder; recorded (which also lands
                # the failure in the ring via the flush hook) and the
                # loop keeps driving for when the disk returns.
                crashsink.record("blackbox-sync", e, fatal=False)
        self.sync()

    def status(self) -> dict:
        r = self.ring
        return {"schema": SCHEMA_VERSION, "enabled": True,
                "name": self.name, "path": r.path, "pid": os.getpid(),
                "slot_size": r.slot_size, "nslots": r.nslots,
                "last_seq": r.last_seq, "seals": r.seals,
                "bytes_written": r.bytes_written,
                "sync_interval": self.interval,
                "anchor_wall_ns": r.anchor_wall_ns,
                "anchor_mono_ns": r.anchor_mono_ns}


# ------------------------------------------------- process-global recorder

_BB: Recorder | None = None
_enable_mu = threading.Lock()


def enabled() -> bool:
    return _BB is not None


def enable(dirpath: str, name: str | None = None,
           slot_size: int | None = None, nslots: int | None = None,
           sync_interval: float | None = None) -> Recorder:
    """Start (or return) THE process recorder, registering the telemetry
    producers: the pulse global observer (pulse + opscope records per
    sampling tick) and the crashsink flush hook (crash records at
    record time, synced on fatal)."""
    global _BB
    with _enable_mu:
        if _BB is not None:
            return _BB
        bb = Recorder(dirpath, name or f"proc-{os.getpid()}",
                      slot_size=slot_size, nslots=nslots,
                      sync_interval=sync_interval).start()
        _BB = bb
    _pulse.add_global_observer(_on_pulse_tick)
    crashsink.add_flush_hook(_on_crash)
    return bb


def enable_from_env() -> Recorder | None:
    """Env-gated enable (`TPU6824_BLACKBOX_DIR`, optional
    `TPU6824_BLACKBOX_NAME`) — the one-line wiring every daemon/frontend
    constructor calls; a cheap no-op when the env is unset."""
    d = os.environ.get("TPU6824_BLACKBOX_DIR")
    if not d:
        return None
    return enable(d, name=os.environ.get("TPU6824_BLACKBOX_NAME"))


def disable() -> None:
    """Stop the recorder (final sync, ring closed, producers
    unregistered) — tests and the bench A/B."""
    global _BB
    with _enable_mu:
        bb, _BB = _BB, None
    if bb is None:
        return
    _pulse.remove_global_observer(_on_pulse_tick)
    crashsink.remove_flush_hook(_on_crash)
    bb.stop()
    bb.ring.close()


def record(kind: str, payload: dict) -> None:
    """Append one record to the process ring (no-op when disabled).
    Telemetry-cadence call sites only — never per-op."""
    bb = _BB
    if bb is not None:
        bb.record(kind, payload)


def stamp(key: str, value) -> None:
    """The request-path producer primitive: one GIL-atomic dict store
    (keys precomputed by the caller).  The cadence sync persists the
    whole table as a heartbeat record."""
    bb = _BB
    if bb is not None:
        bb.stamps[key] = value


def sync() -> None:
    """Force a cadence sync now (watchdog firings, fatal crash records
    — evidence that must be durable at detection time)."""
    bb = _BB
    if bb is not None:
        bb.sync()


def status() -> dict:
    """The `blackbox` wire surface (served next to
    stats/metrics/flight/pulse/opscope): recorder status, or the stable
    disabled shell when no recorder runs."""
    bb = _BB
    if bb is None:
        return status_shell()
    return bb.status()


def status_shell(reason: str | None = None) -> dict:
    """The stable disabled shell — what a poller reports for a member
    that does not serve blackbox (pre-blackbox fleet member, PR 9's
    mixed-fleet rule): same key set, enabled False, never an error."""
    out = {"schema": SCHEMA_VERSION, "enabled": False, "name": None,
           "path": None, "pid": None, "slot_size": None, "nslots": None,
           "last_seq": 0, "seals": 0, "bytes_written": 0,
           "sync_interval": None, "anchor_wall_ns": None,
           "anchor_mono_ns": None}
    if reason is not None:
        out["unavailable"] = reason
    return out


# ---------------------------------------------------- telemetry producers


def _on_pulse_tick(pulse, now) -> None:
    """Pulse global observer: per sampling tick, the latest point of
    every series plus the opscope waterfall land in the ring — memory
    stores only (the sync seam does the IO)."""
    bb = _BB
    if bb is None:
        return
    snap = pulse.series(window=2 * pulse.interval)
    bb.record("pulse", {
        "samples": snap["samples"], "interval": snap["interval"],
        "latest": {name: s["v"][-1]
                   for name, s in snap["series"].items() if s["v"]}})
    from tpu6824.obs import opscope as _opscope

    if _opscope.enabled():
        bb.record("opscope", _opscope.snapshot())


def _on_crash(rec: dict) -> None:
    """crashsink flush hook: every crash record lands in the ring at
    record time; fatal ones force a sync — the dying thread's evidence
    must not wait for the cadence."""
    bb = _BB
    if bb is None:
        return
    bb.record("crash", rec)
    if rec.get("fatal"):
        sync()


# ---------------------------------------------------------------- loading


def wall_of(ring: dict, t_mono_ns: int) -> int:
    """Map one ring's monotonic stamp onto the shared wall timeline via
    its anchor pair — the cross-process join."""
    return ring["anchor_wall_ns"] + (t_mono_ns - ring["anchor_mono_ns"])


def load_ring(path: str) -> dict:
    """Parse one ring file, tolerating torn tails: short files (SIGKILL
    mid-growth, copied prefixes), CRC-failed slots, and partial chunked
    records are counted and skipped, never raised.  Returns header
    fields + whole records ordered by seq, each with a wall-ns stamp
    derived from the anchor pair."""
    out = {"path": path, "valid": False, "name": None, "pid": None,
           "slot_size": None, "nslots": None, "anchor_wall_ns": None,
           "anchor_mono_ns": None, "last_seq": 0, "seals": 0,
           "bytes_written": 0, "records": [], "torn_slots": 0,
           "torn_records": 0, "error": None}
    try:
        with open(path, "rb") as f:
            buf = f.read()
    except OSError as e:
        out["error"] = repr(e)
        return out
    if len(buf) < _HDR.size:
        out["error"] = "truncated header"
        return out
    magic, version, slot_size, nslots, aw, am, pid, name = \
        _HDR.unpack_from(buf, 0)
    if magic[:len(MAGIC)] != MAGIC:
        out["error"] = "bad magic"
        return out
    out.update(valid=True, name=name.rstrip(b"\0").decode("utf-8", "replace"),
               pid=pid, slot_size=slot_size, nslots=nslots,
               anchor_wall_ns=aw, anchor_mono_ns=am)
    if len(buf) >= _HDR_LIVE_OFF + _HDR_LIVE.size:
        last_seq, seals, written = _HDR_LIVE.unpack_from(buf, _HDR_LIVE_OFF)
        out.update(last_seq=last_seq, seals=seals, bytes_written=written)
    chunks: dict[int, tuple] = {}
    for i in range(nslots):
        off = HEADER_SIZE + i * slot_size
        if off + _SLOT.size > len(buf):
            break  # torn tail: the file ends mid-ring; what's left is data
        crc, used, seq, rec, t_ns, kind, part, nparts = \
            _SLOT.unpack_from(buf, off)
        if seq == 0 and used == 0:
            continue  # never written
        end = off + _SLOT.size + used
        if used > slot_size - _SLOT.size or end > len(buf) \
                or zlib.crc32(buf[off + 4:end]) != crc:
            out["torn_slots"] += 1
            continue
        chunks[seq] = (rec, part, nparts, kind,
                       t_ns, buf[off + _SLOT.size:end])
    groups: dict[int, dict[int, tuple]] = {}
    for seq in sorted(chunks):
        rec, part, nparts, kind, t_ns, data = chunks[seq]
        groups.setdefault(rec, {})[part] = (nparts, kind, t_ns, data)
    for rec_id in sorted(groups):
        parts = groups[rec_id]
        nparts = parts[min(parts)][0]
        if set(parts) != set(range(nparts)):
            out["torn_records"] += 1  # wrapped-over or torn continuation
            continue
        _, kind, t_ns, _ = parts[0]
        payload = b"".join(parts[p][3] for p in range(nparts))
        try:
            data = json.loads(payload)
        except ValueError:
            out["torn_records"] += 1
            continue
        out["records"].append({
            "seq": rec_id, "kind": KIND_NAMES.get(kind, f"kind{kind}"),
            "t_mono_ns": t_ns, "t_wall_ns": aw + (t_ns - am),
            "data": data})
    return out


def load_dir(dirpath: str) -> list[dict]:
    """Every ring in a blackbox dir, name-sorted (stable postmortem
    input order)."""
    try:
        names = sorted(n for n in os.listdir(dirpath)
                       if n.endswith(RING_SUFFIX))
    except OSError:
        return []
    return [load_ring(os.path.join(dirpath, n)) for n in names]
