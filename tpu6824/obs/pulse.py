"""pulse — continuous time-series telemetry over the metrics registry.

tpuscope (ISSUE 5) and kernelscope (ISSUE 6) answer "what has this
process done" at a POINT: `metrics.snapshot()` is cumulative totals, and
`stats()` is the instant's health.  Neither answers the question a
running fleet asks — "what is it doing *over time*, and when did that
change" — which is exactly the question a stall, a throughput collapse,
or a latency spike poses.  pulse closes that gap:

  - a `Pulse` samples the process-global registry on its own clock
    (`TPU6824_PULSE_INTERVAL`), deriving per-interval signals from the
    cumulative metrics: counters become RATES (delta/dt), gauges are
    carried as-is, and histograms yield per-interval p50/p95/p99 (the
    log2-bucket delta between consecutive snapshots, so the percentile
    series tracks the LAST interval's latency, not the lifetime
    average's slow drift);
  - every signal lands in a bounded ring (`TPU6824_PULSE_CAP` points per
    series, oldest dropped) — `series()` is the one snapshot shape,
    served over the fabric_service wire as the `pulse` RPC and merged
    fleet-wide by the kernelscope `Collector`;
  - observers (the watchdog) run on the sampling clock, so detection
    latency is one sampling interval by construction.

Zero-overhead-when-idle contract: nothing here runs unless a Pulse is
explicitly started — there is no import-time thread, no hot-path hook,
and no per-op allocation anywhere (sampling cost is registry-snapshot
granular, on pulse's own thread).  With a fabric attached, each tick
also polls `fabric.stats()` so the health gauges and stall diagnosis are
exactly as fresh as the last sample — stats() is a pure read by the
kernelscope contract, so sampling never perturbs the clock thread.

This module also owns the ENVIRONMENT probes bench.py records per
artifact (`environment_snapshot`, `calibration_spin`): the r08 bring-up
proved the box's effective CPU swings 2-5× run-to-run, which benchdiff
can only discount if every artifact carries its own environment
evidence.  Stdlib-only like the rest of obs/.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from tpu6824.obs import metrics as _metrics
from tpu6824.utils import crashsink

__all__ = ["Pulse", "start", "stop", "get", "series_snapshot",
           "environment_snapshot", "calibration_spin", "read_rss_bytes",
           "read_peak_rss_bytes", "SCHEMA_VERSION"]

SCHEMA_VERSION = "pulse-1.0.0"

_DEF_INTERVAL = float(os.environ.get("TPU6824_PULSE_INTERVAL", "1.0"))
_DEF_CAP = int(os.environ.get("TPU6824_PULSE_CAP", "600"))

# Process RSS, refreshed once per sampling tick (ISSUE 14, horizon):
# the one host-memory series the bounded-memory soaks and the
# memory-growth watchdog rule read.  Gauge created at module scope per
# the metric-unregistered rule; reading /proc/self/statm is one small
# file read per tick — sampling-clock granular, zero hot-path cost.
_G_RSS = _metrics.gauge("proc.rss_bytes")
try:
    _PAGE_BYTES = os.sysconf("SC_PAGE_SIZE")
except (ValueError, OSError, AttributeError):  # non-POSIX fallback
    _PAGE_BYTES = 4096


def read_rss_bytes() -> int | None:
    """Resident set size of THIS process in bytes (None where /proc is
    unavailable) — stdlib-only like the rest of obs/."""
    try:
        # tpusan: ok(blocking-io-in-telemetry-path) — one tiny procfs
        # read per sampling tick is the documented cost of the RSS
        # gauge (module comment above); procfs never blocks on storage
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE_BYTES
    except (OSError, ValueError, IndexError):
        try:
            import resource
            import sys as _sys

            # Peak, not current — still a usable upper-bound signal
            # where /proc is missing.  ru_maxrss is KiB on Linux but
            # BYTES on macOS (the platform most likely to take this
            # path): scaling unconditionally would inflate it 1024x
            # and false-fire the memory-growth rule.
            peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            return peak if _sys.platform == "darwin" else peak * 1024
        except Exception:  # noqa: BLE001 — telemetry, never fatal
            return None


def read_peak_rss_bytes() -> int:
    """Process-lifetime resident high-water mark in bytes (0 where
    rusage is unavailable).  THE one home of the platform-sensitive
    ru_maxrss scaling rule — KiB on Linux, bytes on macOS — so callers
    (bench's mem blocks) cannot drift from read_rss_bytes' fallback."""
    try:
        import resource
        import sys as _sys

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return peak if _sys.platform == "darwin" else peak * 1024
    except Exception:  # noqa: BLE001 — telemetry, never fatal
        return 0


class Pulse:
    """Bounded ring time-series over the process-global metrics registry.

    `fabric` (optional): a local PaxosFabric whose `stats()` is polled
    every tick — refreshing the registry's health gauges and keeping
    `last_stats` (the watchdog's stall/crash evidence) one interval
    fresh.  `stall_after` forwards to `stats(stall_after=)` so a
    watchdog can run a tighter stall window than the fabric default.
    """

    def __init__(self, fabric=None, interval: float | None = None,
                 cap: int | None = None, stall_after: float | None = None):
        self.interval = _DEF_INTERVAL if interval is None else float(interval)
        self.cap = _DEF_CAP if cap is None else int(cap)
        self.fabric = fabric
        self.stall_after = stall_after
        self._mu = threading.Lock()
        # name -> {"kind": rate|gauge|quantile, "points": deque[(t, v)]}
        self._series: dict[str, dict] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._prev: tuple[float, dict] | None = None
        # Observer registry (the watchdog), called on the sampling
        # thread after each tick: fn(pulse, now).
        self._observers: list = []
        # Sampler registry (ISSUE 14): zero-arg callables invoked at
        # the TOP of each tick, BEFORE the registry snapshot — how the
        # service layer (services.horizon row-count gauges) refreshes
        # gauges at sampling cadence without obs/ importing services.
        self._samplers: list = []
        self.samples = 0
        self.last_stats: dict | None = None
        self.t_started: float | None = None

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "Pulse":
        if self._thread is not None:
            return self
        # A restarted instance must sample again: without this, a
        # stop()/start() cycle leaves _stop set and the new thread
        # exits after one sample — a silently frozen series.
        self._stop.clear()
        self.t_started = time.monotonic()
        self._thread = threading.Thread(
            target=crashsink.guarded(self._run, "pulse"), daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def add_observer(self, fn) -> None:
        with self._mu:
            if fn not in self._observers:
                # tpusan: ok(unbounded-obs-buffer) — observer registry:
                # one callback per attached watchdog, deduplicated
                # above; it never accumulates samples
                self._observers.append(fn)

    def remove_observer(self, fn) -> None:
        with self._mu:
            if fn in self._observers:
                self._observers.remove(fn)

    def add_sampler(self, fn) -> None:
        with self._mu:
            if fn not in self._samplers:
                # tpusan: ok(unbounded-obs-buffer) — sampler registry:
                # one callable per attached gauge source, deduplicated
                # above; it never accumulates samples
                self._samplers.append(fn)

    def remove_sampler(self, fn) -> None:
        with self._mu:
            if fn in self._samplers:
                self._samplers.remove(fn)

    def _all_samplers(self) -> list:
        with _sampler_mu:
            g = list(_GLOBAL_SAMPLERS)
        with self._mu:
            return g + [f for f in self._samplers if f not in g]

    def _all_observers(self) -> list:
        with _observer_mu:
            g = list(_GLOBAL_OBSERVERS)
        with self._mu:
            return g + [f for f in self._observers if f not in g]

    # ----------------------------------------------------------- sampling

    def _run(self) -> None:
        # First tick immediately: it sets the rate baseline (no points
        # are recorded until the second tick gives a delta window).
        self.sample_once()
        while not self._stop.wait(self.interval):
            self.sample_once()

    def sample_once(self) -> None:
        """One sampling tick (public so tests can drive the clock
        deterministically without the thread)."""
        now = time.monotonic()
        rss = read_rss_bytes()
        if rss is not None:
            _G_RSS.set(rss)
        for fn in self._all_samplers():
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — a broken gauge
                # source must not kill the sampling clock.
                crashsink.record("pulse-sampler", e, fatal=False)
        if self.fabric is not None:
            try:
                self.last_stats = (
                    self.fabric.stats() if self.stall_after is None
                    else self.fabric.stats(stall_after=self.stall_after))
            except Exception as e:  # noqa: BLE001 — a dying fabric is data
                self.last_stats = {"error": repr(e)[:200]}
        snap = _metrics.snapshot()
        prev = self._prev
        self._prev = (now, snap)
        if prev is not None:
            t_prev, snap_prev = prev
            dt = max(now - t_prev, 1e-9)
            delta = _metrics.diff_snapshots(snap_prev, snap)
            with self._mu:
                updated: set[str] = set()
                for name, c in delta.get("counters", {}).items():
                    updated.add(self._record_locked(
                        f"{name}.rate", "rate", now, c["total"] / dt))
                for name, g in snap.get("gauges", {}).items():
                    self._record_locked(name, "gauge", now, g["value"])
                for name, h in delta.get("histograms", {}).items():
                    # Per-interval percentiles (delta buckets), top-level
                    # histograms only — per-key sub-series would make
                    # series cardinality data-dependent.
                    updated.add(self._record_locked(
                        f"{name}.rate", "rate", now, h["count"] / dt))
                    for q in ("p50", "p95", "p99"):
                        if h.get(q) is not None:
                            self._record_locked(f"{name}.{q}", "quantile",
                                                now, h[q])
                # diff_snapshots drops zero deltas (right for bench
                # attribution), but a rate SERIES must record the idle
                # intervals explicitly — a throughput collapse IS a run
                # of zeros, and the watchdog can only see what's in the
                # ring.  Quantile series stay sparse by design (an
                # interval with no observations has no percentile).
                for name, s in self._series.items():
                    if s["kind"] == "rate" and name not in updated:
                        s["points"].append((round(now, 6), 0.0))
            self.samples += 1
        # Snapshot under the registry locks: add_observer appends from
        # attach threads while this sampler iterates, and a bare list()
        # of a mutating list is not atomic without the GIL.
        for fn in self._all_observers():
            try:
                fn(self, now)
            except Exception as e:  # noqa: BLE001 — a broken watchdog rule
                # must not kill the sampling clock; recorded, not fatal.
                crashsink.record("pulse-observer", e, fatal=False)

    def _record_locked(self, name: str, kind: str, t: float, v) -> str:
        s = self._series.get(name)
        if s is None:
            s = self._series[name] = {
                "kind": kind, "points": deque(maxlen=self.cap)}
        s["points"].append((round(t, 6), round(float(v), 6)))
        return name

    # ----------------------------------------------------------- snapshot

    def series(self, names=None, window: float | None = None) -> dict:
        """The one snapshot shape: `{"schema", "enabled", "interval",
        "cap", "samples", "t_mono", "series": {name: {"kind", "t",
        "v"}}}` — timestamps are `time.monotonic()` seconds, joinable
        against flight-recorder `ts` (ns) and the nemesis timeline's
        `t0`.  `window` keeps only points newer than `now - window`;
        `names` filters to the listed series."""
        now = time.monotonic()
        cutoff = None if window is None else now - window
        out: dict[str, dict] = {}
        with self._mu:
            for name, s in self._series.items():
                if names is not None and name not in names:
                    continue
                pts = list(s["points"])
                if cutoff is not None:
                    pts = [p for p in pts if p[0] >= cutoff]
                if not pts:
                    continue
                out[name] = {"kind": s["kind"],
                             "t": [p[0] for p in pts],
                             "v": [p[1] for p in pts]}
        return {"schema": SCHEMA_VERSION, "enabled": True,
                "interval": self.interval, "cap": self.cap,
                "samples": self.samples, "t_mono": round(now, 6),
                "series": out}

    # -------------------------------------------------- rule-side helpers

    def points(self, name: str, window: float | None = None) -> list:
        """[(t, v)] for one series (most-recent last), optionally
        windowed — the watchdog's read primitive."""
        cutoff = None if window is None else time.monotonic() - window
        with self._mu:
            s = self._series.get(name)
            if s is None:
                return []
            pts = list(s["points"])
        return pts if cutoff is None else [p for p in pts if p[0] >= cutoff]

    def last(self, name: str):
        pts = self.points(name)
        return pts[-1][1] if pts else None

    def names(self) -> list[str]:
        with self._mu:
            return list(self._series)


# ------------------------------------------------- process-global pulse

_PULSE: Pulse | None = None
_pulse_mu = threading.Lock()

# Global sampler registry: gauge sources that must be sampled by
# WHICHEVER pulse runs, regardless of registration order (a server
# constructed before pulse.start() still gets its gauges refreshed).
# Bounded: one deduplicated callable per gauge source, never samples.
_GLOBAL_SAMPLERS: list = []
_sampler_mu = threading.Lock()


def add_global_sampler(fn) -> None:
    """Register a gauge-refresh callable with EVERY pulse instance
    (current and future) — the order-independent form of
    `Pulse.add_sampler`, used by services.horizon's row-count gauges."""
    with _sampler_mu:
        if fn not in _GLOBAL_SAMPLERS:
            _GLOBAL_SAMPLERS.append(fn)


def remove_global_sampler(fn) -> None:
    with _sampler_mu:
        if fn in _GLOBAL_SAMPLERS:
            _GLOBAL_SAMPLERS.remove(fn)


# Global observer registry (ISSUE 20): tick callbacks `fn(pulse, now)`
# that must run on WHICHEVER pulse samples, regardless of registration
# order — how blackbox records a pulse/opscope snapshot per tick without
# holding a reference to any particular Pulse.  Bounded: one
# deduplicated callable per consumer, never accumulates samples.
_GLOBAL_OBSERVERS: list = []
_observer_mu = threading.Lock()


def add_global_observer(fn) -> None:
    """Register a per-tick observer with EVERY pulse instance (current
    and future) — the order-independent form of `Pulse.add_observer`."""
    with _observer_mu:
        if fn not in _GLOBAL_OBSERVERS:
            _GLOBAL_OBSERVERS.append(fn)


def remove_global_observer(fn) -> None:
    with _observer_mu:
        if fn in _GLOBAL_OBSERVERS:
            _GLOBAL_OBSERVERS.remove(fn)


def start(fabric=None, interval: float | None = None,
          cap: int | None = None, stall_after: float | None = None) -> Pulse:
    """Start (or return) THE process pulse — the instance the fabric's
    `pulse` RPC serves and the watchdog rides."""
    global _PULSE
    with _pulse_mu:
        if _PULSE is None:
            _PULSE = Pulse(fabric=fabric, interval=interval, cap=cap,
                           stall_after=stall_after).start()
        return _PULSE


def stop() -> None:
    global _PULSE
    with _pulse_mu:
        p, _PULSE = _PULSE, None
    if p is not None:
        p.stop()


def get() -> Pulse | None:
    return _PULSE


def series_snapshot(window: float | None = None) -> dict:
    """The wire shape of the process pulse: the running instance's
    `series()`, or a stable `enabled: False` shell when no pulse runs —
    pollers and the fleet collector never see a missing surface flip
    shape."""
    p = _PULSE
    if p is None:
        return {"schema": SCHEMA_VERSION, "enabled": False,
                "interval": None, "cap": None, "samples": 0,
                "t_mono": round(time.monotonic(), 6), "series": {}}
    return p.series(window=window)


# ------------------------------------------------- environment probes


def _read_first(*paths: str) -> str | None:
    for p in paths:
        try:
            with open(p) as f:
                return f.read().strip()
        except OSError:
            continue
    return None


def environment_snapshot() -> dict:
    """What the box looks like RIGHT NOW: cgroup cpu quota/shares (v2
    then v1), load averages, cpu count, and the derived effective-cpu
    budget.  Every BENCH artifact records one so benchdiff can tell "the
    code got slower" from "the box got smaller" — the r08 lesson
    (service.value −55% with zero code change, pristine-reproduced)."""
    cg: dict = {}
    eff = None
    v2 = _read_first("/sys/fs/cgroup/cpu.max")
    if v2:
        parts = v2.split()
        quota = None if parts[0] == "max" else int(parts[0])
        period = int(parts[1]) if len(parts) > 1 else 100000
        cg["cpu_max"] = v2
        if quota:
            eff = round(quota / period, 3)
    w = _read_first("/sys/fs/cgroup/cpu.weight")
    if w:
        cg["cpu_weight"] = int(w)
    q1 = _read_first("/sys/fs/cgroup/cpu/cpu.cfs_quota_us",
                     "/sys/fs/cgroup/cpu,cpuacct/cpu.cfs_quota_us")
    p1 = _read_first("/sys/fs/cgroup/cpu/cpu.cfs_period_us",
                     "/sys/fs/cgroup/cpu,cpuacct/cpu.cfs_period_us")
    if q1 and p1:
        cg["cfs_quota_us"] = int(q1)
        cg["cfs_period_us"] = int(p1)
        if eff is None and int(q1) > 0:
            eff = round(int(q1) / int(p1), 3)
    s1 = _read_first("/sys/fs/cgroup/cpu/cpu.shares",
                     "/sys/fs/cgroup/cpu,cpuacct/cpu.shares")
    if s1:
        cg["cpu_shares"] = int(s1)
    cpus = os.cpu_count() or 1
    try:
        loadavg = [round(x, 3) for x in os.getloadavg()]
    except OSError:
        loadavg = None
    return {"cpus": cpus,
            "effective_cpus": eff if eff is not None else float(cpus),
            "cgroup": cg, "loadavg": loadavg}


# Fixed calibration workload: pure-Python integer LCG churn — no numpy,
# no allocation growth, identical work every call, so wall time measures
# the BOX (scheduler share, frequency, contention), not the code under
# bench.  ~10-30ms on a healthy core.
_CAL_ITERS = 200_000


def calibration_spin(iters: int = _CAL_ITERS) -> float:
    """Wall milliseconds for the fixed calibration workload.  bench runs
    one at every leg boundary; a leg bracketed by slow spins ran on a
    degraded box, and benchdiff discounts its regression verdicts to
    `suspect-environment` accordingly."""
    t0 = time.perf_counter()
    acc = 12345
    for i in range(iters):
        acc = (acc * 1103515245 + i) & 0xFFFFFFFF
    if acc < 0:  # unreachable; keeps `acc` live against optimizers
        raise AssertionError
    return round((time.perf_counter() - t0) * 1e3, 3)
