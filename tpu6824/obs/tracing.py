"""tpuscope tracing — causal per-op spans + an always-on flight recorder.

Before this module a clerk op's life was only visible as aggregates
(`PhaseProfiler` wall-time buckets, `EventLog` counters).  tpuscope makes
the op itself the unit: a `TraceContext` (trace_id, span_id) is born at
the clerk, carried through the RPC envelope (`rpc/transport.py`'s
optional third frame element), stamped by the service into the proposed
value's metadata (`Op.tc`), recovered on the decided-feed/apply side,
and closed at the clerk reply — so one op's spans read
clerk → rpc → service-submit → fabric-dispatch → apply → reply in
parent/child order, interleaved with the fabric's batch events
(stage/dispatch/retire and per-(g, p) feed deliveries).

Two regimes, by design:

  - **Tracing** (`TPU6824_TRACE=1` / `enable()`, default OFF): per-op
    spans.  When off, every producer's guard (`span()` returns None,
    `enabled()` is False) keeps the hot path at ZERO per-op allocations
    — the steady-state jitguard and bench contracts assume this.
    `TPU6824_TRACE_SAMPLE` (0..1) samples ROOT creation, so a loaded
    deployment can trace 1% of ops.
  - **Flight recorder** (always on): a bounded ring of recent spans and
    instant events across all components (fabric batch events, nemesis
    injections, any finished span).  Batch/fault granularity only —
    nothing per-op lands here unless tracing is on.  The nemesis
    failure artifact dumps the ring, so a linearizability violation
    ships with the correlated trace of the offending ops
    (`TPU6824_FLIGHT_CAP` sizes the ring).

Timestamps are `time.monotonic_ns()` throughout — joinable against the
nemesis timeline's monotonic `wall` offsets via the artifact's `t0`.
`export_trace(path)` writes Chrome trace-event JSON (load in Perfetto /
chrome://tracing) alongside the `jax.profiler` device traces
`utils/profiling.py` already captures.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import random
import threading
import time
from collections import deque
from typing import NamedTuple

from tpu6824.obs import metrics as _metrics

SCHEMA_VERSION = "tpuscope-1.0.0"

# Ring-overflow drop count as a registry gauge, so the pulse/watchdog
# layer can rule on "the flight recorder is eating evidence" without
# polling flight_snapshot() (module scope per metric-unregistered).
_G_FLIGHT_DROPPED = _metrics.gauge("obs.flight.dropped")

_ENABLED = os.environ.get("TPU6824_TRACE", "") in ("1", "true", "yes")
_SAMPLE = float(os.environ.get("TPU6824_TRACE_SAMPLE", "1.0"))
_FLIGHT_CAP = int(os.environ.get("TPU6824_FLIGHT_CAP", 16384))

# itertools.count.__next__ is atomic under the GIL — ids are unique
# across threads without a lock.
_ids = itertools.count(1)
_tls = threading.local()
_rng = random.Random()


class TraceContext(NamedTuple):
    """The portable identity of 'the current span': what rides the RPC
    envelope and the proposed value's metadata (as a plain 2-tuple)."""

    trace_id: int
    span_id: int


def enabled() -> bool:
    return _ENABLED


def fresh_id() -> int:
    """A fresh process-unique id (the span-id counter) — for producers
    that synthesize complete span chains outside the live-span path
    (opscope's tail exemplars need a root trace id with tracing OFF)."""
    return next(_ids)


def enable(sample: float = 1.0) -> None:
    """Turn per-op tracing on (tests / live opt-in)."""
    global _ENABLED, _SAMPLE
    _SAMPLE = sample
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False
    _tls.ctx = None


def current() -> TraceContext | None:
    """The calling thread's active context (None when untraced)."""
    return getattr(_tls, "ctx", None)


@contextlib.contextmanager
def use_ctx(ctx: TraceContext | None):
    """Make `ctx` the thread's active context for the enclosed region
    (RPC servers wrap handler invocation in this; in-process call legs
    wrap the downcall)."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield
    finally:
        _tls.ctx = prev


# ------------------------------------------------------- flight recorder


class FlightRecorder:
    """Bounded, always-on ring of recent span/event records.  Records are
    flat dicts (see `complete`/`event` for the shape); overflow drops the
    oldest and counts the drop — no silent caps."""

    def __init__(self, capacity: int = _FLIGHT_CAP):
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, capacity))
        self.dropped = 0
        # Lifetime append count — the cursor clock for delta drains
        # (blackbox persists only what arrived since its last sync).
        self.appended = 0

    def record(self, rec: dict) -> None:
        dropped = None
        with self._mu:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
                dropped = self.dropped
            self._ring.append(rec)
            self.appended += 1
        if dropped is not None:
            # Gauge mirror outside self._mu (the registry takes its own
            # lock); records are batch/fault granular, and the set only
            # happens in the overflow regime the gauge exists to expose.
            _G_FLIGHT_DROPPED.set(dropped)

    def snapshot(self) -> list[dict]:
        with self._mu:
            return list(self._ring)

    def snapshot_delta(self, cursor: int) -> tuple[list[dict], int, int]:
        """Records appended since `cursor` (a previous return's second
        element; start at 0) as `(records, new_cursor, missed)` —
        `missed` counts records that arrived since the cursor but were
        already pushed out of the bounded ring.  A cursor from before a
        `clear()` self-heals to "everything currently in the ring"."""
        with self._mu:
            total = self.appended
            new = total - cursor
            if new <= 0:
                # cursor at (or, post-clear, beyond) the present
                return [], total, 0
            ring = list(self._ring)
            if new >= len(ring):
                return ring, total, new - len(ring)
            return ring[-new:], total, 0

    def clear(self) -> None:
        with self._mu:
            self._ring.clear()
            self.dropped = 0
            self.appended = 0
        _G_FLIGHT_DROPPED.set(0)


FLIGHT = FlightRecorder()


# ----------------------------------------------------------------- spans


def complete(name: str, trace_id: int, parent_id: int, t0_ns: int,
             t1_ns: int | None = None, comp: str = "app", **args) -> int:
    """Record a FINISHED span with explicit timestamps (the apply side
    emits fabric-dispatch/apply spans retroactively from the proposal
    record).  Returns the new span's id so the caller can chain
    children."""
    sid = next(_ids)
    if t1_ns is None:
        t1_ns = time.monotonic_ns()
    FLIGHT.record({"ph": "X", "name": name, "comp": comp,
                   "trace_id": trace_id, "span_id": sid,
                   "parent_id": parent_id, "ts": t0_ns,
                   "dur": max(0, t1_ns - t0_ns), "args": args})
    return sid


class Span:
    """One open span; `end()` records it into the flight ring.  Only
    ever constructed when tracing is enabled (via `span()`/`child()`)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "comp",
                 "t0_ns", "args")

    def __init__(self, name: str, trace_id: int, parent_id: int,
                 comp: str, args: dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = next(_ids)
        self.parent_id = parent_id
        self.comp = comp
        self.t0_ns = time.monotonic_ns()
        self.args = args

    @property
    def ctx(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id)

    def end(self, **more) -> None:
        if more:
            self.args.update(more)
        FLIGHT.record({"ph": "X", "name": self.name, "comp": self.comp,
                       "trace_id": self.trace_id, "span_id": self.span_id,
                       "parent_id": self.parent_id, "ts": self.t0_ns,
                       "dur": time.monotonic_ns() - self.t0_ns,
                       "args": self.args})


def span(name: str, comp: str = "app", **args) -> Span | None:
    """Open a span: child of the thread's current context when one is
    active, otherwise a NEW ROOT (subject to `TPU6824_TRACE_SAMPLE`).
    Returns None when tracing is disabled or the root was sampled out —
    callers guard with `if sp is not None`."""
    if not _ENABLED:
        return None
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        return Span(name, ctx.trace_id, ctx.span_id, comp, args)
    if _SAMPLE < 1.0 and _rng.random() >= _SAMPLE:
        return None
    return Span(name, next(_ids), 0, comp, args)


def child(name: str, parent: TraceContext | None = None,
          comp: str = "app", **args) -> Span | None:
    """Open a span that must have a parent (explicit, or the thread's
    current context) — never a root.  None when disabled or parentless,
    so mid-stack producers cannot accidentally start orphan traces."""
    if not _ENABLED:
        return None
    ctx = parent if parent is not None else getattr(_tls, "ctx", None)
    if ctx is None:
        return None
    return Span(name, ctx.trace_id, ctx.span_id, comp, args)


def event(name: str, comp: str = "app", trace_id: int = 0,
          args: dict | None = None, **kw) -> None:
    """Instant event straight into the flight ring — ALWAYS ON (fault
    injections, config pushes; never call per-op on a hot path).  Pass
    `args` as a dict when payload keys could collide with this
    signature's parameter names (e.g. a fault's `name` argument)."""
    a = dict(args) if args else {}
    if kw:
        a.update(kw)
    FLIGHT.record({"ph": "i", "name": name, "comp": comp,
                   "trace_id": trace_id, "span_id": next(_ids),
                   "parent_id": 0, "ts": time.monotonic_ns(), "dur": 0,
                   "args": a})


def batch(name: str, t0_ns: int, comp: str = "fabric", **args) -> None:
    """Batch-granularity span (one per fabric stage/dispatch/retire, not
    per op) into the flight ring — always on; producers gate on activity
    so an idle clock doesn't flood the ring."""
    FLIGHT.record({"ph": "X", "name": name, "comp": comp,
                   "trace_id": 0, "span_id": next(_ids), "parent_id": 0,
                   "ts": t0_ns, "dur": time.monotonic_ns() - t0_ns,
                   "args": args})


# ---------------------------------------------------------------- export


def chrome_events(records, process: str | None = None, pid: int = 1,
                  trace_id: int | None = None) -> list[dict]:
    """Flight-ring records → Chrome trace events, NAMESPACED per process.

    Every span/instant is emitted under `pid`; component thread names are
    prefixed with `process` (when given), and `process`/the raw
    trace/span/parent ids ride in args qualified by the process name — so
    when the kernelscope collector concatenates several processes' rings
    into ONE file, span ids that collide numerically (every process
    counts from 1) stay distinguishable and the timelines render as
    separate process tracks instead of interleaving into one.  A
    `process_name` metadata event labels the track.  With `trace_id`,
    only that trace's spans plus the untagged batch events (trace_id 0)
    are kept."""
    comp_tid: dict[str, int] = {}
    evs = []
    for r in records:
        if trace_id is not None and r["trace_id"] not in (trace_id, 0):
            continue
        tid = comp_tid.setdefault(r["comp"], len(comp_tid) + 1)
        args = {"trace_id": r["trace_id"], "span_id": r["span_id"],
                "parent_id": r["parent_id"], **r["args"]}
        if process is not None:
            args["proc"] = process
        ev = {"name": r["name"], "ph": r["ph"], "pid": pid, "tid": tid,
              "ts": r["ts"] / 1e3,  # chrome wants microseconds
              "args": args}
        if r["ph"] == "X":
            ev["dur"] = r["dur"] / 1e3
        else:
            ev["s"] = "g"
        evs.append(ev)
    meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": (f"{process}/{comp}" if process else comp)}}
            for comp, tid in comp_tid.items()]
    if process is not None:
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": process}})
    return meta + evs


def write_chrome_trace(path: str, events: list[dict]) -> str:
    """Wrap prepared Chrome events in the trace-file envelope."""
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms",
                   "metadata": {"tpuscope": SCHEMA_VERSION}}, f)
    return path


def export_trace(path: str, trace_id: int | None = None,
                 process: str | None = None) -> str:
    """Write the flight ring as Chrome trace-event JSON (Perfetto /
    chrome://tracing / `perfetto.dev` all load it).  With `trace_id`,
    only that trace's spans plus the untagged batch events (trace_id 0)
    are exported, so one op's causal chain stays readable against the
    fabric batches that carried it.  `process` namespaces the export's
    pid/thread names (see `chrome_events`) for merge-safe multi-process
    use; single-process exports keep the bare component names.  Returns
    `path`."""
    return write_chrome_trace(
        path, chrome_events(FLIGHT.snapshot(), process=process,
                            pid=(os.getpid() if process else 1),
                            trace_id=trace_id))


def flight_snapshot() -> dict:
    """The flight recorder as one JSON-safe block (the nemesis artifact's
    `flight_recorder` section)."""
    return {"schema": SCHEMA_VERSION, "capacity": FLIGHT._ring.maxlen,
            "dropped": FLIGHT.dropped, "pid": os.getpid(),
            "records": FLIGHT.snapshot()}
