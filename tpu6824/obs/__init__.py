"""tpu6824.obs — "tpuscope": the observability layer.

Three parts, threaded through every other layer (ISSUE 5):

  - `obs.tracing` — causal per-op spans (clerk → rpc → service-submit →
    fabric-dispatch → apply → reply) + the always-on flight recorder +
    Chrome/Perfetto export.  `TPU6824_TRACE=1` turns per-op spans on;
    default-off costs zero per-op allocations.
  - `obs.metrics` — the process-global metrics registry (counters,
    gauges, log2-bucket histograms) absorbing the EventLog counters,
    RPC transport per-method counts/latencies, clerk backoff/retries,
    and fabric health; one `snapshot()` JSON shape, served over the
    fabric_service wire and dumped into BENCH_*.json.
  - the flight recorder's dump rides the nemesis failure artifact
    (`harness/nemesis.py::ReplayArtifact`), so a linearizability
    violation ships with the correlated trace of the offending ops.

kernelscope (ISSUE 6) adds two fleet-level tools on top:

  - `obs.collector` — poll `stats()/metrics()/flight()/pulse()` from
    every process of a wire deployment (plus the local process) into
    ONE namespaced snapshot and ONE merged Perfetto timeline; sums the
    device-resident per-group protocol counters fleet-wide.
  - `obs.benchdiff` — `python -m tpu6824.obs.benchdiff OLD NEW`
    compares two BENCH_*.json artifacts per leg/metric with noise
    thresholds and exits non-zero on regression; artifacts carrying an
    `environment` block (cgroup quota, loadavg, calibration spins) get
    host-edge regressions demoted to `suspect-environment` when the
    box itself demonstrably degraded between the runs.

opscope (ISSUE 15) adds the *which stage* layer:

  - `obs.opscope` — always-on columnar per-stage latency attribution:
    stage timestamps ride the request path as parallel int64
    monotonic-ns columns (frame parse → engine poll → park →
    materialize → dispatch → decide → apply → reply → flush), folded
    per drain into per-stage log2 histograms, with the K slowest ops
    per pulse interval promoted into the flight recorder as synthetic
    span chains (tail-based capture, no TPU6824_TRACE needed).  Served
    as the `opscope` RPC, merged fleet-wide by the Collector, rendered
    by obs.top's waterfall pane, decomposed per bench leg.

pulse (ISSUE 10) adds the *over time* layer:

  - `obs.pulse` — continuous bounded-ring time-series over the
    registry (counters→rates, gauges, per-interval histogram
    p50/p95/p99), served as the fabric_service `pulse` RPC and merged
    fleet-wide by the Collector; also owns the environment probes
    (`environment_snapshot`/`calibration_spin`) bench records.
  - `obs.watchdog` — rules over those series (stalls with kernelscope
    diagnosis, throughput collapse, latency spikes, queue growth,
    thread crashes, drop climb, steady-state recompiles); on trigger it
    auto-captures an evidence bundle in the nemesis-artifact format.
  - `python -m tpu6824.obs.top` — live single-process-or-fleet
    terminal dashboard; `--once --json` for scripting/CI.

Stdlib-only on purpose: importable from the analysis CLI, daemons, and
clerks without dragging in JAX.
"""

from tpu6824.obs import (  # noqa: F401
    collector,
    metrics,
    opscope,
    pulse,
    tracing,
    watchdog,
)
from tpu6824.obs.collector import Collector, local_handle  # noqa: F401
from tpu6824.obs.tracing import (  # noqa: F401
    FLIGHT,
    SCHEMA_VERSION,
    TraceContext,
    batch,
    child,
    complete,
    current,
    disable,
    enable,
    enabled,
    event,
    export_trace,
    flight_snapshot,
    span,
    use_ctx,
)
