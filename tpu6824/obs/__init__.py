"""tpu6824.obs — "tpuscope": the observability layer.

Three parts, threaded through every other layer (ISSUE 5):

  - `obs.tracing` — causal per-op spans (clerk → rpc → service-submit →
    fabric-dispatch → apply → reply) + the always-on flight recorder +
    Chrome/Perfetto export.  `TPU6824_TRACE=1` turns per-op spans on;
    default-off costs zero per-op allocations.
  - `obs.metrics` — the process-global metrics registry (counters,
    gauges, log2-bucket histograms) absorbing the EventLog counters,
    RPC transport per-method counts/latencies, clerk backoff/retries,
    and fabric health; one `snapshot()` JSON shape, served over the
    fabric_service wire and dumped into BENCH_*.json.
  - the flight recorder's dump rides the nemesis failure artifact
    (`harness/nemesis.py::ReplayArtifact`), so a linearizability
    violation ships with the correlated trace of the offending ops.

kernelscope (ISSUE 6) adds two fleet-level tools on top:

  - `obs.collector` — poll `stats()/metrics()/flight()` from every
    process of a wire deployment (plus the local process) into ONE
    namespaced snapshot and ONE merged Perfetto timeline; sums the
    device-resident per-group protocol counters fleet-wide.
  - `obs.benchdiff` — `python -m tpu6824.obs.benchdiff OLD NEW`
    compares two BENCH_*.json artifacts per leg/metric with noise
    thresholds and exits non-zero on regression.

Stdlib-only on purpose: importable from the analysis CLI, daemons, and
clerks without dragging in JAX.
"""

from tpu6824.obs import collector, metrics, tracing  # noqa: F401
from tpu6824.obs.collector import Collector, local_handle  # noqa: F401
from tpu6824.obs.tracing import (  # noqa: F401
    FLIGHT,
    SCHEMA_VERSION,
    TraceContext,
    batch,
    child,
    complete,
    current,
    disable,
    enable,
    enabled,
    event,
    export_trace,
    flight_snapshot,
    span,
    use_ctx,
)
