"""tpuscope metrics — one process-global registry for every component.

Before this module each layer kept its own counters: the fabric's
`EventLog`, the RPC servers' `rpc_count`, ad-hoc bench accumulators.
There was no single surface answering "what is this process doing" — the
question every production poller asks.  The registry holds three metric
kinds behind get-or-create constructors:

  - `Counter`  — monotonic totals, with optional per-key sub-counts
    (e.g. RPC calls by method name);
  - `Gauge`    — last-written values (feed depth, stalled groups);
  - `Histogram`— fixed log2 buckets (bucket k counts observations in
    [2^(k-1), 2^k), i.e. bit_length(v) == k), so `observe()` is a
    bit_length + one int add — no per-observation allocation, ever.

Hot-path discipline (enforced by the tpusan `metric-unregistered` rule):
metric OBJECTS are created via `metrics.counter/gauge/histogram` at
module scope; hot loops only call `.inc()/.set()/.observe()` on the
already-created object.  Batch producers (the decided-feed fan-out, the
EventLog mirror) update once per BATCH, columnar, per the feed-columnar
contract.  `metrics.inc()` is the sanctioned dynamic-name path: the
get-or-create lives here, inside the registry, not at the call site.

`snapshot()` returns one JSON-safe dict — served over the fabric_service
wire (`PaxosFabric.metrics`) and dumped by the bench legs into
`BENCH_*.json`.
"""

from __future__ import annotations

import threading

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
           "counter", "gauge", "histogram", "inc", "set_gauge",
           "snapshot", "diff_snapshots", "reset"]

_NBUCKETS = 64  # log2 buckets cover any int64-scale observation


def _bucket_quantile(buckets, count: int, q: float) -> float:
    """Bucket-resolution quantile over a log2 bucket list: the exclusive
    upper bound (2^k) of the bucket holding the q'th observation."""
    target = q * count
    seen = 0
    for k, c in enumerate(buckets):
        seen += c
        if c and seen >= target:
            return float(1 << k)
    return 0.0


class Counter:
    """Monotonic total + optional per-key sub-totals (key cardinality is
    the caller's responsibility — method names, not user data)."""

    __slots__ = ("name", "_mu", "total", "by")

    def __init__(self, name: str):
        self.name = name
        self._mu = threading.Lock()
        self.total = 0
        self.by: dict[str, int] = {}

    def inc(self, n: int = 1, key: str | None = None) -> None:
        with self._mu:
            self.total += n
            if key is not None:
                self.by[key] = self.by.get(key, 0) + n

    def snapshot(self):
        # Always the same shape — a scalar-until-first-keyed-bump counter
        # would flip type between polls and break every differ downstream.
        with self._mu:
            return {"total": self.total, "by": dict(self.by)}


class Gauge:
    """Last-written value (optionally per key)."""

    __slots__ = ("name", "_mu", "value", "by")

    def __init__(self, name: str):
        self.name = name
        self._mu = threading.Lock()
        self.value = 0.0
        self.by: dict[str, float] = {}

    def set(self, v: float, key: str | None = None) -> None:
        with self._mu:
            if key is None:
                self.value = v
            else:
                self.by[key] = v

    def snapshot(self):
        with self._mu:
            return {"value": self.value, "by": dict(self.by)}


class Histogram:
    """Fixed log2-bucket histogram: bucket k counts observations v with
    bit_length(v) == k, i.e. v in [2^(k-1), 2^k) for positive ints —
    one bit_length + one list-index add per observation, no allocation.
    Values are rounded to non-negative ints by the caller's choice of
    unit (latencies in µs, sizes in cells).  `observe_many` takes any
    iterable for columnar batch updates from feed-path producers."""

    __slots__ = ("name", "_mu", "count", "sum", "_buckets", "by")

    def __init__(self, name: str):
        self.name = name
        self._mu = threading.Lock()
        self.count = 0
        self.sum = 0
        self._buckets = [0] * _NBUCKETS
        self.by: dict[str, Histogram] = {}

    def observe(self, v, key: str | None = None) -> None:
        iv = int(v)
        if iv < 0:
            iv = 0
        b = iv.bit_length()
        if b >= _NBUCKETS:
            b = _NBUCKETS - 1
        with self._mu:
            self.count += 1
            self.sum += iv
            self._buckets[b] += 1
            if key is not None:
                sub = self.by.get(key)
                if sub is None:
                    sub = self.by[key] = Histogram(f"{self.name}.{key}")
        if key is not None:
            sub.observe(iv)

    def observe_many(self, values) -> None:
        """Columnar batch observe (one lock acquisition per batch)."""
        ivs = [max(0, int(v)) for v in values]
        with self._mu:
            for iv in ivs:
                b = iv.bit_length()
                self._buckets[min(b, _NBUCKETS - 1)] += 1
                self.sum += iv
            self.count += len(ivs)

    def add_pow2(self, buckets, count: int, total: int) -> None:
        """Columnar merge of EXTERNALLY-bucketed observations: `buckets`
        is a sequence of per-log2-bucket counts (bucket k = values with
        bit_length k — the same rule `observe` applies), `count`/`total`
        the batch's observation count and value sum.  ONE lock for the
        whole batch — the opscope fold's bincount output and the native
        reply ring's flush histogram both land through here, so the hot
        path never observes per op."""
        with self._mu:
            b = self._buckets
            top = _NBUCKETS - 1
            for k, c in enumerate(buckets):
                if c:
                    b[k if k < top else top] += int(c)
            self.count += int(count)
            self.sum += int(total)

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile estimate (the bucket's exclusive
        upper bound, 2^k)."""
        with self._mu:
            return _bucket_quantile(self._buckets, self.count, q)

    def snapshot(self):
        with self._mu:
            out = {
                "count": self.count,
                "sum": self.sum,
                "pow2": {str(k): c for k, c in enumerate(self._buckets)
                         if c},
                # Estimated quantiles straight from the log2 buckets
                # (bucket upper bound, so at most 2x above the true
                # value) — bench legs and obs/benchdiff consume these
                # without re-deriving bucket math.  Always present, None
                # when the histogram is empty (stable snapshot shape).
                "p50": (_bucket_quantile(self._buckets, self.count, 0.50)
                        if self.count else None),
                "p95": (_bucket_quantile(self._buckets, self.count, 0.95)
                        if self.count else None),
                "p99": (_bucket_quantile(self._buckets, self.count, 0.99)
                        if self.count else None),
            }
            by = {k: h for k, h in self.by.items()}
        out["by"] = {k: h.snapshot() for k, h in by.items()}
        return out


class Registry:
    """name → metric, get-or-create, one per process (`REGISTRY`).
    Re-registering a name with a different kind raises loudly — silent
    type-shadowing would corrupt every poller downstream."""

    def __init__(self):
        self._mu = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get(self, name: str, cls):
        with self._mu:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name)
            elif type(m) is not cls:
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def inc(self, name: str, n: int = 1, key: str | None = None) -> None:
        """Dynamic-name counter bump — the sanctioned path for producers
        whose counter names are data (the EventLog mirror): get-or-create
        happens HERE, inside the registry, not at the hot call site."""
        self._get(name, Counter).inc(n, key=key)

    def set_gauge(self, name: str, v: float,
                  key: str | None = None) -> None:
        """Dynamic-name gauge write — the `inc()` analog for gauges
        whose names are data (the EventLog overflow mirror, keyed by the
        log's registry prefix).  Producers with a static name still
        create the gauge at module scope."""
        self._get(name, Gauge).set(v, key=key)

    def snapshot(self) -> dict:
        """{"counters": {...}, "gauges": {...}, "histograms": {...}} —
        JSON-safe, the one shape every consumer (fabric_service wire,
        bench legs, tests) reads."""
        with self._mu:
            items = list(self._metrics.items())
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in items:
            if isinstance(m, Counter):
                out["counters"][name] = m.snapshot()
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.snapshot()
            else:
                out["histograms"][name] = m.snapshot()
        return out

    def reset(self) -> None:
        """Drop every metric (test isolation only — live metric objects
        held by modules keep working but are no longer snapshot)."""
        with self._mu:
            self._metrics.clear()


REGISTRY = Registry()


def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name: str) -> Histogram:
    return REGISTRY.histogram(name)


def inc(name: str, n: int = 1, key: str | None = None) -> None:
    REGISTRY.inc(name, n, key=key)


def set_gauge(name: str, v: float, key: str | None = None) -> None:
    REGISTRY.set_gauge(name, v, key=key)


def snapshot() -> dict:
    return REGISTRY.snapshot()


def diff_snapshots(before: dict, after: dict) -> dict:
    """`after − before` over two registry `snapshot()` shapes — the
    attribution primitive behind bench's PER-LEG tpuscope sections: take
    a snapshot when a leg starts, diff at its end, and the counters/
    histograms in the result are the leg's own, not the process
    lifetime's.  Counters and histogram counts/sums/buckets subtract
    (metrics absent from `before` diff against zero); gauges are
    last-written values, not accumulators, so the `after` value is kept
    as-is.  Zero-delta counters and histograms are dropped — a leg's
    section names what the leg DID."""
    out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
    b_c = before.get("counters", {})
    for name, a in after.get("counters", {}).items():
        b = b_c.get(name, {})
        total = a["total"] - b.get("total", 0)
        by = {k: v - b.get("by", {}).get(k, 0)
              for k, v in a.get("by", {}).items()
              if v - b.get("by", {}).get(k, 0)}
        if total or by:
            out["counters"][name] = {"total": total, "by": by}
    out["gauges"] = {name: dict(g)
                     for name, g in after.get("gauges", {}).items()}
    b_h = before.get("histograms", {})
    for name, a in after.get("histograms", {}).items():
        d = _diff_hist(b_h.get(name, {}), a)
        if d is not None:
            out["histograms"][name] = d
    return out


def _diff_hist(b: dict, a: dict) -> dict | None:
    count = a.get("count", 0) - b.get("count", 0)
    if count <= 0:
        return None
    b_pow = b.get("pow2", {})
    pow2 = {k: v - b_pow.get(k, 0) for k, v in a.get("pow2", {}).items()
            if v - b_pow.get(k, 0)}
    buckets = [0] * _NBUCKETS
    for k, v in pow2.items():
        buckets[int(k)] = v
    out = {
        "count": count,
        "sum": a.get("sum", 0) - b.get("sum", 0),
        "pow2": pow2,
        "p50": _bucket_quantile(buckets, count, 0.50),
        "p95": _bucket_quantile(buckets, count, 0.95),
        "p99": _bucket_quantile(buckets, count, 0.99),
    }
    sub = {}
    for k, ah in a.get("by", {}).items():
        dh = _diff_hist(b.get("by", {}).get(k, {}), ah)
        if dh is not None:
            sub[k] = dh
    out["by"] = sub
    return out


def reset() -> None:
    REGISTRY.reset()
