"""Wing–Gong linearizability checker over recorded KV histories.

The append-interleaving check (`harness/invariants.py::check_appends`) can
only judge pure-append workloads; it says nothing about mixed
Get/Put/Append histories under churn — a stale read or a lost update that
keeps every marker exactly-once passes it.  This module is the real
yardstick: given a history of timed invocation/response records, decide
whether some total order of the operations (a) respects real time — an op
linearizes somewhere between its call and its return — and (b) is legal
for a KV register (get returns the current value; put replaces; append
concatenates).

Algorithm: Wing & Gong's recursive search ("Testing and verifying
concurrent objects", 1993) with the two refinements Porcupine popularized:

  - **P-compositionality**: linearizability is compositional per object,
    and each key is an independent register — the history is partitioned
    by key and each sub-history checked alone, turning one search over N
    ops into many searches over small per-key windows;
  - **memoized states**: a (remaining-ops, register-value) pair that
    already failed is never re-explored (the cache is what keeps the
    worst case at O(C!) in the concurrency width C, not the history
    length).

Incomplete operations (an invocation whose response was never observed —
clerk timeout, killed server) have UNKNOWN fate: a mutation may or may
not have taken effect, so it may be linearized anywhere after its call or
omitted entirely; an incomplete get constrains nothing and is dropped.

`HistoryClerk` wraps any clerk exposing get/put/append and stamps
monotonic call/return instants into a shared `History`, so existing test
clerks (kvpaxos.Clerk, shardkv.Clerk, wire Proxies behind them) record
without modification.
"""

from __future__ import annotations

import dataclasses
import threading
import time

_INF = float("inf")


@dataclasses.dataclass(frozen=True)
class OpRecord:
    """One invocation/response pair.  `ret` is None when no response was
    observed (fate unknown); `output` is the returned value for get, and
    ignored for put/append."""

    client: object
    kind: str  # 'get' | 'put' | 'append'
    key: str
    value: str  # input payload (put/append); "" for get
    output: str | None
    call: float
    ret: float | None

    def describe(self) -> str:
        arg = f"{self.key!r}, {self.value!r}" if self.kind != "get" \
            else f"{self.key!r}"
        out = "?" if self.ret is None else (
            repr(self.output) if self.kind == "get" else "ok")
        return (f"[{self.call:.6f},"
                f"{'inf' if self.ret is None else f'{self.ret:.6f}'}] "
                f"client {self.client}: {self.kind}({arg}) -> {out}")


class History:
    """Thread-safe recorder shared by every HistoryClerk of a run.  Times
    are monotonic offsets from construction so artifacts are small and
    runs comparable."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ops: list[OpRecord] = []
        self.t0 = time.monotonic()

    def now(self) -> float:
        return time.monotonic() - self.t0

    def record(self, rec: OpRecord) -> None:
        with self._lock:
            self._ops.append(rec)

    def ops(self) -> list[OpRecord]:
        with self._lock:
            return list(self._ops)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ops)


class HistoryClerk:
    """Call/return stamping wrapper around any get/put/append clerk.

    One HistoryClerk = one logical client (its ops are sequential, which
    is what makes the real-time order in the history meaningful).  An
    exception from the underlying clerk records the op as incomplete
    (ret=None, fate unknown) and re-raises — at-most-once machinery may
    still have applied it."""

    _ids = iter(range(1 << 30))
    _ids_lock = threading.Lock()

    def __init__(self, clerk, history: History, client=None):
        self.clerk = clerk
        self.history = history
        if client is None:
            with HistoryClerk._ids_lock:
                client = next(HistoryClerk._ids)
        self.client = client

    def _timed(self, kind: str, key: str, value: str, fn, *args, **kw):
        call = self.history.now()
        try:
            out = fn(*args, **kw)
        except Exception:
            self.history.record(OpRecord(self.client, kind, key, value,
                                         None, call, None))
            raise
        self.history.record(OpRecord(
            self.client, kind, key, value,
            out if kind == "get" else None, call, self.history.now()))
        return out

    def get(self, key: str, **kw) -> str:
        return self._timed("get", key, "", self.clerk.get, key, **kw)

    def put(self, key: str, value: str, **kw):
        return self._timed("put", key, value, self.clerk.put, key, value,
                           **kw)

    def append(self, key: str, value: str, **kw):
        return self._timed("append", key, value, self.clerk.append, key,
                           value, **kw)


# ---------------------------------------------------------------- checker


@dataclasses.dataclass
class KeyResult:
    """Verdict for one key's sub-history.  ok is True (linearizable),
    False (proven non-linearizable), or None (node budget exhausted —
    verdict unknown, treated as failure by CheckResult.ok)."""

    key: str
    ok: bool | None
    nops: int
    nodes: int
    stuck_ops: list[str] = dataclasses.field(default_factory=list)

    def describe(self) -> str:
        if self.ok:
            return f"key {self.key!r}: linearizable ({self.nops} ops)"
        verdict = ("NOT linearizable" if self.ok is False
                   else "UNDECIDED (search budget exhausted)")
        lines = [f"key {self.key!r}: {verdict} "
                 f"({self.nops} ops, {self.nodes} nodes searched)"]
        if self.stuck_ops:
            lines.append("  cannot linearize past:")
            lines.extend(f"    {s}" for s in self.stuck_ops)
        return "\n".join(lines)


@dataclasses.dataclass
class CheckResult:
    results: list[KeyResult]

    @property
    def ok(self) -> bool:
        return all(r.ok is True for r in self.results)

    @property
    def violations(self) -> list[KeyResult]:
        return [r for r in self.results if r.ok is False]

    @property
    def undecided(self) -> list[KeyResult]:
        return [r for r in self.results if r.ok is None]

    def describe(self) -> str:
        if self.ok:
            n = sum(r.nops for r in self.results)
            return (f"linearizable: {n} ops over "
                    f"{len(self.results)} keys")
        return "\n".join(r.describe() for r in self.results
                         if r.ok is not True)


def check_history(history, max_nodes_per_key: int = 2_000_000
                  ) -> CheckResult:
    """Check a full mixed-key history (a History, or a list of OpRecord)
    for linearizability, per-key (P-compositionality: a KV map is
    linearizable iff every per-key register is)."""
    ops = history.ops() if isinstance(history, History) else list(history)
    per_key: dict[str, list[OpRecord]] = {}
    for r in ops:
        per_key.setdefault(r.key, []).append(r)
    results = [
        _check_key(key, recs, max_nodes_per_key)
        for key, recs in sorted(per_key.items())
    ]
    return CheckResult(results)


def _check_key(key: str, recs: list[OpRecord], max_nodes: int) -> KeyResult:
    """Wing–Gong search over one key's records.

    State is the register value (a str; a never-written key reads "" —
    the clerks' ErrNoKey surface).  The search keeps a `remaining`
    bitmask; op i is a linearization candidate ("minimal") iff no other
    remaining op returned before i was invoked.  Every COMPLETED op must
    be placed; incomplete mutations are optional; incomplete gets are
    dropped up front (their output is unknown, so they never constrain)."""
    # Drop incomplete gets; stable order for reproducible diagnostics.
    recs = [r for r in recs if not (r.ret is None and r.kind == "get")]
    recs.sort(key=lambda r: (r.call, _INF if r.ret is None else r.ret))
    n = len(recs)
    if n == 0:
        return KeyResult(key, True, 0, 0)
    call = [r.call for r in recs]
    ret = [_INF if r.ret is None else r.ret for r in recs]
    completed = 0
    for i, r in enumerate(recs):
        if r.ret is not None:
            completed |= 1 << i

    def minimal(mask: int) -> list[int]:
        # i is minimal in mask iff call[i] < min(ret[j] for j != i in mask)
        idx = [i for i in range(n) if mask >> i & 1]
        if len(idx) == 1:
            return idx
        m1 = m2 = _INF  # two smallest returns
        a1 = -1
        for i in idx:
            if ret[i] < m1:
                m1, m2, a1 = ret[i], m1, i
            elif ret[i] < m2:
                m2 = ret[i]
        return [i for i in idx
                if call[i] < (m2 if i == a1 else m1)]

    full = (1 << n) - 1
    seen: set[tuple[int, str]] = set()
    nodes = 0
    # DFS over (remaining mask, register value); stack of frames holding
    # the candidate list still to try at that node.
    stack = [(full, "", minimal(full), 0)]
    best_mask = full  # fewest-completed-remaining point, for diagnostics
    while stack:
        mask, state, cands, ci = stack.pop()
        if bin(mask & completed).count("1") < \
                bin(best_mask & completed).count("1"):
            best_mask = mask
        if mask & completed == 0:
            return KeyResult(key, True, n, nodes)
        if ci >= len(cands):
            continue
        stack.append((mask, state, cands, ci + 1))
        i = cands[ci]
        r = recs[i]
        if r.kind == "get":
            if r.output != state:
                continue
            nstate = state
        elif r.kind == "put":
            nstate = r.value
        else:  # append
            nstate = state + r.value
        nmask = mask & ~(1 << i)
        # Memo on (mask, hash(state)), not the state string itself — an
        # append-heavy search would otherwise retain one O(history-bytes)
        # concatenation per explored node (Porcupine stores state hashes
        # for the same reason; a 64-bit collision wrongly pruning a
        # viable branch is ~(nodes²/2⁶⁴) — negligible at the node budget).
        nk = (nmask, hash(nstate))
        if nk in seen:
            continue
        seen.add(nk)
        nodes += 1
        if nodes > max_nodes:
            return KeyResult(key, None, n, nodes)
        stack.append((nmask, nstate, minimal(nmask), 0))
    stuck = [recs[i].describe() for i in range(n)
             if best_mask >> i & 1 and recs[i].ret is not None][:6]
    return KeyResult(key, False, n, nodes, stuck_ops=stuck)
