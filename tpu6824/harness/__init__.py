from tpu6824.harness.cluster import Deployment, make_sockdir
from tpu6824.harness.linearize import (
    CheckResult,
    History,
    HistoryClerk,
    OpRecord,
    check_history,
)
from tpu6824.harness.nemesis import (
    DeploymentTarget,
    FabricTarget,
    FaultSchedule,
    Nemesis,
    ReplayArtifact,
    seed_from_env,
)

__all__ = [
    "CheckResult",
    "Deployment",
    "DeploymentTarget",
    "FabricTarget",
    "FaultSchedule",
    "History",
    "HistoryClerk",
    "Nemesis",
    "OpRecord",
    "ReplayArtifact",
    "check_history",
    "make_sockdir",
    "seed_from_env",
]
