from tpu6824.harness.cluster import Deployment, make_sockdir

__all__ = ["Deployment", "make_sockdir"]
