"""Deterministic nemesis — seeded, reproducible fault schedules.

Every fault suite in this repo used to hand-script its own churn thread
(`tests/test_wire_churn.py::churner`, per-test partition loops, ad-hoc
kill/revive).  The nemesis engine replaces those with ONE schedule
generator: a `FaultSchedule` is generated entirely up front from a seed —
a list of `(t, action, args)` events — so any failure reproduces from
`(seed, schedule)` alone, and a `Nemesis` thread injects the events into
a target at their offsets, recording each injection with its actual wall
timestamp.

Two targets ship:

  - `FabricTarget` — an in-process `PaxosFabric` (plus any services on
    it): partitions/heals via the link masks, per-peer unreliable
    toggles, kill/revive, clock pauses (GC + retire backlog pressure),
    live pipeline-depth churn, and arbitrary caller-provided extra
    actions (e.g. a shardkv reconfiguration trigger);
  - `DeploymentTarget` — a wire `harness.Deployment`: per-server
    unreliable accept loops, reversible deafness (socket path renamed
    aside, `rpc.Server.deafen/undeafen`), and delay-proxy interposition.

Schedule generation is a small state machine, not a memoryless sampler:
revives target currently-killed peers, kills never exceed a minority per
group (a majority can always exist once partitions heal), delay/deafen
don't stack, and a restore tail at the end of the window heals/revives/
un-delays everything so a soak always ends in a recoverable state (the
runner ALSO calls `target.restore()` on exit, belt and braces).

Replay: `TPU6824_NEMESIS_SEED` overrides a test's baked-in seed
(`seed_from_env`), and a failure artifact written by the `nemesis_report`
fixture (tests/conftest.py) carries the seed, the generated schedule, and
the as-injected timeline plus the one-command replay line.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import re
import threading
import time

from tpu6824.obs import blackbox as _blackbox
from tpu6824.obs import tracing as _tracing
from tpu6824.utils import crashsink
from tpu6824.utils.trace import dprintf

#: Relative frequency of each action in generated schedules.  Actions a
#: target does not list in its spec() are skipped; extras default to
#: EXTRA_WEIGHT unless listed here explicitly.
DEFAULT_WEIGHTS = {
    "partition_minority": 3.0,  # majority/minority split (progress holds)
    "partition_random": 2.0,    # random 3-class split (TestManyPartition)
    "partition_isolate": 1.0,   # every peer alone: NO majority until heal
    "heal": 5.0,
    "unreliable": 2.0,
    "reliable": 2.0,
    "kill": 1.5,
    "revive": 3.0,
    "clock_pause": 0.7,
    "pipeline_depth": 0.7,
    # deployment-target actions
    "deafen": 1.5,
    "undeafen": 3.0,
    "delay_on": 1.5,
    "delay_off": 3.0,
    # durafault actions (process crash/reboot + disk-fault dimension)
    "crash_process": 1.2,
    "reboot_process": 3.0,
    "disk_fault": 1.5,
    # netfault (ISSUE 12): byte-level wire faults
    "net_fault": 1.5,
    # txnkv (ISSUE 13): crash the transaction driver between
    # prepare-quorum and commit-record
    "kill_mid_commit": 1.0,
    # horizon (ISSUE 14): crash a process and leave it down long enough
    # for the group's GC horizon to pass it — revival (the ordinary
    # reboot_process / restore tail) must catch up via snapshot-install
    "lag_revive": 1.0,
    # fleetfe (ISSUE 18): the frontend TIER as a fault dimension — kill
    # a serving frontend outright, drain one gracefully (stop accepting,
    # flush parked replies, exit), revive a downed one.  The generator
    # always leaves >= 1 frontend alive so open-loop clerks can migrate.
    "fe_kill": 1.2,
    "fe_revive": 3.0,
    "fe_drain": 0.8,
}
EXTRA_WEIGHT = 1.5

#: Disk-fault kinds a `disk_fault` event may arm (utils/durafs.py), and
#: the disk dispositions a `crash_process` may carry: keep the disk,
#: reboot over a power-crashed disk (un-synced writes rolled back), or
#: lose it entirely.
DISK_FAULT_KINDS = ("torn", "fsync_lie", "enospc", "crash_rename")
CRASH_DISK_MODES = ("keep", "dirty", "lose")

#: Wire-fault kinds a `net_fault` event may arm on a netfault scope
#: (rpc/netfault.py — corrupt/truncate/split/coalesce/stall/dup_frame/
#: reset, the byte-level fault vocabulary of ISSUE 12).
NET_FAULT_KINDS = ("corrupt", "truncate", "split", "coalesce", "stall",
                   "dup_frame", "reset")

#: Disk dispositions a `kill_mid_commit` event may carry (ISSUE 13):
#: the crash fired between prepare-quorum and commit-record either
#: keeps the crashed party's disk or reboots over a power-crashed one.
MID_COMMIT_DISK_MODES = ("keep", "dirty")


def seed_from_env(default: int) -> int:
    """A test's nemesis seed, overridable for one-command replay:
    TPU6824_NEMESIS_SEED=<seed> python -m pytest <nodeid>."""
    return int(os.environ.get("TPU6824_NEMESIS_SEED", default))


@dataclasses.dataclass(frozen=True)
class NemesisEvent:
    t: float      # scheduled offset from nemesis start (seconds)
    action: str
    args: dict

    def to_dict(self) -> dict:
        return {"t": self.t, "action": self.action, "args": dict(self.args)}


class FaultSchedule:
    """An immutable, fully-materialized fault timeline.  Equality is by
    event content — two schedules generated from the same (seed, spec,
    params) compare equal, which is the determinism contract the replay
    tests assert."""

    #: Artifact schema version.  1 = the original (implicit) vocabulary;
    #: 2 adds the durafault actions (crash_process/reboot_process/
    #: disk_fault) and stamps artifacts explicitly; 3 adds the netfault
    #: action (`net_fault {scope, kind, frac}` — byte-level wire
    #: faults, ISSUE 12); 4 adds the txnkv action (`kill_mid_commit
    #: {disk}` — crash the transaction driver between prepare-quorum
    #: and commit-record, ISSUE 13); 5 adds the horizon action
    #: (`lag_revive {name, disk}` — crash a process and hold it down
    #: past the group's GC horizon so its revival must catch up via
    #: snapshot-install, ISSUE 14); 6 adds the fleetfe actions
    #: (`fe_kill/fe_revive/fe_drain {name}` — kill, revive, or
    #: gracefully drain a frontend-tier process, ISSUE 18).
    #: `from_dict` accepts unstamped v1 artifacts — old
    #: /tmp/nemesis-*.json captures keep replaying — loads stamped
    #: v2/v3/v4/v5 captures byte-exact, and never rejects a NEWER stamp
    #: (events are plain (t, action, args) rows; unknown actions fail
    #: loudly at apply time, which is the right place).
    SCHEMA = 6

    def __init__(self, events: list[NemesisEvent], seed: int | None = None,
                 params: dict | None = None, schema: int | None = None):
        self.events = list(events)
        self.seed = seed
        self.params = dict(params or {})
        self.schema = self.SCHEMA if schema is None else int(schema)

    def __iter__(self):
        return iter(self.events)

    def __len__(self):
        return len(self.events)

    def __eq__(self, other):
        return (isinstance(other, FaultSchedule)
                and self.events == other.events)

    def signature(self) -> list[tuple]:
        """Content signature (what replay must reproduce exactly)."""
        return [(round(e.t, 9), e.action, tuple(sorted(e.args.items())))
                for e in self.events]

    def to_dict(self) -> dict:
        return {"schema": self.schema, "seed": self.seed,
                "params": self.params,
                "events": [e.to_dict() for e in self.events]}

    @classmethod
    def from_dict(cls, d: dict) -> "FaultSchedule":
        return cls([NemesisEvent(e["t"], e["action"], dict(e["args"]))
                    for e in d["events"]],
                   seed=d.get("seed"), params=d.get("params"),
                   schema=d.get("schema", 1))

    @classmethod
    def from_json(cls, path: str) -> "FaultSchedule":
        """Load the exact event list from a failure artifact — byte-exact
        replay even if generation parameters have since changed."""
        with open(path) as f:
            d = json.load(f)
        return cls.from_dict(d["schedule"] if "schedule" in d else d)

    # ------------------------------------------------------- generation

    @classmethod
    def generate(cls, seed: int, duration: float, spec: dict,
                 weights: dict | None = None,
                 min_gap: float = 0.05, max_gap: float = 0.25
                 ) -> "FaultSchedule":
        """Deterministic schedule over `duration` seconds for a target
        described by `spec` (target.spec()).  Same (seed, duration, spec,
        weights, gaps) → identical schedule, always."""
        rng = random.Random(seed)
        acts = list(spec["actions"])
        w = dict(DEFAULT_WEIGHTS)
        w.update(weights or {})
        events: list[NemesisEvent] = []
        st = _GenState(spec)
        t = 0.0
        while True:
            t += rng.uniform(min_gap, max_gap)
            if t >= duration:
                break
            avail = [a for a in acts if st.applicable(a)]
            if not avail:
                continue
            wts = [w.get(a, EXTRA_WEIGHT) for a in avail]
            action = rng.choices(avail, weights=wts, k=1)[0]
            args = st.sample(action, rng)
            if args is None:
                continue
            events.append(NemesisEvent(round(t, 6), action, args))
        # Restore tail: end every schedule in a healed, fully-live state.
        t = duration
        for action, args in st.restore_tail():
            events.append(NemesisEvent(round(t, 6), action, args))
            t += 0.01
        return cls(events, seed=seed,
                   params={"duration": duration, "spec": spec,
                           "min_gap": min_gap, "max_gap": max_gap,
                           "weights": weights or {}})


class _GenState:
    """Generation-time bookkeeping so sampled events stay coherent (see
    module docstring)."""

    def __init__(self, spec: dict):
        self.spec = spec
        self.kind = spec.get("kind", "fabric")
        self.groups = list(spec.get("groups", []))
        self.P = int(spec.get("npeers", 0))
        self.names = list(spec.get("names", []))
        self.killed: dict[int, set] = {g: set() for g in self.groups}
        self.partitioned: set = set()
        self.unreliable: set = set()  # (g, p) or name
        self.deaf: set = set()
        self.delayed: set = set()
        # durafault: whole-process crash/reboot + disk-fault dimension.
        # Procs are grouped (proc_groups: name -> label, default ONE
        # shared group) so concurrent crashes stay a minority per group
        # — the same liveness bound kills obey.
        self.procs = list(spec.get("procs", []))
        self.proc_groups = dict(spec.get("proc_groups", {}))
        self.disk_modes = list(spec.get("disk_modes", CRASH_DISK_MODES))
        self.scopes = list(spec.get("scopes", []))
        self.disk_kinds = list(spec.get("disk_kinds", DISK_FAULT_KINDS))
        self.crashed: set = set()
        # netfault: byte-level wire-fault scopes (NetTarget).
        self.net_scopes = list(spec.get("net_scopes", []))
        self.net_kinds = list(spec.get("net_kinds", NET_FAULT_KINDS))
        # txnkv: mid-commit kill disk dispositions (TxnKillTarget).
        self.txn_disk_modes = list(
            spec.get("txn_disk_modes", MID_COMMIT_DISK_MODES))
        # fleetfe: serving-tier frontends (FrontendTarget).  The sampler
        # keeps >= 1 alive at all times — a storm that downs the whole
        # tier tests nothing but clerk timeouts; the migration scenario
        # needs a survivor to migrate TO.
        self.frontends = list(spec.get("frontends", []))
        self.fe_down: set = set()

    def _max_killed(self) -> int:
        return max(0, (self.P - 1) // 2)

    def _proc_group(self, name) -> str:
        return self.proc_groups.get(name, "_all")

    def _crashable(self) -> list:
        """Procs whose crash keeps every proc-group at a minority down."""
        out = []
        for n in self.procs:
            if n in self.crashed:
                continue
            grp = self._proc_group(n)
            size = sum(1 for m in self.procs if self._proc_group(m) == grp)
            down = sum(1 for m in self.crashed if self._proc_group(m) == grp)
            if down < max(0, (size - 1) // 2):
                out.append(n)
        return out

    def applicable(self, a: str) -> bool:
        if a == "revive":
            return any(self.killed.get(g) for g in self.groups)
        if a == "kill":
            return any(len(self.killed.get(g, ())) < self._max_killed()
                       for g in self.groups)
        if a == "reliable":
            return bool(self.unreliable)
        if a == "undeafen":
            return bool(self.deaf)
        if a == "delay_off":
            return bool(self.delayed)
        if a in ("deafen", "delay_on"):
            return bool(self._quiet_names())
        if a in ("crash_process", "lag_revive"):
            return bool(self._crashable())
        if a == "reboot_process":
            return bool(self.crashed)
        if a == "disk_fault":
            return bool(self.scopes)
        if a == "net_fault":
            return bool(self.net_scopes)
        if a in ("fe_kill", "fe_drain"):
            return len(self.frontends) - len(self.fe_down) >= 2
        if a == "fe_revive":
            return bool(self.fe_down)
        return True

    def _quiet_names(self):
        return [x for x in self.names
                if x not in self.deaf and x not in self.delayed]

    def sample(self, action: str, rng: random.Random) -> dict | None:
        g = rng.choice(self.groups) if self.groups else None
        P = self.P
        if action == "partition_minority":
            maj = sorted(rng.sample(range(P), P // 2 + 1))
            minr = [p for p in range(P) if p not in maj]
            self.partitioned.add(g)
            return {"g": g, "parts": [maj, minr]}
        if action == "partition_random":
            classes: list[list[int]] = [[], [], []]
            for p in range(P):
                classes[rng.randrange(3)].append(p)
            self.partitioned.add(g)
            return {"g": g, "parts": [c for c in classes if c]}
        if action == "partition_isolate":
            self.partitioned.add(g)
            return {"g": g, "parts": [[p] for p in range(P)]}
        if action == "heal":
            # Target an actually-partitioned group when one exists (as
            # revive targets killed peers): with many groups a uniform
            # pick would mostly heal healthy groups and leave a
            # partitioned one majority-less far longer than the heal
            # weight suggests.
            if self.partitioned:
                g = rng.choice(sorted(self.partitioned))
            self.partitioned.discard(g)
            return {"g": g}
        if action == "unreliable":
            if self.kind == "deployment":
                name = rng.choice(self.names)
                self.unreliable.add(name)
                return {"name": name, "flag": True}
            p = rng.randrange(P)
            self.unreliable.add((g, p))
            return {"g": g, "p": p, "flag": True}
        if action == "reliable":
            tgt = rng.choice(sorted(self.unreliable, key=repr))
            self.unreliable.discard(tgt)
            if self.kind == "deployment":
                return {"name": tgt, "flag": False}
            return {"g": tgt[0], "p": tgt[1], "flag": False}
        if action == "kill":
            cands = [gg for gg in self.groups
                     if len(self.killed[gg]) < self._max_killed()]
            if not cands:
                return None
            g = rng.choice(cands)
            p = rng.choice([p for p in range(P)
                            if p not in self.killed[g]])
            self.killed[g].add(p)
            return {"g": g, "p": p}
        if action == "revive":
            cands = [gg for gg in self.groups if self.killed[gg]]
            g = rng.choice(cands)
            p = rng.choice(sorted(self.killed[g]))
            self.killed[g].discard(p)
            return {"g": g, "p": p}
        if action == "clock_pause":
            return {"dur": round(rng.uniform(0.05, 0.2), 6)}
        if action == "pipeline_depth":
            return {"depth": rng.choice([1, 2, 3])}
        if action == "deafen":
            name = rng.choice(self._quiet_names())
            self.deaf.add(name)
            return {"name": name}
        if action == "undeafen":
            name = rng.choice(sorted(self.deaf))
            self.deaf.discard(name)
            return {"name": name}
        if action == "delay_on":
            name = rng.choice(self._quiet_names())
            self.delayed.add(name)
            return {"name": name, "delay": round(rng.uniform(0.01, 0.08), 6)}
        if action == "delay_off":
            name = rng.choice(sorted(self.delayed))
            self.delayed.discard(name)
            return {"name": name}
        if action == "crash_process":
            cands = self._crashable()
            if not cands:
                return None
            name = rng.choice(cands)
            self.crashed.add(name)
            # Disk disposition rides the event: mostly keep the disk,
            # sometimes reboot over a power-crashed one (un-synced
            # writes rolled back by durafs), rarely lose it outright.
            weights = {"keep": 3.0, "dirty": 2.0, "lose": 1.0}
            disk = rng.choices(self.disk_modes,
                               weights=[weights.get(m, 1.0)
                                        for m in self.disk_modes], k=1)[0]
            return {"name": name, "disk": disk}
        if action == "lag_revive":
            # The horizon scenario (ISSUE 14): crash a process that
            # STAYS down while traffic drives the group's GC horizon
            # past it — the target's lag hook owns "past the horizon";
            # the ordinary reboot_process / restore tail revives it,
            # which must then catch up via snapshot-install.  Disk
            # disposition spans all three modes: the catch-up path must
            # hold whether the image is intact, power-crashed, or gone.
            cands = self._crashable()
            if not cands:
                return None
            name = rng.choice(cands)
            self.crashed.add(name)
            disk = rng.choices(self.disk_modes,
                               weights=[{"keep": 3.0, "dirty": 2.0,
                                         "lose": 2.0}.get(m, 1.0)
                                        for m in self.disk_modes], k=1)[0]
            return {"name": name, "disk": disk}
        if action == "reboot_process":
            name = rng.choice(sorted(self.crashed))
            self.crashed.discard(name)
            return {"name": name}
        if action == "disk_fault":
            return {"scope": rng.choice(sorted(self.scopes)),
                    "kind": rng.choice(self.disk_kinds),
                    "frac": round(rng.random(), 6)}
        if action == "net_fault":
            return {"scope": rng.choice(sorted(self.net_scopes)),
                    "kind": rng.choice(self.net_kinds),
                    "frac": round(rng.random(), 6)}
        if action in ("fe_kill", "fe_drain"):
            alive = [n for n in self.frontends if n not in self.fe_down]
            if len(alive) < 2:
                return None
            name = rng.choice(alive)
            self.fe_down.add(name)
            return {"name": name}
        if action == "fe_revive":
            name = rng.choice(sorted(self.fe_down))
            self.fe_down.discard(name)
            return {"name": name}
        if action == "kill_mid_commit":
            # Mostly keep the disk; sometimes reboot over a
            # power-crashed one (the crash_process weighting, minus
            # `lose` — losing the coordinator group's whole disk is a
            # different scenario than a mid-commit crash).
            return {"disk": rng.choices(
                self.txn_disk_modes,
                weights=[{"keep": 3.0, "dirty": 2.0}.get(m, 1.0)
                         for m in self.txn_disk_modes], k=1)[0]}
        return {}  # extra action: no args

    def restore_tail(self) -> list[tuple[str, dict]]:
        tail: list[tuple[str, dict]] = []
        for g in sorted(self.partitioned):
            tail.append(("heal", {"g": g}))
        for g in sorted(self.killed):
            for p in sorted(self.killed[g]):
                tail.append(("revive", {"g": g, "p": p}))
        for tgt in sorted(self.unreliable, key=repr):
            if self.kind == "deployment":
                tail.append(("reliable", {"name": tgt, "flag": False}))
            else:
                tail.append(("reliable",
                             {"g": tgt[0], "p": tgt[1], "flag": False}))
        for name in sorted(self.delayed):
            tail.append(("delay_off", {"name": name}))
        for name in sorted(self.deaf):
            tail.append(("undeafen", {"name": name}))
        # Revival guarantee: every scheduled crash ends rebooted (the
        # runner's target.restore() re-reboots as belt and braces for
        # crashes injected before a stop()).
        for name in sorted(self.crashed):
            tail.append(("reboot_process", {"name": name}))
        # Frontend-tier revival guarantee: killed/drained frontends end
        # revived, so the post-soak reads always have the full tier.
        for name in sorted(self.fe_down):
            tail.append(("fe_revive", {"name": name}))
        return tail


# ------------------------------------------------------------------ targets


class FabricTarget:
    """Nemesis adapter over an in-process PaxosFabric (and the services
    riding it).  `groups` limits which fabric lanes the nemesis may touch
    (e.g. exclude a shardmaster group); `extra` maps action-name →
    zero-arg callable, sampled by the generator like any other action
    (the hook shardkv soaks use to make reconfiguration a schedule-driven
    fault dimension)."""

    ACTIONS = ["partition_minority", "partition_random", "partition_isolate",
               "heal", "unreliable", "reliable", "kill", "revive",
               "clock_pause", "pipeline_depth"]

    def __init__(self, fabric, groups=None, extra: dict | None = None,
                 actions: list[str] | None = None):
        self.fabric = fabric
        self.groups = list(range(fabric.G) if groups is None else groups)
        self.extra = dict(extra or {})
        self.actions = list(self.ACTIONS if actions is None else actions)
        self._depth0 = fabric.pipeline_depth
        self._clock0 = fabric.clock_running

    def spec(self) -> dict:
        return {"kind": "fabric", "groups": self.groups,
                "npeers": self.fabric.P,
                "actions": self.actions + sorted(self.extra)}

    def apply(self, action: str, args: dict) -> None:
        f = self.fabric
        if action in ("partition_minority", "partition_random",
                      "partition_isolate"):
            f.partition(args["g"], *args["parts"])
        elif action == "heal":
            f.heal(args["g"])
        elif action in ("unreliable", "reliable"):
            f.set_unreliable(args["flag"], g=args["g"], p=args["p"])
        elif action == "kill":
            f.kill(args["g"], args["p"])
        elif action == "revive":
            f.revive(args["g"], args["p"])
        elif action == "clock_pause":
            f.stop_clock()
            time.sleep(args["dur"])
            if self._clock0:
                f.start_clock()  # never start a clock the owner didn't run
        elif action == "pipeline_depth":
            f.set_pipeline_depth(args["depth"])
        elif action in self.extra:
            self.extra[action](**args)
        else:
            raise ValueError(f"unknown fabric nemesis action {action!r}")

    def restore(self) -> None:
        f = self.fabric
        for g in self.groups:
            for p in range(f.P):
                if f.is_dead(g, p):
                    f.revive(g, p)
            f.heal(g)
            f.set_unreliable(False, g=g)
        f.set_pipeline_depth(self._depth0)
        if self._clock0:
            f.start_clock()  # a clock_pause interrupted mid-flight


class ProcessTarget:
    """Whole-process crash/reboot as a nemesis dimension (durafault).

    `crash_fn(name, disk)` and `reboot_fn(name)` are caller-provided
    (e.g. `DisKVSystem.crash`/`.reboot`, or SIGKILL+respawn for real OS
    processes); `disk` is one of CRASH_DISK_MODES — "keep" reboots over
    the intact directory, "dirty" models a power crash first (durafs
    rolls un-synced writes back), "lose" wipes it.  The generator bounds
    concurrent crashes to a minority per proc-group and the restore tail
    reboots everything, so a soak always ends with every process
    revivable; `restore()` re-reboots runtime-tracked crashes as the
    belt-and-braces half (a stop() mid-schedule skips the tail)."""

    ACTIONS = ["crash_process", "reboot_process"]

    def __init__(self, procs: list[str], crash_fn, reboot_fn,
                 proc_groups: dict | None = None,
                 disk_modes: tuple = CRASH_DISK_MODES,
                 lag_fn=None):
        """`lag_fn(name, disk)` (optional, ISSUE 14) enables the
        `lag_revive` action: crash the process AND drive/await the
        group's GC horizon past its watermark, so the eventual
        reboot_process (or restore tail) revives it BEHIND Min() and
        the service-level snapshot-install catch-up is exercised under
        the schedule like any other fault dimension."""
        self.procs = list(procs)
        self.crash_fn = crash_fn
        self.reboot_fn = reboot_fn
        self.proc_groups = dict(proc_groups or {})
        self.disk_modes = tuple(disk_modes)
        self.lag_fn = lag_fn
        self._crashed: set = set()

    def spec(self) -> dict:
        acts = list(self.ACTIONS)
        if self.lag_fn is not None:
            acts.append("lag_revive")
        return {"kind": "process", "procs": self.procs,
                "proc_groups": self.proc_groups,
                "disk_modes": list(self.disk_modes),
                "actions": acts}

    def apply(self, action: str, args: dict) -> None:
        if action == "crash_process":
            self._crashed.add(args["name"])
            self.crash_fn(args["name"], args.get("disk", "keep"))
        elif action == "lag_revive":
            if self.lag_fn is None:
                # Replaying a schema-5 capture against a target built
                # without the lag hook: fail loudly with the actual
                # problem, not a NoneType call.
                raise ValueError(
                    "lag_revive event but this ProcessTarget has no "
                    "lag_fn — construct it with lag_fn=... to replay "
                    "horizon captures")
            self._crashed.add(args["name"])
            self.lag_fn(args["name"], args.get("disk", "keep"))
        elif action == "reboot_process":
            self.reboot_fn(args["name"])
            self._crashed.discard(args["name"])
        else:
            raise ValueError(f"unknown process nemesis action {action!r}")

    def restore(self) -> None:
        for name in sorted(self._crashed):
            try:
                self.reboot_fn(name)
            except Exception as e:  # noqa: BLE001 — restore is best-effort
                crashsink.record("nemesis-reboot", e, fatal=False)
        self._crashed.clear()


class DiskTarget:
    """Disk faults as a nemesis dimension: each `disk_fault` event arms
    ONE deterministic fault (kind + tear fraction, both carried in the
    event args) on a named `durafs.DuraDisk` scope, firing at that
    scope's next durable write.  Because arming is a pure function of
    the schedule and firing is a pure function of the write sequence,
    replaying a seed replays the disk faults byte-exactly like any other
    nemesis event."""

    ACTIONS = ["disk_fault"]

    def __init__(self, disks: dict, kinds: tuple = DISK_FAULT_KINDS):
        self.disks = dict(disks)  # scope name -> DuraDisk
        self.kinds = tuple(kinds)

    def spec(self) -> dict:
        return {"kind": "disk", "scopes": sorted(self.disks),
                "disk_kinds": list(self.kinds),
                "actions": list(self.ACTIONS)}

    def apply(self, action: str, args: dict) -> None:
        if action != "disk_fault":
            raise ValueError(f"unknown disk nemesis action {action!r}")
        self.disks[args["scope"]].arm(args["kind"],
                                      frac=args.get("frac", 0.5))

    def restore(self) -> None:
        for disk in self.disks.values():
            disk.disarm()  # armed-but-unfired faults must not leak


class NetTarget:
    """Byte-level wire faults as a nemesis dimension (netfault, ISSUE
    12): each `net_fault {scope, kind, frac}` event arms ONE
    deterministic fault on a named injector — a `netfault.WireFault`
    over a transport scope (client-side FramedConn sends and/or the
    pure-Python server's reply path), or a `NativeServer` (its C++
    reply-path hook; `netfault_arm` has the same arm shape).  Because
    arming is a pure function of the schedule and firing is a pure
    function of the scope's framed-send sequence, replaying a seed
    re-arms the identical faults — the byte-level analog of
    `DiskTarget`.

    `scopes` maps scope name → injector; an injector is anything with
    `arm(kind, frac)` + a disarm surface (`disarm()` for WireFault,
    `netfault_clear()` for NativeServer)."""

    ACTIONS = ["net_fault"]

    def __init__(self, scopes: dict, kinds: tuple = NET_FAULT_KINDS):
        self.scopes = dict(scopes)
        self.kinds = tuple(kinds)

    @staticmethod
    def _arm(inj, kind: str, frac: float) -> None:
        if hasattr(inj, "arm"):
            inj.arm(kind, frac=frac)
        else:
            inj.netfault_arm(kind, frac)

    def spec(self) -> dict:
        return {"kind": "net", "net_scopes": sorted(self.scopes),
                "net_kinds": list(self.kinds),
                "actions": list(self.ACTIONS)}

    def apply(self, action: str, args: dict) -> None:
        if action != "net_fault":
            raise ValueError(f"unknown net nemesis action {action!r}")
        self._arm(self.scopes[args["scope"]], args["kind"],
                  args.get("frac", 0.5))

    def restore(self) -> None:
        for inj in self.scopes.values():
            if hasattr(inj, "disarm"):
                inj.disarm()  # armed-but-unfired faults must not leak
            else:
                inj.netfault_clear()


class TxnKillTarget:
    """kill-mid-commit as a nemesis dimension (txnkv, ISSUE 13): each
    `kill_mid_commit {disk}` event ARMS a one-shot hook — typically
    `txnkv.MidCommitKiller.arm` — that the transaction layer fires
    between prepare-quorum and commit-record: the driving clerk dies
    with the participants' locks held and NO coordinator decision
    written, optionally crashing a coordinator-group party with the
    given disk disposition (keep | dirty).  The fate of that
    transaction then rests entirely on the participant resolvers + the
    first-writer-wins coordinator log, which is exactly what the
    composite soaks must prove survives partitions, reconfiguration,
    and wire faults.  `disarm_fn` (optional) clears an armed-but-
    unfired hook at restore so it cannot leak into the post-soak
    reads."""

    ACTIONS = ["kill_mid_commit"]

    def __init__(self, arm_fn, disarm_fn=None,
                 disk_modes: tuple = MID_COMMIT_DISK_MODES):
        self.arm_fn = arm_fn
        self.disarm_fn = disarm_fn
        self.disk_modes = tuple(disk_modes)

    def spec(self) -> dict:
        return {"kind": "txn", "txn_disk_modes": list(self.disk_modes),
                "actions": list(self.ACTIONS)}

    def apply(self, action: str, args: dict) -> None:
        if action != "kill_mid_commit":
            raise ValueError(f"unknown txn nemesis action {action!r}")
        self.arm_fn(args.get("disk", "keep"))

    def restore(self) -> None:
        if self.disarm_fn is not None:
            self.disarm_fn()


class FrontendTarget:
    """The serving tier as a nemesis dimension (fleetfe, ISSUE 18):
    `fe_kill {name}` downs a frontend process outright (its parked
    columnar waiters are abandoned, its intern refs released — clerks
    migrate their in-flight (cid, cseq) to a surviving frontend and
    dedupe through the replicated dup table), `fe_drain {name}` takes
    one down gracefully (stop accepting, flush parked replies, exit —
    `ClerkFrontend.drain`), and `fe_revive {name}` brings a downed one
    back on its old address.  The generator always leaves >= 1 frontend
    alive and the restore tail revives everything; `restore()` re-revives
    runtime-tracked downs as the belt-and-braces half, mirroring
    `ProcessTarget`.

    `kill_fn(name)` / `revive_fn(name)` / `drain_fn(name)` are
    caller-provided (in-process `ClerkFrontend.kill`/`.drain` + rebuild,
    or SIGKILL/SIGTERM + respawn for real OS processes).  `drain_fn` is
    optional — without it `fe_drain` leaves the vocabulary, the same
    shape as ProcessTarget's lag_fn gate."""

    ACTIONS = ["fe_kill", "fe_revive"]

    def __init__(self, frontends: list[str], kill_fn, revive_fn,
                 drain_fn=None):
        self.frontends = list(frontends)
        self.kill_fn = kill_fn
        self.revive_fn = revive_fn
        self.drain_fn = drain_fn
        self._down: set = set()

    def spec(self) -> dict:
        acts = list(self.ACTIONS)
        if self.drain_fn is not None:
            acts.append("fe_drain")
        return {"kind": "frontend", "frontends": self.frontends,
                "actions": acts}

    def apply(self, action: str, args: dict) -> None:
        if action == "fe_kill":
            self._down.add(args["name"])
            self.kill_fn(args["name"])
        elif action == "fe_drain":
            if self.drain_fn is None:
                # Replaying a schema-6 capture against a target built
                # without the drain hook: fail loudly with the actual
                # problem, not a NoneType call.
                raise ValueError(
                    "fe_drain event but this FrontendTarget has no "
                    "drain_fn — construct it with drain_fn=... to "
                    "replay fleetfe captures")
            self._down.add(args["name"])
            self.drain_fn(args["name"])
        elif action == "fe_revive":
            self.revive_fn(args["name"])
            self._down.discard(args["name"])
        else:
            raise ValueError(f"unknown frontend nemesis action {action!r}")

    def restore(self) -> None:
        for name in sorted(self._down):
            try:
                self.revive_fn(name)
            except Exception as e:  # noqa: BLE001 — restore is best-effort
                crashsink.record("nemesis-fe-revive", e, fatal=False)
        self._down.clear()


class CompositeTarget:
    """One schedule over several targets (e.g. FabricTarget +
    ProcessTarget + DiskTarget): specs merge — the FIRST target's kind
    wins (put the fabric/deployment target first, it shapes the
    partition/unreliable sampling) — action vocabularies must be
    disjoint, and apply() dispatches each event to the target that owns
    its action."""

    def __init__(self, *targets):
        self.targets = list(targets)
        self._owner: dict[str, object] = {}
        for t in self.targets:
            for a in t.spec()["actions"]:
                if a in self._owner:
                    raise ValueError(
                        f"action {a!r} claimed by two targets")
                self._owner[a] = t

    def spec(self) -> dict:
        merged: dict = {"actions": []}
        for t in reversed(self.targets):  # first target's keys win
            s = t.spec()
            merged.update({k: v for k, v in s.items() if k != "actions"})
        for t in self.targets:
            merged["actions"] += list(t.spec()["actions"])
        return merged

    def apply(self, action: str, args: dict) -> None:
        t = self._owner.get(action)
        if t is None:
            raise ValueError(f"unknown composite nemesis action {action!r}")
        t.apply(action, args)

    def restore(self) -> None:
        # Reverse order: disks disarm before processes reboot before the
        # fabric heals/revives (a reboot over a still-armed disk would
        # fire a stale fault into the recovery write path).
        for t in reversed(self.targets):
            t.restore()


class DeploymentTarget:
    """Nemesis adapter over a wire `harness.Deployment`: reversible
    deafness (socket path renamed aside), per-server unreliable accept
    loops, and delay-proxy interposition — the same schedule engine, over
    real sockets.  With `crash_fn`/`reboot_fn` provided, the durafault
    `crash_process`/`reboot_process` actions join the vocabulary (an
    embedded ProcessTarget tracks crash state and the restore
    guarantee)."""

    ACTIONS = ["unreliable", "reliable", "deafen", "undeafen",
               "delay_on", "delay_off"]

    def __init__(self, dep, names: list[str],
                 actions: list[str] | None = None,
                 crash_fn=None, reboot_fn=None, procs=None,
                 proc_groups: dict | None = None):
        self.dep = dep
        self.names = list(names)
        self.actions = list(self.ACTIONS if actions is None else actions)
        self._proc: ProcessTarget | None = None
        if crash_fn is not None:
            self._proc = ProcessTarget(
                list(procs if procs is not None else names),
                crash_fn, reboot_fn, proc_groups=proc_groups)

    def spec(self) -> dict:
        s = {"kind": "deployment", "names": self.names,
             "actions": list(self.actions)}
        if self._proc is not None:
            ps = self._proc.spec()
            s.update({k: v for k, v in ps.items()
                      if k not in ("kind", "actions")})
            s["actions"] += ps["actions"]
        return s

    def apply(self, action: str, args: dict) -> None:
        if self._proc is not None and action in self._proc.ACTIONS:
            self._proc.apply(action, args)
            return
        dep = self.dep
        if action in ("unreliable", "reliable"):
            dep.set_unreliable(args["name"], args["flag"])
        elif action == "deafen":
            dep.deafen(args["name"])
        elif action == "undeafen":
            dep.undeafen(args["name"])
        elif action == "delay_on":
            dep.interpose_delay(args["name"], args["delay"])
        elif action == "delay_off":
            dep.remove_delay(args["name"])
        else:
            raise ValueError(f"unknown deployment nemesis action {action!r}")

    def restore(self) -> None:
        for name in self.names:
            for fn in (lambda n=name: self.dep.remove_delay(n),
                       lambda n=name: self.dep.undeafen(n),
                       lambda n=name: self.dep.set_unreliable(n, False)):
                try:
                    fn()
                except Exception:
                    pass
        if self._proc is not None:
            self._proc.restore()


# ------------------------------------------------------------------- runner


class Nemesis:
    """Executes a FaultSchedule against a target in a daemon thread,
    recording every injection.  The recorded timeline's (t, action, args)
    sequence is a pure function of the schedule — replaying the same seed
    injects the identical fault sequence; only the `wall` stamps differ."""

    def __init__(self, target, schedule: FaultSchedule):
        self.target = target
        self.schedule = schedule
        self.timeline: list[dict] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.t0: float | None = None

    def start(self) -> "Nemesis":
        self._thread = threading.Thread(
            target=crashsink.guarded(self._run, "nemesis-runner"),
            daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        self.t0 = time.monotonic()
        try:
            for ev in self.schedule:
                while not self._stop.is_set():
                    dt = ev.t - (time.monotonic() - self.t0)
                    if dt <= 0:
                        break
                    self._stop.wait(min(dt, 0.05))
                if self._stop.is_set():
                    break
                rec = {"t": ev.t,
                       "wall": round(time.monotonic() - self.t0, 6),
                       "action": ev.action, "args": dict(ev.args)}
                dprintf("nemesis", "inject t=%+.3f %s %r", ev.t,
                        ev.action, ev.args)
                # tpuscope flight recorder (always-on): the as-injected
                # fault, timestamped on the same monotonic clock as every
                # span — the join key for "what was the system doing when
                # the violation happened".  Args go as a dict: fault args
                # like `name` must not collide with event()'s signature.
                _tracing.event(f"nemesis.{ev.action}", comp="nemesis",
                               args={"t": ev.t,
                                     **{k: repr(v)
                                        for k, v in ev.args.items()}})
                # blackbox (ISSUE 20): the injection also lands in the
                # crash-surviving ring, so a postmortem joins the
                # VICTIM's final window to the fault that killed it even
                # when the harness process itself died before writing
                # its artifact.
                _blackbox.record("nemesis", {
                    "t": ev.t, "action": ev.action,
                    "args": {k: repr(v) for k, v in ev.args.items()}})
                try:
                    self.target.apply(ev.action, ev.args)
                except Exception as e:  # noqa: BLE001 — recorded, not fatal
                    rec["error"] = repr(e)
                    dprintf("nemesis", "inject %s FAILED: %r", ev.action, e)
                self.timeline.append(rec)
        finally:
            try:
                self.target.restore()
                dprintf("nemesis", "restored target after %d injections",
                        len(self.timeline))
            except Exception as e:  # noqa: BLE001 — restore is best-effort
                crashsink.record("nemesis-restore", e, fatal=False)

    def join(self, timeout: float | None = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def stop(self) -> None:
        """Abort outstanding events (the target is still restored)."""
        self._stop.set()
        self.join()

    @property
    def done(self) -> bool:
        return self._thread is not None and not self._thread.is_alive()

    def signature(self) -> list[tuple]:
        """(t, action, args) of every INJECTED event — the replay-identity
        object (wall stamps and error strings excluded)."""
        return [(round(r["t"], 9), r["action"],
                 tuple(sorted(r["args"].items())))
                for r in self.timeline]


# ----------------------------------------------------------------- artifact


class ReplayArtifact:
    """Failure-replay capsule a nemesis test registers with the
    `nemesis_report` fixture: on test failure the fixture prints the seed
    + fault timeline and writes /tmp/nemesis-<test>.json carrying
    everything needed to re-run the identical schedule."""

    def __init__(self, test: str = ""):
        self.test = test
        self.seed: int | None = None
        self.schedule: FaultSchedule | None = None
        self.nemesis: Nemesis | None = None
        self.collector = None  # kernelscope fleet Collector (optional)
        self.extra: dict = {}

    def attach(self, nemesis: Nemesis | None = None, seed: int | None = None,
               schedule: FaultSchedule | None = None, collector=None,
               **extra) -> None:
        if nemesis is not None:
            self.nemesis = nemesis
            self.schedule = schedule or nemesis.schedule
        if schedule is not None:
            self.schedule = schedule
        if seed is not None:
            self.seed = seed
        elif self.schedule is not None and self.schedule.seed is not None:
            self.seed = self.schedule.seed
        if collector is not None:
            # kernelscope: a soak over a multi-process wire deployment
            # registers its fleet collector here, and the failure
            # artifact embeds the MERGED cross-process view (to_dict)
            # instead of only this process's flight ring.
            self.collector = collector
        self.extra.update(extra)

    @property
    def attached(self) -> bool:
        return self.schedule is not None or self.nemesis is not None

    def replay_command(self) -> str:
        seed = "<seed>" if self.seed is None else self.seed
        return (f"TPU6824_NEMESIS_SEED={seed} "
                f"python -m pytest '{self.test}'")

    def to_dict(self) -> dict:
        # Analyzer-version stamp (lazy import: the analyzer is stdlib-only
        # but keep harness import costs flat): artifacts record which
        # tpusan rule set was in force when the run was taken, so rule
        # additions across PRs stay auditable against old captures.
        from tpu6824.analysis import ANALYZER_VERSION

        d = {"test": self.test, "seed": self.seed,
             "replay": self.replay_command(), "extra": self.extra,
             "analyzer": ANALYZER_VERSION,
             # tpuscope schema stamp, next to the analyzer stamp: which
             # span/metric shapes the flight_recorder section speaks.
             "tpuscope": _tracing.SCHEMA_VERSION}
        if self.schedule is not None:
            d["schedule"] = self.schedule.to_dict()
        if self.nemesis is not None:
            d["timeline"] = list(self.nemesis.timeline)
            if self.nemesis.t0 is not None:
                # Monotonic origin of the timeline's `wall` offsets —
                # the flight recorder's `ts` (monotonic ns) joins the
                # fault timeline via ts/1e9 - t0.
                d["t0_monotonic"] = self.nemesis.t0
        # The flight recorder dump: recent spans (the violating ops' per-
        # op chains when tracing was on) + always-on events (nemesis
        # injections, fabric batch activity), joinable by timestamp and
        # trace_id — the "what was the system doing at that moment" the
        # verdict alone cannot answer.
        d["flight_recorder"] = _tracing.flight_snapshot()
        # pulse: when continuous telemetry is running in this process,
        # the artifact carries the recent time-series window too — the
        # same evidence a watchdog bundle captures for a live incident,
        # so an injected failure and a caught-in-production one read
        # identically (series timestamps join the timeline via t0).
        from tpu6824.obs import pulse as _pulse
        ps = _pulse.series_snapshot()
        if ps.get("enabled"):
            d["pulse"] = ps
        # kernelscope: when a fleet collector is attached (wire-deployment
        # soaks), the artifact carries the merged multi-process snapshot —
        # every process's metrics/stats/flight under its own namespace,
        # plus the fleet-summed device protocol counters.  Polled AT
        # FAILURE TIME; members the faults killed show up in `errors`,
        # which is itself evidence.
        if self.collector is not None:
            try:
                snap = self.collector.snapshot()
                d["kernelscope"] = {
                    "snapshot": snap,
                    "protocol": self.collector.merge_protocol(snap),
                }
            except Exception as e:  # noqa: BLE001 — never cost the artifact
                d["kernelscope"] = {"error": repr(e)[:200]}
        return d

    def write(self, outdir: str = "/tmp") -> str:
        base = re.sub(r"[^A-Za-z0-9_.-]+", "_",
                      self.test.split("::")[-1] or "nemesis")
        path = os.path.join(outdir, f"nemesis-{base}.json")
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=1, default=str)
        return path

    def describe(self) -> str:
        lines = [f"nemesis seed: {self.seed}",
                 f"replay: {self.replay_command()}"]
        timeline = (self.nemesis.timeline if self.nemesis is not None
                    else [e.to_dict() for e in (self.schedule or [])])
        lines.append(f"fault timeline ({len(timeline)} events):")
        for r in timeline:
            err = f"  ERROR {r['error']}" if r.get("error") else ""
            lines.append(f"  t={r['t']:+8.3f}s {r['action']} "
                         f"{r['args']}{err}")
        return "\n".join(lines)
