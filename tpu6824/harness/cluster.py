"""Cluster harness — place service objects behind real Unix sockets.

This is the equivalent of the reference suites' fixture layer: the `port()`
naming scheme (`/var/tmp/824-<uid>/<svc>-<pid>-<tag>-<i>`,
`paxos/test_test.go:21-30`), per-server accept loops, and the filesystem
surgery hooks.  A `Deployment` owns one rpc.Server per service object and
hands out `Proxy` handles; because clerks and servers reach peers through
`net.call(obj, obj.method, ...)` and catch RPCError, a Proxy drops in
anywhere an in-process server object is expected — same service code runs
in-process or over the wire.
"""

from __future__ import annotations

import os
import shutil
import uuid

from tpu6824.rpc import DelayProxy, Proxy, Server, connect


def make_sockdir(tag: str = "") -> str:
    """Short, unique socket dir (AF_UNIX caps sun_path at ~108 bytes)."""
    d = os.path.join(
        f"/var/tmp/tpu824-{os.getuid()}",
        (tag + "-" if tag else "") + uuid.uuid4().hex[:8],
    )
    os.makedirs(d, exist_ok=True)
    return d


class Deployment:
    """A set of named services behind sockets, with harness fault hooks."""

    def __init__(self, tag: str = "", timeout: float = 10.0):
        self.dir = make_sockdir(tag)
        self.timeout = timeout
        self._servers: dict[str, Server] = {}
        self._objs: dict[str, object] = {}
        self._proxies: dict[str, DelayProxy] = {}

    def addr(self, name: str) -> str:
        return os.path.join(self.dir, name)

    def serve(self, name: str, obj, methods: list[str] | None = None,
              seed: int | None = None, native: bool = True) -> Proxy:
        """Expose `obj` at a socket; returns a Proxy to it.  Uses the C++
        epoll event loop (rpc/native_server.py) when the toolchain allows —
        pass native=False to force the Python accept loop."""
        from tpu6824.rpc.native_server import make_server

        srv = make_server(self.addr(name), seed=seed, prefer_native=native)
        srv.register_obj(obj, methods)
        srv.start()  # register-before-expose
        self._servers[name] = srv
        self._objs[name] = obj
        return self.proxy(name)

    def proxy(self, name: str) -> Proxy:
        return connect(self.addr(name), timeout=self.timeout)

    def obj(self, name: str):
        return self._objs[name]

    def server(self, name: str) -> Server:
        return self._servers[name]

    # ------------------------------------------------------- fault hooks

    def set_unreliable(self, name: str, flag: bool) -> None:
        self._servers[name].set_unreliable(flag)

    def deafen(self, name: str) -> None:
        self._servers[name].deafen()

    def undeafen(self, name: str) -> None:
        """Restore a deafened service's public socket path (rpc.Server
        renamed it aside) — deafness is a reversible, schedulable fault."""
        self._servers[name].undeafen()

    def kill(self, name: str) -> None:
        """Socket teardown + object kill() if it has one."""
        srv = self._servers.pop(name, None)
        if srv:
            srv.kill()
        obj = self._objs.pop(name, None)
        if obj is not None and hasattr(obj, "kill"):
            obj.kill()

    def rpc_count(self, name: str) -> int:
        return self._servers[name].rpc_count

    def interpose_delay(self, name: str, delay: float = 0.0) -> DelayProxy:
        """Swap a DelayProxy in front of a live service, transparently to
        dialers: the real socket is RENAMED aside (a bound Unix socket
        stays connectable through its renamed path — the socket-rename
        trick, `pbservice/test_test.go:897-954`) and the proxy binds the
        public path itself.  rename, unlike the alias approach this
        replaced, works on filesystems that refuse hard links to sockets —
        where `link_alias`'s symlink fallback would have re-resolved the
        proxy's backend path to the re-pointed public path, i.e. the proxy
        dialing itself in an infinite accept→dial loop."""
        if name in self._proxies:
            raise RuntimeError(f"{name} already has a delay proxy")
        public = self.addr(name)
        hidden = public + ".real"
        os.rename(public, hidden)  # server now dialable at hidden only
        proxy = DelayProxy(public, hidden, delay).start()
        self._proxies[name] = proxy
        return proxy

    def remove_delay(self, name: str) -> None:
        """Undo interpose_delay: the public path is the server's again."""
        proxy = self._proxies.pop(name, None)
        if proxy is None:
            raise RuntimeError(f"{name} has no delay proxy")
        public = self.addr(name)
        hidden = public + ".real"
        proxy.kill()  # unlinks the public path it bound
        os.rename(hidden, public)

    def shutdown(self) -> None:
        for proxy in self._proxies.values():
            proxy.kill()
        self._proxies.clear()
        for name in list(self._servers):
            self.kill(name)
        shutil.rmtree(self.dir, ignore_errors=True)

    def __enter__(self) -> "Deployment":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
