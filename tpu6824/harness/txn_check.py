"""Wing–Gong checker for TRANSACTIONAL histories (ISSUE 13).

The per-key checker (`harness/linearize.py`) rests on
P-compositionality: a KV map is linearizable iff every per-key register
is, so histories partition by key and each sub-history is searched
alone.  A cross-group transaction breaks that decomposition on purpose
— one operation reads and writes SEVERAL keys atomically — so the
compositional unit generalizes from single keys to read/write sets:
transactions whose key sets never (transitively) overlap are
independent, and the history partitions into CONNECTED COMPONENTS of
the key-sharing graph instead of single keys.  Within a component the
search is Wing & Gong's again, over multi-key states:

  - a total order of the COMMITTED transactions must exist that
    (a) respects real time — a transaction takes effect somewhere
    between its call and its return — and (b) is legal: every read
    sub-op observes exactly the value the preceding writes produced
    (a never-written key reads "");
  - an ABORTED transaction must have NO effect: it is excluded from
    the search entirely, so a value only an aborted transaction wrote
    can never legally be observed — a dirty read surfaces as
    non-serializability;
  - a transaction of UNKNOWN fate (clerk died mid-commit; the
    coordinator record decides it eventually, but this history never
    observed which way) may take effect anywhere after its call or
    not at all — its reads constrain nothing (never returned), its
    writes are optional;
  - a HALF-APPLIED transaction — some groups committed, others did
    not — is exactly a state no total order of atomic transactions
    can produce, which is what makes this checker the atomicity
    yardstick for the 2PC layer.

Plain KV ops interleave freely: `kv_record` adapts a
`linearize.OpRecord` (get/put/append) into a single-op transaction, so
mixed workloads (transfers + ordinary clerk traffic) check under ONE
verdict.

Memoized states (Porcupine-style): a (remaining-mask, state-hash) pair
that already failed is never re-explored; state is the component's
key→value map, hashed canonically.
"""

from __future__ import annotations

import dataclasses

_INF = float("inf")

STATUSES = ("committed", "aborted", "unknown")


@dataclasses.dataclass(frozen=True)
class TxnRecord:
    """One transaction's invocation/response pair.

    `ops` is the flattened sub-op tuple, entries ("r", key, observed) /
    ("w", key, value) / ("a", key, appended): reads are checked against
    the state BEFORE the transaction's writes apply (so a CAS
    contributes an "r" with its expectation and a "w" with its new
    value), then writes/appends apply in order.  `ret` is None when no
    response was observed; `status` is 'committed' | 'aborted' |
    'unknown' (unknown ⇒ ret is None)."""

    client: object
    ops: tuple
    call: float
    ret: float | None
    status: str = "committed"

    def keys(self) -> frozenset:
        return frozenset(k for _, k, _v in self.ops)

    def describe(self) -> str:
        body = ", ".join(f"{o}({k!r})={v!r}" for o, k, v in self.ops)
        end = "?" if self.ret is None else f"{self.ret:.6f}"
        return (f"[{self.call:.6f},{end}] client {self.client} "
                f"{self.status}: {body}")


def kv_record(rec) -> TxnRecord:
    """Adapt a linearize.OpRecord (get/put/append) into a single-op
    transaction so plain clerk traffic and transactions check under one
    verdict.  An incomplete get is dropped by the caller exactly as
    linearize drops it (it constrains nothing); an incomplete mutation
    becomes an unknown-fate transaction."""
    if rec.kind == "get":
        ops = (("r", rec.key, rec.output if rec.output is not None
                else ""),)
    elif rec.kind == "put":
        ops = (("w", rec.key, rec.value),)
    else:
        ops = (("a", rec.key, rec.value),)
    status = "committed" if rec.ret is not None else "unknown"
    return TxnRecord(client=rec.client, ops=ops, call=rec.call,
                     ret=rec.ret, status=status)


@dataclasses.dataclass
class ComponentResult:
    """Verdict for one key-connected component (cf.
    linearize.KeyResult).  ok: True / False / None (budget)."""

    keys: tuple
    ok: bool | None
    ntxns: int
    nodes: int
    stuck: list = dataclasses.field(default_factory=list)

    def describe(self) -> str:
        label = ",".join(map(str, self.keys[:4])) + (
            ",…" if len(self.keys) > 4 else "")
        if self.ok:
            return f"component [{label}]: serializable ({self.ntxns} txns)"
        verdict = ("NOT atomically serializable" if self.ok is False
                   else "UNDECIDED (search budget exhausted)")
        lines = [f"component [{label}]: {verdict} "
                 f"({self.ntxns} txns, {self.nodes} nodes searched)"]
        if self.stuck:
            lines.append("  cannot serialize past:")
            lines.extend(f"    {s}" for s in self.stuck)
        return "\n".join(lines)


@dataclasses.dataclass
class TxnCheckResult:
    results: list

    @property
    def ok(self) -> bool:
        return all(r.ok is True for r in self.results)

    @property
    def violations(self) -> list:
        return [r for r in self.results if r.ok is False]

    @property
    def undecided(self) -> list:
        return [r for r in self.results if r.ok is None]

    def describe(self) -> str:
        if self.ok:
            n = sum(r.ntxns for r in self.results)
            return (f"atomically serializable: {n} txns over "
                    f"{len(self.results)} components")
        return "\n".join(r.describe() for r in self.results
                         if r.ok is not True)


def check_txn_history(history, max_nodes_per_component: int = 2_000_000
                      ) -> TxnCheckResult:
    """Check a transactional history — a TxnHistory
    (services.txnkv.TxnHistory), or an iterable of TxnRecord — for
    strict serializability with atomic effects."""
    recs = (history.records() if hasattr(history, "records")
            else list(history))
    # Aborted transactions must have no effect — excluded up front; the
    # probe for their effects is every OTHER record's reads.
    recs = [r for r in recs if r.status != "aborted"]
    # Union-find over keys → connected components (the generalized
    # P-compositionality unit).
    parent: dict = {}

    def find(k):
        r = k
        while parent.get(r, r) != r:
            r = parent[r]
        while parent.get(k, k) != k:
            parent[k], k = r, parent[k]
        return r

    for rec in recs:
        ks = sorted(rec.keys())
        for k in ks:
            parent.setdefault(k, k)
        for a, b in zip(ks, ks[1:]):
            ra, rb = find(a), find(b)
            if ra != rb:
                parent[ra] = rb
    comps: dict = {}
    for rec in recs:
        ks = rec.keys()
        if not ks:
            continue
        comps.setdefault(find(next(iter(sorted(ks)))), []).append(rec)
    results = [
        _check_component(comp, max_nodes_per_component)
        for _, comp in sorted(comps.items())
    ]
    return TxnCheckResult(results)


def _apply(state: dict, rec: TxnRecord) -> dict | None:
    """rec against `state`: None if a read mismatches (illegal here),
    else the post-state.  Unknown-fate reads never constrain (they were
    never observed)."""
    check_reads = rec.status == "committed"
    for o, k, v in rec.ops:
        if o == "r" and check_reads and state.get(k, "") != v:
            return None
    ns = None
    for o, k, v in rec.ops:
        if o == "r":
            continue
        if ns is None:
            ns = dict(state)
        if o == "w":
            ns[k] = v
        else:  # append
            ns[k] = ns.get(k, "") + v
    return state if ns is None else ns


def _check_component(recs: list, max_nodes: int) -> ComponentResult:
    keys = tuple(sorted({k for r in recs for k in r.keys()}))
    # Unknown-fate READ-ONLY transactions constrain nothing: drop.
    recs = [r for r in recs
            if not (r.status == "unknown"
                    and all(o == "r" for o, _k, _v in r.ops))]
    recs.sort(key=lambda r: (r.call, _INF if r.ret is None else r.ret))
    n = len(recs)
    if n == 0:
        return ComponentResult(keys, True, 0, 0)
    if n > 62:
        # Mask-width guard: a component this entangled exceeds the
        # search's practical budget anyway — report UNDECIDED loudly
        # rather than degrade into a silent non-verdict.
        return ComponentResult(keys, None, n, 0,
                               stuck=["component too wide for search"])
    call = [r.call for r in recs]
    ret = [_INF if r.ret is None else r.ret for r in recs]
    committed = 0
    for i, r in enumerate(recs):
        if r.status == "committed":
            committed |= 1 << i

    def minimal(mask: int) -> list[int]:
        idx = [i for i in range(n) if mask >> i & 1]
        if len(idx) == 1:
            return idx
        m1 = m2 = _INF
        a1 = -1
        for i in idx:
            if ret[i] < m1:
                m1, m2, a1 = ret[i], m1, i
            elif ret[i] < m2:
                m2 = ret[i]
        return [i for i in idx if call[i] < (m2 if i == a1 else m1)]

    full = (1 << n) - 1
    seen: set = set()
    nodes = 0
    # Each frame: (mask, state, candidate list, cursor).  A candidate
    # entry is (i, apply?) — unknown-fate transactions branch twice:
    # take effect here, or never (drop from mask, state unchanged).
    def cands_for(mask):
        out = []
        for i in minimal(mask):
            out.append((i, True))
            if recs[i].status == "unknown":
                out.append((i, False))
        return out

    stack = [(full, {}, cands_for(full), 0)]
    best_mask = full
    while stack:
        mask, state, cands, ci = stack.pop()
        if bin(mask & committed).count("1") < \
                bin(best_mask & committed).count("1"):
            best_mask = mask
        if mask & committed == 0:
            return ComponentResult(keys, True, n, nodes)
        if ci >= len(cands):
            continue
        stack.append((mask, state, cands, ci + 1))
        i, take = cands[ci]
        nstate = _apply(state, recs[i]) if take else state
        if nstate is None:
            continue  # reads illegal at this point in the order
        nmask = mask & ~(1 << i)
        nk = (nmask, hash(tuple(sorted(nstate.items()))))
        if nk in seen:
            continue
        seen.add(nk)
        nodes += 1
        if nodes > max_nodes:
            return ComponentResult(keys, None, n, nodes)
        stack.append((nmask, nstate, cands_for(nmask), 0))
    stuck = [recs[i].describe() for i in range(n)
             if best_mask >> i & 1 and recs[i].status == "committed"][:6]
    return ComponentResult(keys, False, n, nodes, stuck=stuck)
