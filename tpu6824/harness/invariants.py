"""Shared invariant checkers (the reference's test helpers), packaged so
the pytest suites, bench, and the driver entry points use ONE definition.

`check_appends` — every concurrent client's appends appear in the final
value exactly once and in per-client order; the linearizability yardstick
every KV suite shares (`kvpaxos/test_test.go:342-362`,
`pbservice/test_test.go:424-444`, reused by the diskv suite).  Markers are
`"x {client} {op} y"` — the spaces make multi-digit indices unambiguous
under substring search.
"""


def check_appends(final: str, nclients: int, nops: int,
                  exact_length: bool = False) -> None:
    for i in range(nclients):
        last = -1
        for j in range(nops):
            marker = f"x {i} {j} y"
            pos = final.find(marker)
            assert pos >= 0, f"missing {marker!r} in {final!r}"
            assert final.find(marker, pos + 1) < 0, f"dup {marker!r}"
            assert pos > last, f"out of order: {marker!r}"
            last = pos
    if exact_length:
        want = sum(len(f"x {i} {j} y")
                   for i in range(nclients) for j in range(nops))
        assert len(final) == want, (len(final), want)
