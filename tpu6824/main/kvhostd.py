"""kvhostd — one decentralized kvpaxos replica as an OS process.

The reference's deployment model made executable (cf. `main/diskvd.go`:
a daemon per replica wired by argv): this process embeds its own Paxos
peer (gob endpoint at `{sockdir}/px-{me}`), runs the KV RSM over
per-message wire consensus with its `nservers-1` sibling processes, and
serves Go-wire clerks (`KVPaxos.Get`/`KVPaxos.PutAppend`) at
`{sockdir}/clerk-{me}`.

    python -m tpu6824.main.kvhostd --dir /var/tmp/kv --n 3 --me 0
"""

from __future__ import annotations

import argparse
import signal
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", required=True, help="socket directory")
    ap.add_argument("--n", type=int, default=3, help="replica count")
    ap.add_argument("--me", type=int, required=True, help="replica index")
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--lifetime", type=float, default=600.0,
                    help="suicide timer, like diskvd's (main/diskvd.go:30-74)")
    ap.add_argument("--pooled", action="store_true",
                    help="long-lived net/rpc client connections to peers "
                         "(optimized profile; per-connection fault "
                         "injection then fires only at dial time)")
    ap.add_argument("--persist", default=None, metavar="DIR",
                    help="durable consensus state: survive crash+restart")
    args = ap.parse_args(argv)

    from tpu6824.services.kvpaxos import make_host_replica
    from tpu6824.shim import endpoints

    peer, server = make_host_replica(args.dir, args.n, args.me,
                                     seed=args.seed,
                                     persist_dir=args.persist,
                                     peer_kw={"pooled": args.pooled})
    ep = endpoints.serve_kvpaxos(server, f"{args.dir}/clerk-{args.me}")

    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    print(f"kvhostd ready me={args.me} clerk={ep.addr}", flush=True)
    deadline = time.time() + args.lifetime
    while not stop and time.time() < deadline:
        time.sleep(0.2)
    ep.kill()
    server.kill()
    return 0


if __name__ == "__main__":
    sys.exit(main())
