"""lockd — lock server daemon (the reference's `main/lockd.go`).

Primary mode forwards to the backup:

    python -m tpu6824.main.lockd --addr .../lp --primary --backup-addr .../lb
    python -m tpu6824.main.lockd --addr .../lb
"""

from __future__ import annotations

import argparse
import time


def main(argv=None):
    ap = argparse.ArgumentParser(prog="lockd")
    ap.add_argument("--addr", required=True)
    ap.add_argument("--primary", action="store_true")
    ap.add_argument("--backup-addr", default="",
                    help="backup's socket (primary mode only)")
    ap.add_argument("--ttl", type=float, default=600.0)
    args = ap.parse_args(argv)

    from tpu6824.rpc import connect
    from tpu6824.rpc.native_server import make_server
    from tpu6824.services.lockservice import LockServer

    backup = connect(args.backup_addr) if args.backup_addr else None
    ls = LockServer(am_primary=args.primary, backup=backup)
    srv = make_server(args.addr).register_obj(ls).start()
    role = "primary" if args.primary else "backup"
    print(f"lockd: {role} at {args.addr}", flush=True)
    try:
        time.sleep(args.ttl)
    finally:
        ls.kill()
        srv.kill()


if __name__ == "__main__":
    main()
