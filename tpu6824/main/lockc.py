"""lockc — lock client CLI (the reference's `main/lockc.go`).

    python -m tpu6824.main.lockc --primary .../lp --backup .../lb lock name
    python -m tpu6824.main.lockc --primary .../lp --backup .../lb unlock name
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(prog="lockc")
    ap.add_argument("--primary", required=True)
    ap.add_argument("--backup", required=True)
    ap.add_argument("op", choices=["lock", "unlock"])
    ap.add_argument("name")
    args = ap.parse_args(argv)

    from tpu6824.rpc import connect
    from tpu6824.services.lockservice import Clerk

    ck = Clerk(connect(args.primary), connect(args.backup))
    ok = ck.lock(args.name) if args.op == "lock" else ck.unlock(args.name)
    print("true" if ok else "false")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
