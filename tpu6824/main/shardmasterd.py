"""shardmasterd — one shardmaster replica as a daemon.

    python -m tpu6824.main.shardmasterd --addr /var/tmp/.../sm0 \
        --fabric /var/tmp/.../fabric --g 0 --me 0 [--ttl 600]
"""

from __future__ import annotations

import argparse
import time


def main(argv=None):
    ap = argparse.ArgumentParser(prog="shardmasterd")
    ap.add_argument("--addr", required=True)
    ap.add_argument("--fabric", required=True)
    ap.add_argument("--g", type=int, default=0, help="fabric group lane")
    ap.add_argument("--me", type=int, required=True)
    ap.add_argument("--ttl", type=float, default=600.0)
    args = ap.parse_args(argv)

    from tpu6824.core.fabric_service import remote_fabric
    from tpu6824.rpc.native_server import make_server
    from tpu6824.services.shardmaster import ShardMasterServer

    sm = ShardMasterServer(remote_fabric(args.fabric), args.g, args.me)
    srv = make_server(args.addr).register_obj(sm).start()
    print(f"shardmasterd: replica {args.me} at {args.addr}", flush=True)
    try:
        time.sleep(args.ttl)
    finally:
        sm.kill()
        srv.kill()


if __name__ == "__main__":
    main()
