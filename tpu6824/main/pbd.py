"""pbd — primary/backup KV server daemon (the reference's `main/pbd.go`).

    python -m tpu6824.main.pbd --addr /var/tmp/.../pb1 --name pb1 \
        --vs /var/tmp/.../vs --peer pb2=/var/tmp/.../pb2 [--ttl 600]
"""

from __future__ import annotations

import argparse
import time


def main(argv=None):
    ap = argparse.ArgumentParser(prog="pbd")
    ap.add_argument("--addr", required=True)
    ap.add_argument("--name", required=True,
                    help="this server's identity in the view (directory key)")
    ap.add_argument("--vs", required=True, help="viewservice addr")
    ap.add_argument("--peer", action="append", default=[],
                    help="name=addr of a peer pb server (repeat)")
    ap.add_argument("--ttl", type=float, default=600.0)
    args = ap.parse_args(argv)

    from tpu6824.rpc import connect
    from tpu6824.rpc.native_server import make_server
    from tpu6824.services.common import FlakyNet
    from tpu6824.services.pbservice import PBServer

    directory = {}
    for spec in args.peer:
        name, _, addr = spec.partition("=")
        directory[name] = connect(addr)
    pb = PBServer(args.name, connect(args.vs), FlakyNet(), directory)
    srv = make_server(args.addr).register_obj(pb).start()
    print(f"pbd: {args.name} at {args.addr}", flush=True)
    try:
        time.sleep(args.ttl)
    finally:
        pb.kill()
        srv.kill()


if __name__ == "__main__":
    main()
