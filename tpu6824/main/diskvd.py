"""diskvd — one persistent shardkv (diskv) replica as a daemon.

The process-granular deployment the reference tests demand for Lab 5: the
harness compiles and `os.StartProcess`es a real daemon per replica so a kill
is a REAL crash and a removed directory is REAL disk loss
(`diskv/test_test.go:62-233`, `main/diskvd.go:30-74`).

    python -m tpu6824.main.diskvd --addr .../g500-0 --fabric .../fabric \
        --fg 1 --gid 500 --me 0 --sm .../sm0 --sm .../sm1 \
        --peer g500-1=.../g500-1 --peer g500-2=.../g500-2 \
        --dir /data/g500-0 [--restart] [--ttl 600]
"""

from __future__ import annotations

import argparse
import time


def main(argv=None):
    ap = argparse.ArgumentParser(prog="diskvd")
    ap.add_argument("--addr", required=True)
    ap.add_argument("--fabric", required=True)
    ap.add_argument("--fg", type=int, required=True, help="fabric group lane")
    ap.add_argument("--gid", type=int, required=True)
    ap.add_argument("--me", type=int, required=True)
    ap.add_argument("--sm", action="append", required=True,
                    help="shardmaster replica addr (repeat)")
    ap.add_argument("--peer", action="append", default=[],
                    help="name=addr of a peer replica (repeat)")
    ap.add_argument("--dir", required=True)
    ap.add_argument("--restart", action="store_true")
    ap.add_argument("--ttl", type=float, default=600.0)
    args = ap.parse_args(argv)

    from tpu6824.core.fabric_service import remote_fabric
    from tpu6824.rpc import connect
    from tpu6824.rpc.native_server import make_server
    from tpu6824.services.diskv import DisKVServer

    directory = {}
    for spec in args.peer:
        name, _, addr = spec.partition("=")
        directory[name] = connect(addr)
    sm_proxies = [connect(a) for a in args.sm]

    kv = DisKVServer(
        remote_fabric(args.fabric), args.fg, args.gid, args.me,
        sm_proxies, directory, dir=args.dir, restart=args.restart,
    )
    srv = make_server(args.addr).register_obj(kv).start()
    print(f"diskvd: g{args.gid}-{args.me} at {args.addr} "
          f"(dir={args.dir}, restart={args.restart})", flush=True)
    try:
        time.sleep(args.ttl)
    finally:
        kv.dead = True
        srv.kill()


if __name__ == "__main__":
    main()
