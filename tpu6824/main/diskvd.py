"""diskvd — one persistent shardkv (diskv) replica as a daemon.

The process-granular deployment the reference tests demand for Lab 5: the
harness compiles and `os.StartProcess`es a real daemon per replica so a kill
is a REAL crash and a removed directory is REAL disk loss
(`diskv/test_test.go:62-233`, `main/diskvd.go:30-74`).

Two consensus modes:

  - `--fabric ADDR`: the replica dials a fabricd process that owns the
    device arrays (the batched-runtime deployment).  A SIGKILL destroys
    the RSM + disk but the acceptor state lives on in fabricd.
  - `--px-sockdir DIR --px-n N`: the replica embeds its OWN durable
    consensus peer — an in-process `HostPaxosPeer` with
    `persist_dir=<dir>/paxos` — speaking per-message gob RPC to its peer
    replicas' endpoints (`DIR/px-<i>`).  This is the reference's Lab 5
    crash model EXACTLY (`diskv/test_test.go:103-117`): process death
    destroys BOTH the KV state and the acceptor state; the disk restores
    both on `--restart`, and directory removal is a total loss the
    replica must recover from via re-run rounds / peer snapshot.

    python -m tpu6824.main.diskvd --addr .../g500-0 --fabric .../fabric \
        --fg 1 --gid 500 --me 0 --sm .../sm0 --sm .../sm1 \
        --peer g500-1=.../g500-1 --peer g500-2=.../g500-2 \
        --dir /data/g500-0 [--restart] [--ttl 600]
"""

from __future__ import annotations

import argparse
import os
import time


def main(argv=None):
    ap = argparse.ArgumentParser(prog="diskvd")
    ap.add_argument("--addr", required=True)
    ap.add_argument("--fabric", help="fabricd socket (fabric mode)")
    ap.add_argument("--px-sockdir",
                    help="host-paxos mode: dir of per-replica consensus "
                         "endpoints px-<i>; the peer persists under "
                         "<dir>/paxos")
    ap.add_argument("--px-n", type=int, default=3,
                    help="host-paxos mode: replica-group size")
    ap.add_argument("--fg", type=int, required=True, help="fabric group lane")
    ap.add_argument("--gid", type=int, required=True)
    ap.add_argument("--me", type=int, required=True)
    ap.add_argument("--sm", action="append", required=True,
                    help="shardmaster replica addr (repeat)")
    ap.add_argument("--peer", action="append", default=[],
                    help="name=addr of a peer replica (repeat)")
    ap.add_argument("--dir", required=True)
    ap.add_argument("--restart", action="store_true")
    ap.add_argument("--ttl", type=float, default=600.0)
    args = ap.parse_args(argv)
    if bool(args.fabric) == bool(args.px_sockdir):
        ap.error("exactly one of --fabric / --px-sockdir is required")

    from tpu6824.rpc import connect
    from tpu6824.rpc.native_server import make_server
    from tpu6824.services.diskv import DisKVServer

    directory = {}
    for spec in args.peer:
        name, _, addr = spec.partition("=")
        directory[name] = connect(addr)
    sm_proxies = [connect(a) for a in args.sm]

    peer = None
    if args.px_sockdir:
        from tpu6824.core.hostpeer import FLOOR_ALL
        from tpu6824.services.host_backend import make_host_replica
        from tpu6824.services.shardkv import (
            SKVOP_NAME, SKVOP_WIRE, HostOpPeer,
        )

        paxos_dir = os.path.join(args.dir, "paxos")
        # Amnesiac restart (--restart over a missing/empty paxos ledger):
        # the consensus endpoint must come up granting NOTHING — there
        # must be no window between its accept loop starting and the
        # rejoin protocol installing the real participation floor
        # (DisKVServer._lower_amnesia_floor lowers it).
        amnesiac = args.restart and not (
            os.path.isdir(paxos_dir) and os.listdir(paxos_dir))
        peer_kw = {"participation_floor": FLOOR_ALL} if amnesiac else {}
        peer, kv = make_host_replica(
            args.px_sockdir, "px", SKVOP_NAME, SKVOP_WIRE,
            lambda p: DisKVServer(
                None, args.fg, args.gid, p.me, sm_proxies, directory,
                dir=args.dir, restart=args.restart, px=HostOpPeer(p)),
            args.px_n, args.me,
            persist_dir=paxos_dir, **peer_kw,
        )
    else:
        from tpu6824.core.fabric_service import remote_fabric

        kv = DisKVServer(
            remote_fabric(args.fabric), args.fg, args.gid, args.me,
            sm_proxies, directory, dir=args.dir, restart=args.restart,
        )
    srv = make_server(args.addr).register_obj(kv).start()
    print(f"diskvd: g{args.gid}-{args.me} at {args.addr} "
          f"(dir={args.dir}, restart={args.restart}, "
          f"consensus={'host-px' if peer is not None else 'fabric'})",
          flush=True)
    try:
        time.sleep(args.ttl)
    finally:
        kv.dead = True
        if peer is not None:
            peer.kill()
        srv.kill()


if __name__ == "__main__":
    main()
