"""pbc — primary/backup KV client CLI (the reference's `main/pbc.go`).

    python -m tpu6824.main.pbc --vs .../vs --peer pb1=.../pb1 --peer pb2=.../pb2 \
        get k
    ... put k v   |   ... append k v
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(prog="pbc")
    ap.add_argument("--vs", required=True)
    ap.add_argument("--peer", action="append", default=[],
                    help="name=addr of a pb server (repeat)")
    ap.add_argument("--timeout", type=float, default=10.0)
    ap.add_argument("op", choices=["get", "put", "append"])
    ap.add_argument("key")
    ap.add_argument("value", nargs="?", default="")
    args = ap.parse_args(argv)

    from tpu6824.rpc import connect
    from tpu6824.services.pbservice import Clerk

    directory = {}
    for spec in args.peer:
        name, _, addr = spec.partition("=")
        directory[name] = connect(addr)
    ck = Clerk(connect(args.vs), directory)
    if args.op == "get":
        print(ck.get(args.key, timeout=args.timeout))
    elif args.op == "put":
        ck.put(args.key, args.value, timeout=args.timeout)
    else:
        ck.append(args.key, args.value, timeout=args.timeout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
