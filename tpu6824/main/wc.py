"""wc — word-count MapReduce application (the reference's `main/wc.go`).

Words are maximal runs of letters; counts are merged across map tasks and the
final output is key-sorted.  `--top N` prints the N most frequent words in
`word: count` form — the shape `main/test-wc.sh` checks against its golden
top-10 (`main/mr-testout.txt`); the corpus itself (`main/kjv12.txt`) is not
shipped in the reference fork either.

    python -m tpu6824.main.wc sequential <file> [--nmap 4] [--nreduce 3]
    python -m tpu6824.main.wc master <file> [--workers 3] [--top 10]
"""

from __future__ import annotations

import argparse
import sys


def run(mode: str, text: str, nmap: int, nreduce: int, nworkers: int):
    from tpu6824.services.mapreduce import (
        run_distributed,
        run_sequential,
        wc_map,
        wc_reduce,
    )

    if mode == "sequential":
        return run_sequential(text, nmap, nreduce, wc_map, wc_reduce)
    return run_distributed(text, nmap, nreduce, wc_map, wc_reduce,
                           nworkers=nworkers)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="wc")
    ap.add_argument("mode", choices=["sequential", "master"])
    ap.add_argument("file")
    ap.add_argument("--nmap", type=int, default=4)
    ap.add_argument("--nreduce", type=int, default=3)
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--top", type=int, default=0,
                    help="print only the N most frequent words")
    args = ap.parse_args(argv)

    with open(args.file, encoding="utf-8") as f:
        text = f.read()
    counts = run(args.mode, text, args.nmap, args.nreduce, args.workers)
    if args.top:
        # test-wc.sh shape: sort by count (stable on key), take the top N.
        top = sorted(counts, key=lambda kv: (int(kv[1]), kv[0]))[-args.top:]
        for k, v in top:
            print(f"{k}: {v}")
    else:
        for k, v in counts:
            print(f"{k} {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
