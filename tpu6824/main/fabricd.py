"""fabricd — run the device-owning fabric runtime as a daemon.

The TPU-native analog of the reference's per-process Paxos listeners
(`paxos/paxos.go:488-557`): one process owns the (G, I, P) consensus arrays
and the step clock; replica daemons (shardmasterd, diskvd) dial in.

    python -m tpu6824.main.fabricd --addr /var/tmp/.../fabric \
        --groups 3 --peers 3 --instances 32 [--ttl 600]

`--ttl` is the suicide timer the reference's diskvd daemon carries so
orphaned test processes die on their own (`main/diskvd.go:64-74`).
"""

from __future__ import annotations

import argparse
import signal
import sys
import time


def main(argv=None):
    ap = argparse.ArgumentParser(prog="fabricd")
    ap.add_argument("--addr", required=True)
    ap.add_argument("--groups", type=int, default=1)
    ap.add_argument("--peers", type=int, default=3)
    ap.add_argument("--instances", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ttl", type=float, default=600.0)
    ap.add_argument("--restore", default=None, metavar="CKPT",
                    help="resume from a fabric checkpoint file, or from "
                         "the newest VALID snapshot in a checkpoint "
                         "directory (torn snapshots are discarded)")
    ap.add_argument("--checkpoint", default=None, metavar="CKPT",
                    help="write a checkpoint here on shutdown (and every "
                         "--checkpoint-interval seconds)")
    ap.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                    help="continuous checkpointing (durafault): a daemon "
                         "snapshots into DIR/ckpt-<seq>.bin every "
                         "--checkpoint-interval seconds (default 0.5), "
                         "pruning old snapshots; one final snapshot on "
                         "shutdown")
    ap.add_argument("--checkpoint-interval", type=float, default=0.0)
    ap.add_argument("--checkpoint-keep", type=int, default=3)
    ap.add_argument("--pulse", type=float, default=0.0, metavar="SECS",
                    help="sample continuous time-series telemetry every "
                         "SECS seconds (obs/pulse.py; served as the "
                         "`pulse` RPC, rendered by python -m "
                         "tpu6824.obs.top); 0 = off")
    ap.add_argument("--watchdog-dir", default=None, metavar="DIR",
                    help="run the anomaly watchdog over the pulse "
                         "series (requires --pulse); evidence bundles "
                         "for stalls/collapses/spikes land in DIR in "
                         "the nemesis-artifact format")
    ap.add_argument("--blackbox-dir", default=None, metavar="DIR",
                    help="flight-data recorder (obs/blackbox.py): "
                         "append crash-surviving telemetry to a ring "
                         "file DIR/fabricd-<pid>.bbx; reconstruct with "
                         "python -m tpu6824.obs.postmortem DIR")
    args = ap.parse_args(argv)
    if args.watchdog_dir and not args.pulse:
        ap.error("--watchdog-dir requires --pulse")
    if args.checkpoint_interval and not (args.checkpoint
                                         or args.checkpoint_dir):
        ap.error("--checkpoint-interval requires --checkpoint or "
                 "--checkpoint-dir")
    if args.checkpoint and args.checkpoint_dir:
        ap.error("--checkpoint and --checkpoint-dir are exclusive")
    if args.restore:
        clash = [k for k in ("groups", "peers", "instances", "seed")
                 if getattr(args, k) != ap.get_default(k)]
        if clash:
            ap.error(f"--restore takes its dimensions from the checkpoint; "
                     f"conflicting flags: {', '.join('--' + c for c in clash)}")

    import os

    from tpu6824.core.checkpointd import ContinuousCheckpointer, recover_newest
    from tpu6824.core.fabric import PaxosFabric
    from tpu6824.core.fabric_service import serve_fabric

    if args.restore and os.path.isdir(args.restore):
        fabric, report = recover_newest(args.restore, auto_step=True)
        print(f"fabricd: recovered from {report['restored_from']} "
              f"({len(report['discarded'])} discarded)", flush=True)
    elif args.restore:
        fabric = PaxosFabric.restore(args.restore, auto_step=True)
    else:
        fabric = PaxosFabric(
            ngroups=args.groups, npeers=args.peers,
            ninstances=args.instances, seed=args.seed, auto_step=True,
        )
    srv = serve_fabric(fabric, args.addr)
    if args.blackbox_dir:
        from tpu6824.obs import blackbox as _blackbox

        _blackbox.enable(args.blackbox_dir, name=f"fabricd-{os.getpid()}")
    if args.pulse:
        pulse = fabric.start_pulse(interval=args.pulse)
        if args.watchdog_dir:
            from tpu6824.obs.watchdog import Watchdog

            os.makedirs(args.watchdog_dir, exist_ok=True)
            Watchdog(pulse, outdir=args.watchdog_dir).start()
    ckptd = None
    if args.checkpoint_dir:
        ckptd = ContinuousCheckpointer(
            fabric, args.checkpoint_dir,
            interval=args.checkpoint_interval or 0.5,
            keep=args.checkpoint_keep).start()
    print(f"fabricd: serving (G={fabric.G}, I={fabric.I}, "
          f"P={fabric.P}) at {args.addr}", flush=True)

    def _ckpt():
        # checkpoint() requires a stopped clock (torn-state guard).
        fabric.stop_clock()
        try:
            fabric.checkpoint(args.checkpoint)
        except OSError as e:
            # Transient (disk full, perms): keep serving, retry next
            # interval rather than taking down every dialed-in daemon.
            print(f"fabricd: checkpoint failed: {e}", flush=True)
        finally:
            fabric.start_clock()

    # SIGTERM → SystemExit so the finally block runs (final checkpoint);
    # the reference daemons just die, but a checkpointing daemon must not.
    signal.signal(signal.SIGTERM, lambda s, f: sys.exit(0))

    try:
        deadline = time.monotonic() + args.ttl
        while time.monotonic() < deadline:
            nap = min(args.checkpoint_interval or args.ttl,
                      deadline - time.monotonic())
            time.sleep(max(0.0, nap))
            if args.checkpoint and args.checkpoint_interval:
                _ckpt()
    finally:
        # A second SIGTERM must not abort the final checkpoint mid-write.
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        srv.kill()
        if ckptd is not None:
            ckptd.stop(final=True)  # snapshots anything after the last tick
        fabric.stop_clock()
        if args.checkpoint:
            fabric.checkpoint(args.checkpoint)


if __name__ == "__main__":
    main()
