"""fabricd — run the device-owning fabric runtime as a daemon.

The TPU-native analog of the reference's per-process Paxos listeners
(`paxos/paxos.go:488-557`): one process owns the (G, I, P) consensus arrays
and the step clock; replica daemons (shardmasterd, diskvd) dial in.

    python -m tpu6824.main.fabricd --addr /var/tmp/.../fabric \
        --groups 3 --peers 3 --instances 32 [--ttl 600]

`--ttl` is the suicide timer the reference's diskvd daemon carries so
orphaned test processes die on their own (`main/diskvd.go:64-74`).
"""

from __future__ import annotations

import argparse
import time


def main(argv=None):
    ap = argparse.ArgumentParser(prog="fabricd")
    ap.add_argument("--addr", required=True)
    ap.add_argument("--groups", type=int, default=1)
    ap.add_argument("--peers", type=int, default=3)
    ap.add_argument("--instances", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ttl", type=float, default=600.0)
    args = ap.parse_args(argv)

    from tpu6824.core.fabric import PaxosFabric
    from tpu6824.core.fabric_service import serve_fabric

    fabric = PaxosFabric(
        ngroups=args.groups, npeers=args.peers, ninstances=args.instances,
        seed=args.seed, auto_step=True,
    )
    srv = serve_fabric(fabric, args.addr)
    print(f"fabricd: serving (G={args.groups}, I={args.instances}, "
          f"P={args.peers}) at {args.addr}", flush=True)
    try:
        time.sleep(args.ttl)
    finally:
        srv.kill()
        fabric.stop_clock()


if __name__ == "__main__":
    main()
