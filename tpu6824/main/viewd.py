"""viewd — viewservice daemon (the reference's `main/viewd.go`).

    python -m tpu6824.main.viewd --addr /var/tmp/.../vs [--ttl 600]
"""

from __future__ import annotations

import argparse
import time


def main(argv=None):
    ap = argparse.ArgumentParser(prog="viewd")
    ap.add_argument("--addr", required=True)
    ap.add_argument("--ttl", type=float, default=600.0)
    args = ap.parse_args(argv)

    from tpu6824.rpc.native_server import make_server
    from tpu6824.services.viewservice import ViewServer

    vs = ViewServer()
    srv = make_server(args.addr).register_obj(vs).start()
    print(f"viewd: serving at {args.addr}", flush=True)
    try:
        time.sleep(args.ttl)
    finally:
        vs.kill()
        srv.kill()


if __name__ == "__main__":
    main()
