"""toy_rpc — a minimal RPC library in ~100 lines, for pedagogy.

The capability mirror of the reference's `main/toy-rpc.go:12-160`: a client
multiplexes concurrent calls over ONE duplex byte stream by tagging each
request with a transaction id (xid) and matching replies back to the waiting
caller; the server handles requests concurrently so replies can return out of
order.  Demonstrates the core idea under every `call()` in the framework.

Run the demo:  python -m tpu6824.main.toy_rpc
"""

from __future__ import annotations

import itertools
import pickle
import socket
import struct
import threading

from tpu6824.utils import crashsink

_LEN = struct.Struct(">I")


def _send(sock, obj):
    data = pickle.dumps(obj)
    # tpusan: ok(lock-blocking-reachable) — _wlock exists precisely to
    # serialize whole-frame socket writes; the blocking send IS the
    # operation the lock guards, not work smuggled under it.
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv(sock):
    hdr = b""
    while len(hdr) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(hdr))
        if not chunk:
            raise EOFError
        hdr += chunk
    (n,) = _LEN.unpack(hdr)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise EOFError
        buf += chunk
    return pickle.loads(buf)


class ToyServer:
    """Serves one connection; each request handled in its own thread so a
    slow call does not block later ones (toy-rpc.go's per-request goroutine)."""

    def __init__(self, sock, handlers: dict):
        self.sock = sock
        self.handlers = handlers
        self._wlock = threading.Lock()
        threading.Thread(target=crashsink.guarded(self._loop, "toyrpc-loop"),
                         daemon=True).start()

    def _loop(self):
        try:
            while True:
                xid, name, args = _recv(self.sock)
                threading.Thread(
                    target=crashsink.guarded(self._handle, "toyrpc-handler"),
                    args=(xid, name, args), daemon=True
                ).start()
        except (EOFError, OSError):
            pass

    def _handle(self, xid, name, args):
        try:
            result = (True, self.handlers[name](*args))
        except Exception as e:
            result = (False, str(e))
        with self._wlock:
            try:
                _send(self.sock, (xid, result))
            except OSError:
                pass


class ToyClient:
    """xid-matching client: concurrent call() from many threads over one
    stream; a reader thread routes each reply to its waiting caller."""

    def __init__(self, sock):
        self.sock = sock
        self._wlock = threading.Lock()
        self._xids = itertools.count(1)
        self._pending: dict[int, list] = {}
        self._mu = threading.Lock()
        threading.Thread(target=crashsink.guarded(self._reader, "toyrpc-reader"),
                         daemon=True).start()

    def _reader(self):
        try:
            while True:
                xid, result = _recv(self.sock)
                with self._mu:
                    slot = self._pending.get(xid)
                if slot is not None:
                    slot[1] = result
                    slot[0].set()
        except (EOFError, OSError):
            pass

    def call(self, name, *args, timeout=10.0):
        xid = next(self._xids)
        slot = [threading.Event(), None]
        with self._mu:
            self._pending[xid] = slot
        with self._wlock:
            _send(self.sock, (xid, name, args))
        if not slot[0].wait(timeout):
            raise TimeoutError(f"toy rpc {name} timed out")
        with self._mu:
            del self._pending[xid]
        ok, payload = slot[1]
        if not ok:
            raise RuntimeError(payload)
        return payload


def demo():
    import time

    a, b = socket.socketpair()
    ToyServer(b, {
        "add": lambda x, y: x + y,
        "slow_echo": lambda s: (time.sleep(0.2), s)[1],
    })
    cli = ToyClient(a)

    results = {}
    # Out-of-order completion: the slow call is issued first, finishes last.
    t = threading.Thread(target=lambda: results.update(slow=cli.call("slow_echo", "tortoise")))
    t.start()
    results["fast"] = cli.call("add", 2, 3)
    t.join()
    print(f"add(2,3) = {results['fast']}  (returned before slow_echo)")
    print(f"slow_echo = {results['slow']!r}")
    assert results == {"fast": 5, "slow": "tortoise"}
    print("toy_rpc demo OK")


if __name__ == "__main__":
    demo()
