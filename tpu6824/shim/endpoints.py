"""Per-service gob/net-rpc endpoints — SURVEY §7 layer 5.

Each `serve_*` wraps one of our running service objects in a `GobRpcServer`
on a Unix socket, registered under the exact method names the reference's Go
clerks dial ("KVPaxos.Get", "ShardMaster.Join", "ViewServer.Ping", ... —
grep of client.go call sites), translating between the Go wire structs
(`shim/wire.py`) and our Python service surfaces.

Semantics preserved in translation:

  - **At-most-once ids.**  Go clerks stamp ops with a random `OpID int64`
    (kvpaxos/common.go:26, pbservice/common.go:26) or a `(CID string, Seq
    int)` pair (shardkv/common.go:23-24).  Our services key duplicate
    filters on `(cid, cseq)`; an OpID maps to `(OpID, 0)` — same uniqueness,
    same replay behavior on retries.
  - **Errors in-band.**  Go services report `Err` inside replies, not as RPC
    failures; adapters catch our RPCError only where the reference's server
    would itself have answered in-band (ErrNotReady on TransferState).
    A dead/timed-out server surfaces as a transport failure — which is what
    the Go clerk's `call()` sees from a dead reference server too.
  - **Config translation.**  Our `Config` (gid tuples, UNASSIGNED=0) maps
    onto Go's `{Num, Shards [10]int64, Groups map[int64][]string}` with
    identical gid numbering (shardmaster/common.go:37-41).

The Paxos peer protocol ("Paxos.Prepare"/"Accept"/"Decided", paxos/rpc.go)
is served over gob by `core/hostpeer.py::HostPaxosPeer` — the decentralized
backend, which registers exactly those method names on its own socket.  On
the fabric backend the same traffic instead rides the device plane as
masked tensor exchanges (SURVEY §2.3), so no endpoint here wraps it; the
schemas live in wire.py and are shared by both.
"""

from __future__ import annotations

from tpu6824.services.common import fresh_cid
from tpu6824.shim import wire
from tpu6824.shim.netrpc import GobRpcServer
from tpu6824.utils.errors import OK, ErrNotReady, RPCError


# ------------------------------------------------------------- kvpaxos


def serve_kvpaxos(server, addr: str, seed: int | None = None) -> GobRpcServer:
    """kvpaxos clerk surface (kvpaxos/client.go:75,98)."""
    s = GobRpcServer(addr, seed=seed, registry=wire.default_registry())

    def get(a):
        err, value = server.get(a["Key"], a["OpID"], 0)
        return {"Err": err, "Value": value}

    def put_append(a):
        kind = a["Op"].lower()  # Go "Put"/"Append" → ours "put"/"append"
        err, _ = server.put_append(kind, a["Key"], a["Value"], a["OpID"], 0)
        return {"Err": err}

    s.register_method("KVPaxos.Get", get, wire.KV_GET_ARGS, wire.KV_GET_REPLY)
    s.register_method("KVPaxos.PutAppend", put_append,
                      wire.KV_PUTAPPEND_ARGS, wire.KV_PUTAPPEND_REPLY)
    return s.start()


# --------------------------------------------------------- viewservice


def serve_viewservice(server, addr: str, seed: int | None = None) -> GobRpcServer:
    """viewservice surface (viewservice/client.go:64,75)."""
    s = GobRpcServer(addr, seed=seed)

    def _view_dict(v):
        return {"Viewnum": v.viewnum, "Primary": v.primary, "Backup": v.backup}

    def ping(a):
        v = server.ping(a["Me"], a["Viewnum"])
        return {"View": _view_dict(v)}

    def get(_a):
        return {"View": _view_dict(server.get())}

    s.register_method("ViewServer.Ping", ping, wire.PING_ARGS, wire.PING_REPLY)
    s.register_method("ViewServer.Get", get, wire.VS_GET_ARGS, wire.VS_GET_REPLY)
    return s.start()


# ----------------------------------------------------------- pbservice


def serve_pbservice(server, addr: str, seed: int | None = None) -> GobRpcServer:
    """pbservice clerk surface (pbservice/client.go:104,128).  The
    replica-internal RPCs (BackupGet/BackupPutAppend/InitState) stay on the
    framework's own replica channel — a Go CLIENT never dials them."""
    s = GobRpcServer(addr, seed=seed)

    def get(a):
        err, value = server.get(a["Key"], a["OpID"], 0)
        return {"Err": err, "Value": value}

    def put_append(a):
        kind = a["Method"].lower()
        err, _ = server.put_append(a["Key"], kind, a["Value"], a["OpID"], 0)
        return {"Err": err}

    s.register_method("PBServer.Get", get, wire.PB_GET_ARGS, wire.PB_GET_REPLY)
    s.register_method("PBServer.PutAppend", put_append,
                      wire.PB_PUTAPPEND_ARGS, wire.PB_PUTAPPEND_REPLY)
    return s.start()


# --------------------------------------------------------- lockservice


def serve_lockservice(server, addr: str, seed: int | None = None) -> GobRpcServer:
    """lockservice clerk surface (lockservice/client.go:73 + the Unlock the
    reference left stubbed).  Go's LockArgs carries no client id — each RPC
    is a fresh op, so a fresh cid preserves the reference behavior."""
    s = GobRpcServer(addr, seed=seed)

    def lock(a):
        return {"OK": bool(server.lock(a["Lockname"], fresh_cid(), 0))}

    def unlock(a):
        return {"OK": bool(server.unlock(a["Lockname"], fresh_cid(), 0))}

    s.register_method("LockServer.Lock", lock, wire.LOCK_ARGS, wire.LOCK_REPLY)
    s.register_method("LockServer.Unlock", unlock,
                      wire.UNLOCK_ARGS, wire.UNLOCK_REPLY)
    return s.start()


# --------------------------------------------------------- shardmaster


def config_to_wire(cfg) -> dict:
    """Our Config → Go shardmaster.Config (shardmaster/common.go:37-41)."""
    return {
        "Num": cfg.num,
        "Shards": list(cfg.shards),  # UNASSIGNED == 0 == Go's invalid gid
        "Groups": {gid: list(srvs) for gid, srvs in cfg.groups},
    }


def serve_shardmaster(server, addr: str, seed: int | None = None) -> GobRpcServer:
    """shardmaster clerk surface (shardmaster/client.go:63-113).  Go args
    carry no dedup ids (each RPC is a fresh op in the reference too), so
    adapters stamp a fresh cid per call."""
    s = GobRpcServer(addr, seed=seed)

    def join(a):
        server.join(a["GID"], tuple(a["Servers"]), fresh_cid(), 0)
        return {}

    def leave(a):
        server.leave(a["GID"], fresh_cid(), 0)
        return {}

    def move(a):
        server.move(a["Shard"], a["GID"], fresh_cid(), 0)
        return {}

    def query(a):
        cfg = server.query(a["Num"], fresh_cid(), 0)
        return {"Config": config_to_wire(cfg)}

    s.register_method("ShardMaster.Join", join, wire.SM_JOIN_ARGS,
                      wire.SM_JOIN_REPLY)
    s.register_method("ShardMaster.Leave", leave, wire.SM_LEAVE_ARGS,
                      wire.SM_LEAVE_REPLY)
    s.register_method("ShardMaster.Move", move, wire.SM_MOVE_ARGS,
                      wire.SM_MOVE_REPLY)
    s.register_method("ShardMaster.Query", query, wire.SM_QUERY_ARGS,
                      wire.SM_QUERY_REPLY)
    return s.start()


# ------------------------------------------------------------- shardkv


def serve_shardkv(server, addr: str, seed: int | None = None) -> GobRpcServer:
    """shardkv surface (shardkv/client.go:109,148 + the cross-group
    TransferState, server.go:331).  CID is a string on this wire
    (shardkv/common.go:23); our dup filter keys on it unchanged."""
    s = GobRpcServer(addr, seed=seed)

    def get(a):
        err, value = server.get(a["Key"], a["CID"], a["Seq"])
        return {"Err": err, "Value": value}

    def put_append(a):
        kind = a["Op"].lower()
        err, _ = server.put_append(a["Key"], kind, a["Value"], a["CID"],
                                   a["Seq"])
        return {"Err": err}

    def transfer_state(a):
        empty = {"KVStore": {}, "MRRSMap": {}, "Replies": {}}
        try:
            xs = server.transfer_state(a["ConfigNum"], (a["Shard"],))
        except RPCError as e:
            # The donor answers ErrNotReady in-band (shardkv/server.go:344).
            if ErrNotReady in str(e):
                return {"Err": ErrNotReady, "XState": empty}
            raise
        replies, mrrs = {}, {}
        for cid, (cseq, reply) in xs.dup:
            err, value = reply if isinstance(reply, tuple) else (OK, "")
            mrrs[str(cid)] = cseq
            replies[str(cid)] = {"Err": err, "Value": value or ""}
        return {"Err": OK, "XState": {
            "KVStore": dict(xs.kv), "MRRSMap": mrrs, "Replies": replies,
        }}

    s.register_method("ShardKV.Get", get, wire.SKV_GET_ARGS,
                      wire.SKV_GET_REPLY)
    s.register_method("ShardKV.PutAppend", put_append,
                      wire.SKV_PUTAPPEND_ARGS, wire.SKV_PUTAPPEND_REPLY)
    s.register_method("ShardKV.TransferState", transfer_state,
                      wire.SKV_TRANSFER_ARGS, wire.SKV_TRANSFER_REPLY)
    return s.start()


# --------------------------------------------------------------- diskv


def serve_diskv(server, addr: str, seed: int | None = None) -> GobRpcServer:
    """diskv clerk surface (diskv/client.go:104,143) — same shapes as
    shardkv's clerk wire."""
    s = GobRpcServer(addr, seed=seed)

    def get(a):
        err, value = server.get(a["Key"], a["CID"], a["Seq"])
        return {"Err": err, "Value": value}

    def put_append(a):
        kind = a["Op"].lower()
        err, _ = server.put_append(a["Key"], kind, a["Value"], a["CID"],
                                   a["Seq"])
        return {"Err": err}

    s.register_method("DisKV.Get", get, wire.DKV_GET_ARGS, wire.DKV_GET_REPLY)
    s.register_method("DisKV.PutAppend", put_append,
                      wire.DKV_PUTAPPEND_ARGS, wire.DKV_PUTAPPEND_REPLY)
    return s.start()
