"""Go `net/rpc` connection protocol over Unix sockets, speaking gob.

This is the exact wire conversation Go's `rpc.Dial("unix", srv)` +
`c.Call(name, args, reply)` has with an `rpc.Server` — the transport under
every `call()` in the reference (`paxos/rpc.go:24-42` and its clones).  One
connection carries, per call:

  client → server:  Request{ServiceMethod string; Seq uint64}, then args
  server → client:  Response{ServiceMethod string; Seq uint64; Error string},
                    then the reply value (an empty struct when Error is set,
                    net/rpc's `invalidRequest`)

Each direction is its own gob stream (type definitions sent once per
direction per connection).  Dial-per-call clients send one request with
Seq 1 (Go's net/rpc client numbers from 1), but the server loop supports
pipelined sequential calls the way net/rpc does.

The server reuses the L0 accept-loop fault-injection semantics
(`tpu6824/rpc/transport.py`, mirroring `paxos/paxos.go:524-552`): unreliable
mode drops 10% of connections unprocessed and discards 20% of replies after
executing the call (SHUT_WR — executed-but-unacked), and the socket path
tricks (deafen / link_alias) apply unchanged since identity is still a
filesystem pathname.
"""

from __future__ import annotations

import socket

from tpu6824.rpc import transport
from tpu6824.shim import gob
from tpu6824.utils.errors import RPCError

# net/rpc's header structs (rpc/server.go: Request, Seq is uint64).
REQUEST = gob.Struct("Request", [
    ("ServiceMethod", gob.STRING),
    ("Seq", gob.UINT),
])
RESPONSE = gob.Struct("Response", [
    ("ServiceMethod", gob.STRING),
    ("Seq", gob.UINT),
    ("Error", gob.STRING),
])
# net/rpc's `invalidRequest = struct{}{}` reply body on error.
INVALID = gob.Struct("InvalidRequest", [])


def _sock_read(conn: socket.socket):
    def read(n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise EOFError("connection closed")
            buf += chunk
        return bytes(buf)

    return read


class GobRpcServer(transport.Server):
    """A `transport.Server` whose connections speak Go net/rpc + gob instead
    of the framework's native pickle framing.  Handlers are registered under
    Go method names ("KVPaxos.Get") with their gob schemas; a handler takes
    the zero-completed args dict and returns the reply dict (or raises — the
    error text travels in Response.Error, as net/rpc does)."""

    def __init__(self, addr: str, seed: int | None = None,
                 registry: gob.Registry | None = None):
        super().__init__(addr, seed=seed)
        self.registry = registry or gob.Registry()
        self._methods: dict[str, tuple] = {}

    def register_method(self, name: str, fn,
                        args_schema: gob.Struct,
                        reply_schema: gob.Struct) -> "GobRpcServer":
        self._methods[name] = (fn, args_schema, reply_schema)
        return self

    # transport.Server's accept loop calls this per connection.
    def _serve_conn(self, conn: socket.socket, discard_reply: bool) -> None:
        try:
            conn.settimeout(30.0)
            dec = gob.Decoder(_sock_read(conn))
            enc = gob.Encoder(conn.sendall, self.registry)
            while True:
                try:
                    _, req = dec.next()
                except (EOFError, OSError):
                    return
                req = gob.complete(REQUEST, req)
                method = req["ServiceMethod"]
                entry = self._methods.get(method)
                if entry is None:
                    dec.next()  # consume and discard the args body
                    self._respond(enc, method, req["Seq"],
                                  f"rpc: can't find method {method}",
                                  INVALID, {}, conn, discard_reply)
                    if discard_reply:
                        return  # one deaf reply per unreliable connection
                    continue
                fn, args_schema, reply_schema = entry
                _, args = dec.next()
                args = gob.complete(args_schema, args)
                try:
                    reply = fn(args)
                    err = ""
                except Exception as e:  # app error → Response.Error
                    reply, reply_schema, err = {}, INVALID, str(e) or repr(e)
                self._respond(enc, method, req["Seq"], err,
                              reply_schema, reply, conn, discard_reply)
                if discard_reply:
                    return  # one deaf reply per unreliable connection
        except (gob.GobError, RPCError, OSError, EOFError, RecursionError):
            pass
        finally:
            conn.close()

    @staticmethod
    def _respond(enc, method, seq, err, reply_schema, reply, conn,
                 discard_reply) -> None:
        if discard_reply:
            # Executed, but the client sees a dead connection — the SHUT_WR
            # trick (paxos/paxos.go:535-538).
            conn.shutdown(socket.SHUT_WR)
            return
        enc.encode(RESPONSE, {"ServiceMethod": method, "Seq": seq,
                              "Error": err})
        enc.encode(reply_schema, reply)


def gob_call(addr: str, method: str, args_schema: gob.Struct, args: dict,
             reply_schema: gob.Struct | None = None,
             registry: gob.Registry | None = None,
             timeout: float = 10.0) -> dict:
    """One dial-per-call net/rpc invocation — the client half of the
    reference's `call()` (`paxos/rpc.go:24-42`), with the same contract:
    raises RPCError when the server can't be reached or the reply is lost
    (the op may still have executed); a Response.Error becomes an RPCError
    too, matching `call()` returning false on `c.Call` error."""
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(timeout)
    try:
        try:
            sock.connect(addr)
            enc = gob.Encoder(sock.sendall, registry)
            enc.encode(REQUEST, {"ServiceMethod": method, "Seq": 1})
            enc.encode(args_schema, args or {})
            dec = gob.Decoder(_sock_read(sock))
            _, resp = dec.next()
            resp = gob.complete(RESPONSE, resp)
            _, reply = dec.next()
        except (OSError, EOFError, gob.GobError, RecursionError) as e:
            raise RPCError(f"gob call {method}@{addr}: {e}") from e
        if resp["Error"]:
            raise RPCError(f"{method}@{addr}: {resp['Error']}")
        return gob.complete(reply_schema, reply) if reply_schema else reply
    finally:
        sock.close()
