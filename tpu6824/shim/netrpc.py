"""Go `net/rpc` connection protocol over Unix sockets, speaking gob.

This is the exact wire conversation Go's `rpc.Dial("unix", srv)` +
`c.Call(name, args, reply)` has with an `rpc.Server` — the transport under
every `call()` in the reference (`paxos/rpc.go:24-42` and its clones).  One
connection carries, per call:

  client → server:  Request{ServiceMethod string; Seq uint64}, then args
  server → client:  Response{ServiceMethod string; Seq uint64; Error string},
                    then the reply value (an empty struct when Error is set,
                    net/rpc's `invalidRequest`)

Each direction is its own gob stream (type definitions sent once per
direction per connection).  Dial-per-call clients send one request with
Seq 1 (Go's net/rpc client numbers from 1), but the server loop supports
pipelined sequential calls the way net/rpc does.

The server reuses the L0 accept-loop fault-injection semantics
(`tpu6824/rpc/transport.py`, mirroring `paxos/paxos.go:524-552`): unreliable
mode drops 10% of connections unprocessed and discards 20% of replies after
executing the call (SHUT_WR — executed-but-unacked), and the socket path
tricks (deafen / link_alias) apply unchanged since identity is still a
filesystem pathname.
"""

from __future__ import annotations

import socket

from tpu6824.rpc import transport
from tpu6824.shim import gob
from tpu6824.utils.errors import RPCError

# net/rpc's header structs (rpc/server.go: Request, Seq is uint64).
REQUEST = gob.Struct("Request", [
    ("ServiceMethod", gob.STRING),
    ("Seq", gob.UINT),
])
RESPONSE = gob.Struct("Response", [
    ("ServiceMethod", gob.STRING),
    ("Seq", gob.UINT),
    ("Error", gob.STRING),
])
# net/rpc's `invalidRequest = struct{}{}` reply body on error.
INVALID = gob.Struct("InvalidRequest", [])


def _sock_read(conn: socket.socket):
    def read(n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise EOFError("connection closed")
            buf += chunk
        return bytes(buf)

    return read


class GobRpcServer(transport.Server):
    """A `transport.Server` whose connections speak Go net/rpc + gob instead
    of the framework's native pickle framing.  Handlers are registered under
    Go method names ("KVPaxos.Get") with their gob schemas; a handler takes
    the zero-completed args dict and returns the reply dict (or raises — the
    error text travels in Response.Error, as net/rpc does)."""

    def __init__(self, addr: str, seed: int | None = None,
                 registry: gob.Registry | None = None):
        super().__init__(addr, seed=seed)
        self.registry = registry or gob.Registry()
        self._methods: dict[str, tuple] = {}

    def register_method(self, name: str, fn,
                        args_schema: gob.Struct,
                        reply_schema: gob.Struct) -> "GobRpcServer":
        self._methods[name] = (fn, args_schema, reply_schema)
        return self

    # transport.Server's accept loop calls this per connection; the fault
    # coins are drawn per REQUEST (the accept-loop semantics at request
    # granularity, matching transport.Server since pooled connections
    # became the default), and every injected fault tears the connection
    # down so pooled and dial-per-call clients pay the same redial.
    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(30.0)
            dec = gob.Decoder(_sock_read(conn))
            enc = gob.Encoder(conn.sendall, self.registry)
            while not self._dead.is_set():
                try:
                    _, req = dec.next()
                except (EOFError, OSError):
                    return
                req = gob.complete(REQUEST, req)
                with self._lock:
                    self.rpc_count += 1
                    unrel = self._unreliable
                    r1 = self._rng.random()
                    r2 = self._rng.random()
                drop_req = unrel and r1 < transport.REQ_DROP
                discard_reply = unrel and r2 < transport.REP_DROP
                method = req["ServiceMethod"]
                entry = self._methods.get(method)
                if entry is None:
                    dec.next()  # consume and discard the args body
                    if drop_req:
                        return  # discarded unprocessed (op NOT executed)
                    self._respond(enc, method, req["Seq"],
                                  f"rpc: can't find method {method}",
                                  INVALID, {}, conn, discard_reply)
                    if discard_reply:
                        return  # deaf reply tears the connection down
                    continue
                fn, args_schema, reply_schema = entry
                _, args = dec.next()
                args = gob.complete(args_schema, args)
                if drop_req:
                    return  # discarded unprocessed (op NOT executed)
                try:
                    reply = fn(args)
                    err = ""
                except Exception as e:  # app error → Response.Error
                    reply, reply_schema, err = {}, INVALID, str(e) or repr(e)
                self._respond(enc, method, req["Seq"], err,
                              reply_schema, reply, conn, discard_reply)
                if discard_reply:
                    return  # deaf reply tears the connection down
        except (gob.GobError, RPCError, OSError, EOFError, RecursionError):
            pass
        finally:
            with self._lock:
                self._live.discard(conn)
            conn.close()

    @staticmethod
    def _respond(enc, method, seq, err, reply_schema, reply, conn,
                 discard_reply) -> None:
        if discard_reply:
            # Executed, but the client sees a dead connection — the SHUT_WR
            # trick (paxos/paxos.go:535-538).
            conn.shutdown(socket.SHUT_WR)
            return
        enc.encode(RESPONSE, {"ServiceMethod": method, "Seq": seq,
                              "Error": err})
        enc.encode(reply_schema, reply)


def _roundtrip(enc, dec, addr, method, seq, args_schema, args):
    """One Request/args → Response/reply exchange on an established
    connection — the wire conversation shared by `gob_call` and
    `GobClientPool.call`.  Transport/codec failures become RPCError; the
    caller decides connection lifecycle before surfacing Response.Error."""
    try:
        enc.encode(REQUEST, {"ServiceMethod": method, "Seq": seq})
        enc.encode(args_schema, args or {})
        _, resp = dec.next()
        resp = gob.complete(RESPONSE, resp)
        _, reply = dec.next()
    except (OSError, EOFError, gob.GobError, RecursionError) as e:
        raise RPCError(f"gob call {method}@{addr}: {e}") from e
    return resp, reply


def _finish(resp, reply, addr, method, reply_schema):
    if resp["Error"]:
        raise RPCError(f"{method}@{addr}: {resp['Error']}")
    return gob.complete(reply_schema, reply) if reply_schema else reply


class GobClientPool:
    """Reusable net/rpc client connections — Go's `rpc.Dial` + long-lived
    `rpc.Client` model, as the optimized alternative to the reference's
    dial-per-call `call()` wrapper (`paxos/rpc.go:24-42`).

    Wire-identical per request (Request{ServiceMethod, Seq} + args body);
    only the connection lifecycle differs, and every net/rpc server —
    including Go's `rpc.ServeConn` and `GobRpcServer._serve_conn` above —
    already serves many sequential requests per connection.  Keeps up to
    `cap_idle` idle connections per address (concurrent callers borrow
    distinct connections, so fan-out does not serialize); any transport or
    decode error closes that connection and raises RPCError — the caller's
    at-most-once obligations are exactly those of `gob_call`.

    NOT a drop-in where per-CALL fault injection matters: the reference
    harness's accept-loop coin flips fire per connection, so a pooled
    client sees them only at dial time.  Fidelity deployments (the test
    harness, the bench's reference-model `wire` config) keep dial-per-call.
    """

    def __init__(self, registry: gob.Registry | None = None,
                 timeout: float = 10.0, cap_idle: int = 4):
        import threading

        self.registry = registry
        self.timeout = timeout
        self.cap_idle = cap_idle
        self._idle: dict[str, list] = {}
        self._mu = threading.Lock()
        self._closed = False

    def _dial(self, addr: str):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            sock.settimeout(self.timeout)
            sock.connect(addr)
            enc = gob.Encoder(sock.sendall, self.registry)
            dec = gob.Decoder(_sock_read(sock))
        except BaseException:
            sock.close()
            raise
        return [sock, enc, dec, 0]  # [sock, encoder, decoder, last seq]

    def _take(self, addr: str):
        with self._mu:
            if self._closed:
                raise RPCError("client pool closed")
            stack = self._idle.get(addr)
            if stack:
                return stack.pop()
        return self._dial(addr)

    def _put(self, addr: str, conn) -> None:
        with self._mu:
            if not self._closed:
                stack = self._idle.setdefault(addr, [])
                if len(stack) < self.cap_idle:
                    stack.append(conn)
                    return
        conn[0].close()

    def call(self, addr: str, method: str, args_schema: gob.Struct,
             args: dict, reply_schema: gob.Struct | None = None) -> dict:
        try:
            conn = self._take(addr)
        except OSError as e:
            raise RPCError(f"gob dial {addr}: {e}") from e
        sock = conn[0]
        conn[3] = seq = conn[3] + 1
        ok = False
        try:
            resp, reply = _roundtrip(conn[1], conn[2], addr, method, seq,
                                     args_schema, args)
            if resp["Seq"] != seq:
                # One-at-a-time per connection: a mismatch means the stream
                # is desynchronized (e.g. a previous half-read).
                raise RPCError(f"{method}@{addr}: seq mismatch "
                               f"{resp['Seq']} != {seq}")
            ok = True
        finally:
            # Exactly one owner on every exit path: re-pool on success,
            # close on ANY failure (including unexpected exception types —
            # a half-written request must never be reused).
            if ok:
                self._put(addr, conn)  # app errors leave the conn healthy
            else:
                sock.close()
        return _finish(resp, reply, addr, method, reply_schema)

    def close(self) -> None:
        """Terminal: closes idle connections now; connections in flight are
        closed as their calls finish (never re-pooled), and later calls
        raise RPCError."""
        with self._mu:
            self._closed = True
            for stack in self._idle.values():
                for conn in stack:
                    conn[0].close()
            self._idle.clear()


def gob_call(addr: str, method: str, args_schema: gob.Struct, args: dict,
             reply_schema: gob.Struct | None = None,
             registry: gob.Registry | None = None,
             timeout: float = 10.0) -> dict:
    """One dial-per-call net/rpc invocation — the client half of the
    reference's `call()` (`paxos/rpc.go:24-42`), with the same contract:
    raises RPCError when the server can't be reached or the reply is lost
    (the op may still have executed); a Response.Error becomes an RPCError
    too, matching `call()` returning false on `c.Call` error."""
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(timeout)
    try:
        try:
            sock.connect(addr)
        except OSError as e:
            raise RPCError(f"gob call {method}@{addr}: {e}") from e
        enc = gob.Encoder(sock.sendall, registry)
        dec = gob.Decoder(_sock_read(sock))
        # Go's net/rpc client numbers from 1.
        resp, reply = _roundtrip(enc, dec, addr, method, 1,
                                 args_schema, args)
        return _finish(resp, reply, addr, method, reply_schema)
    finally:
        sock.close()
