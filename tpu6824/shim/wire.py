"""The reference's exact RPC wire structs, as gob schemas.

Field names, order, and Go types are copied from the reference's common.go /
rpc.go files (citations inline) — field ORDER matters because gob type
definitions list fields positionally, and NAMES matter because gob decoders
match wire fields to local struct fields by name.  Named Go string types
(`Err`) and sized ints (`int64`, `uint`, `uint64`) collapse to gob's builtin
string/int/uint ids, exactly as Go's encoder treats them.
"""

from tpu6824.shim.gob import (
    BOOL, INT, INTERFACE, STRING, UINT, Array, Map, Registry, Slice, Struct,
)

# --------------------------------------------------------------- paxos
# paxos/rpc.go:52-84.  Value is interface{} — the application's Op struct
# rides inside (kvpaxos gob-registers its Op; see REGISTRY below).

PREPARE_ARGS = Struct("PrepareArgs", [("Instance", INT), ("Proposal", INT)])
PREPARE_REPLY = Struct("PrepareReply", [
    ("Err", STRING), ("Instance", INT), ("Proposal", INT),
    ("Value", INTERFACE),
])
ACCEPT_ARGS = Struct("AcceptArgs", [
    ("Instance", INT), ("Proposal", INT), ("Value", INTERFACE),
])
ACCEPT_REPLY = Struct("AcceptReply", [("Err", STRING)])
DECIDED_ARGS = Struct("DecidedArgs", [
    ("Sender", INT), ("DoneIns", INT), ("Instance", INT),
    ("Value", INTERFACE),
])
DECIDED_REPLY = Struct("DecidedReply", [])

# ------------------------------------------------------------- kvpaxos
# kvpaxos/common.go:17-42.

KV_PUTAPPEND_ARGS = Struct("PutAppendArgs", [
    ("Key", STRING), ("Value", STRING), ("Op", STRING), ("OpID", INT),
])
KV_PUTAPPEND_REPLY = Struct("PutAppendReply", [("Err", STRING)])
KV_GET_ARGS = Struct("GetArgs", [("Key", STRING), ("OpID", INT)])
KV_GET_REPLY = Struct("GetReply", [("Err", STRING), ("Value", STRING)])

# kvpaxos/server.go:25-33 — the Op logged through Paxos, gob-registered so
# it can travel in PrepareReply.Value etc.  Fields match the reference
# struct exactly (OpID, Op, Key, Value) — no extras, so a Go peer's decoder
# sees precisely the wire fields its own `gob.Register(Op{})` declared.
KV_OP = Struct("Op", [
    ("OpID", INT), ("Op", STRING), ("Key", STRING), ("Value", STRING),
])

# --------------------------------------------------------- viewservice
# viewservice/common.go:36-40, 58-80.

VIEW = Struct("View", [
    ("Viewnum", UINT), ("Primary", STRING), ("Backup", STRING),
])
PING_ARGS = Struct("PingArgs", [("Me", STRING), ("Viewnum", UINT)])
PING_REPLY = Struct("PingReply", [("View", VIEW)])
VS_GET_ARGS = Struct("GetArgs", [])
VS_GET_REPLY = Struct("GetReply", [("View", VIEW)])

# ----------------------------------------------------------- pbservice
# pbservice/common.go:21-47, 76-88.

PB_PUTAPPEND_ARGS = Struct("PutAppendArgs", [
    ("Key", STRING), ("Value", STRING), ("OpID", INT), ("Method", STRING),
])
PB_PUTAPPEND_REPLY = Struct("PutAppendReply", [("Err", STRING)])
PB_GET_ARGS = Struct("GetArgs", [("Key", STRING), ("OpID", INT)])
PB_GET_REPLY = Struct("GetReply", [("Err", STRING), ("Value", STRING)])
PB_INITSTATE_ARGS = Struct("InitStateArgs", [("State", Map(STRING, STRING))])
PB_INITSTATE_REPLY = Struct("InitStateReply", [("Err", STRING)])

# --------------------------------------------------------- lockservice
# lockservice/common.go:14-33.

LOCK_ARGS = Struct("LockArgs", [("Lockname", STRING)])
LOCK_REPLY = Struct("LockReply", [("OK", BOOL)])
UNLOCK_ARGS = Struct("UnlockArgs", [("Lockname", STRING)])
UNLOCK_REPLY = Struct("UnlockReply", [("OK", BOOL)])

# --------------------------------------------------------- shardmaster
# shardmaster/common.go:35-69.  Shards is [10]int64; Groups map[int64][]string.

CONFIG = Struct("Config", [
    ("Num", INT), ("Shards", Array(10, INT)),
    ("Groups", Map(INT, Slice(STRING))),
])
SM_JOIN_ARGS = Struct("JoinArgs", [("GID", INT), ("Servers", Slice(STRING))])
SM_JOIN_REPLY = Struct("JoinReply", [])
SM_LEAVE_ARGS = Struct("LeaveArgs", [("GID", INT)])
SM_LEAVE_REPLY = Struct("LeaveReply", [])
SM_MOVE_ARGS = Struct("MoveArgs", [("Shard", INT), ("GID", INT)])
SM_MOVE_REPLY = Struct("MoveReply", [])
SM_QUERY_ARGS = Struct("QueryArgs", [("Num", INT)])
SM_QUERY_REPLY = Struct("QueryReply", [("Config", CONFIG)])

# ------------------------------------------------------------- shardkv
# shardkv/common.go:21-56; Rep and XState from shardkv/server.go:60-80.

SKV_GET_ARGS = Struct("GetArgs", [
    ("Key", STRING), ("CID", STRING), ("Seq", INT),
])
SKV_GET_REPLY = Struct("GetReply", [("Err", STRING), ("Value", STRING)])
SKV_PUTAPPEND_ARGS = Struct("PutAppendArgs", [
    ("Key", STRING), ("Value", STRING), ("Op", STRING), ("CID", STRING),
    ("Seq", INT),
])
SKV_PUTAPPEND_REPLY = Struct("PutAppendReply", [("Err", STRING)])
REP = Struct("Rep", [("Err", STRING), ("Value", STRING)])
XSTATE = Struct("XState", [
    ("KVStore", Map(STRING, STRING)),
    ("MRRSMap", Map(STRING, INT)),
    ("Replies", Map(STRING, REP)),
])
SKV_TRANSFER_ARGS = Struct("TransferStateArgs", [
    ("ConfigNum", INT), ("Shard", INT),
])
SKV_TRANSFER_REPLY = Struct("TransferStateReply", [
    ("Err", STRING), ("XState", XSTATE),
])

# --------------------------------------------------------------- diskv
# diskv/common.go mirrors shardkv's args (CID string, Seq int).

DKV_GET_ARGS = SKV_GET_ARGS
DKV_GET_REPLY = SKV_GET_REPLY
DKV_PUTAPPEND_ARGS = SKV_PUTAPPEND_ARGS
DKV_PUTAPPEND_REPLY = SKV_PUTAPPEND_REPLY


def default_registry() -> Registry:
    """Concrete types Go registers for interface{} transport —
    the analog of the reference's `gob.Register(Op{})` calls."""
    return (
        Registry()
        .register("kvpaxos.Op", KV_OP)
        .register("string", STRING)
        .register("int", INT)
    )
