"""Go `encoding/gob` stream codec — pure Python, no Go required.

Implements the gob wire format (the encoding under Go's `net/rpc`, which is
the reference's transport codec everywhere — `paxos/rpc.go:25` dials with
`rpc.Dial`, whose connections speak gob) precisely enough that an unmodified
Go clerk can exchange every wire struct in the reference with this framework.

Format summary (derived from Go's encoding/gob specification, gob/doc.go):

  - **Unsigned int**: value < 128 → one byte.  Otherwise one byte holding
    ``256 - n`` (n = minimal big-endian byte count) followed by those bytes.
  - **Signed int**: bit 0 is the sign; ``i >= 0 → u = i<<1``,
    ``i < 0 → u = (~i)<<1 | 1``, then unsigned encoding.
  - **Bool**: uint 0/1.  **Float**: float64 bits byte-reversed, as uint.
  - **String / []byte**: uint length + raw bytes.
  - **Slice**: uint count + elements.  **Array**: uint count (== fixed len) +
    elements.  **Map**: uint count + alternating key, value.
  - **Struct**: (uint field-delta, field value)... terminated by uint 0.
    Field deltas start from index -1; zero-valued fields are omitted.
  - **Top-level non-struct values** are preceded by a single 0x00 "delta"
    byte (Go's `decodeSingle` requires a zero delta).
  - **Stream**: a sequence of messages, each a uint byte-count + payload.
    Payload starts with a signed type id.  Negative id → a type *definition*
    (a `wireType` meta-struct) for ``-id``; the value follows in a later
    message.  Positive id → a value of that type.  Ids < 64 are predefined
    (bool=1 int=2 uint=3 float=4 bytes=5 string=6 complex=7 interface=8);
    user-defined compound types are assigned 65, 66, ... per stream, each
    defined before first use.
  - **Interface values**: uint name length + registered concrete-type name,
    signed concrete type id, uint byte-count, then the concrete value encoded
    as a top-level body.  Type definitions needed by the concrete type are
    emitted as separate messages *before* the message containing the
    interface value.  A nil interface is a zero-length name.

Named non-struct Go types (`type Err string`, `uint64`, `int64`) collapse to
their builtin base type, exactly as Go's type system does — so `Err` travels
as string (id 6) and `Seq uint64` as uint (id 3).

Python value mapping: struct ↔ dict keyed by Go field name, map ↔ dict,
slice/array ↔ list, string ↔ str, bytes ↔ bytes, interface ↔
``(registered_name, value)`` tuple or ``None``.

No Go toolchain exists in this image, so the golden byte vectors in
`tests/test_gob.py` are hand-derived from the specification rather than
captured from a live Go encoder; the derivations are spelled out there.
"""

from __future__ import annotations

import struct as _struct
import threading

__all__ = [
    "BOOL", "INT", "UINT", "FLOAT", "BYTES", "STRING", "INTERFACE",
    "Slice", "Array", "Map", "Struct",
    "GobError", "Encoder", "Decoder", "Registry", "zero_of", "complete",
]

_MAX_MESSAGE = 64 << 20
# Decode-nesting cap: a hostile stream can define a slice whose element id is
# itself (or an arbitrarily deep typedef chain), which would otherwise drive
# the recursive decoder to a Python RecursionError.  Go's decoder has the
# same class of guard (maxIgnoreNestingDepth).  The reference's deepest real
# struct (TransferStateReply → XState → map[string]Rep) nests 4 levels.
_MAX_DEPTH = 64

BOOL_ID = 1
INT_ID = 2
UINT_ID = 3
FLOAT_ID = 4
BYTES_ID = 5
STRING_ID = 6
COMPLEX_ID = 7
INTERFACE_ID = 8
_FIRST_USER_ID = 65


class GobError(Exception):
    pass


# --------------------------------------------------------------------------
# schemas


class GobType:
    """Base schema node.  `key()` is a structural identity — two schema nodes
    with equal keys describe the same Go type and share one wire type id,
    mirroring Go's per-reflect-type id assignment."""

    def key(self):
        raise NotImplementedError

    def __eq__(self, other):
        return isinstance(other, GobType) and self.key() == other.key()

    def __hash__(self):
        return hash(self.key())


class _Builtin(GobType):
    def __init__(self, name: str, tid: int):
        self.name = name
        self.id = tid

    def key(self):
        return ("builtin", self.id)

    def __repr__(self):
        return self.name


BOOL = _Builtin("BOOL", BOOL_ID)
INT = _Builtin("INT", INT_ID)
UINT = _Builtin("UINT", UINT_ID)
FLOAT = _Builtin("FLOAT", FLOAT_ID)
BYTES = _Builtin("BYTES", BYTES_ID)
STRING = _Builtin("STRING", STRING_ID)
INTERFACE = _Builtin("INTERFACE", INTERFACE_ID)


class Slice(GobType):
    def __init__(self, elem: GobType):
        self.elem = elem

    def key(self):
        return ("slice", self.elem.key())

    def __repr__(self):
        return f"Slice({self.elem!r})"


class Array(GobType):
    def __init__(self, length: int, elem: GobType):
        self.length = length
        self.elem = elem

    def key(self):
        return ("array", self.length, self.elem.key())

    def __repr__(self):
        return f"Array({self.length}, {self.elem!r})"


class Map(GobType):
    def __init__(self, kt: GobType, vt: GobType):
        self.kt = kt
        self.vt = vt

    def key(self):
        return ("map", self.kt.key(), self.vt.key())

    def __repr__(self):
        return f"Map({self.kt!r}, {self.vt!r})"


class Struct(GobType):
    def __init__(self, name: str, fields: list[tuple[str, GobType]]):
        self.name = name
        self.fields = list(fields)

    def key(self):
        return ("struct", self.name, tuple((n, t.key()) for n, t in self.fields))

    def __repr__(self):
        return f"Struct({self.name!r})"


def zero_of(t: GobType):
    """Go's zero value for a schema node, in the Python mapping."""
    if t is BOOL:
        return False
    if t in (INT, UINT):
        return 0
    if t is FLOAT:
        return 0.0
    if t is BYTES:
        return b""
    if t is STRING:
        return ""
    if t is INTERFACE:
        return None
    if isinstance(t, Slice):
        return []
    if isinstance(t, Array):
        return [zero_of(t.elem) for _ in range(t.length)]
    if isinstance(t, Map):
        return {}
    if isinstance(t, Struct):
        return {n: zero_of(ft) for n, ft in t.fields}
    raise GobError(f"no zero for {t!r}")


def _is_zero(t: GobType, v) -> bool:
    if t is BOOL:
        return not v
    if t in (INT, UINT):
        return v == 0
    if t is FLOAT:
        return v == 0.0
    if t in (BYTES, STRING):
        return len(v) == 0
    if t is INTERFACE:
        return v is None
    if isinstance(t, (Slice, Map)):
        return v is None or len(v) == 0
    if isinstance(t, Array):
        return all(_is_zero(t.elem, e) for e in v)
    if isinstance(t, Struct):
        return all(_is_zero(ft, _field_of(v, n, ft)) for n, ft in t.fields)
    raise GobError(f"no zero-check for {t!r}")


def _field_of(v, name: str, ft: GobType):
    """Struct field access for both value conventions (dict or object)."""
    if isinstance(v, dict):
        return v.get(name, zero_of(ft))
    return getattr(v, name)


def complete(t: GobType, v):
    """Fill gob's omitted-zero-field holes: recursively supply Go zero values
    for struct fields absent from a decoded dict."""
    if isinstance(t, Struct):
        return {
            n: complete(ft, v[n]) if n in v else zero_of(ft)
            for n, ft in t.fields
        }
    if isinstance(t, (Slice, Array)):
        return [complete(t.elem, e) for e in v]
    if isinstance(t, Map):
        return {k: complete(t.vt, e) for k, e in v.items()}
    return v


class Registry:
    """Concrete types transmittable inside interface values — the analog of
    `gob.Register` (the reference registers its Op structs so they can ride
    `PrepareArgs.Value interface{}`, e.g. kvpaxos's `gob.Register(Op{})`)."""

    def __init__(self):
        self._by_name: dict[str, GobType] = {}

    def register(self, name: str, t: GobType) -> "Registry":
        self._by_name[name] = t
        return self

    def lookup(self, name: str) -> GobType:
        try:
            return self._by_name[name]
        except KeyError:
            raise GobError(f"unregistered interface concrete type {name!r}")


# --------------------------------------------------------------------------
# primitive (de)serializers


def enc_uint(out: bytearray, u: int) -> None:
    if u < 0 or u >= 1 << 64:
        raise GobError(f"uint out of range: {u}")  # Go caps at uint64
    if u < 128:
        out.append(u)
        return
    raw = u.to_bytes((u.bit_length() + 7) // 8, "big")
    out.append(256 - len(raw))
    out += raw


def enc_int(out: bytearray, i: int) -> None:
    enc_uint(out, (i << 1) if i >= 0 else ((~i) << 1) | 1)


def enc_float(out: bytearray, f: float) -> None:
    enc_uint(out, int.from_bytes(_struct.pack(">d", f)[::-1], "big"))


def enc_string(out: bytearray, s) -> None:
    raw = s.encode("utf-8") if isinstance(s, str) else bytes(s)
    enc_uint(out, len(raw))
    out += raw


class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise GobError("truncated gob data")
        b = self.data[self.pos:self.pos + n]
        self.pos += n
        return b

    def uint(self) -> int:
        b = self.take(1)[0]
        if b < 128:
            return b
        n = 256 - b
        if n > 8:
            raise GobError(f"bad uint byte count {n}")
        return int.from_bytes(self.take(n), "big")

    def int_(self) -> int:
        u = self.uint()
        return ~(u >> 1) if (u & 1) else (u >> 1)

    def float_(self) -> float:
        u = self.uint()
        return _struct.unpack(">d", u.to_bytes(8, "big")[::-1])[0]

    def string(self) -> str:
        raw = self.take(self.uint())
        try:
            return raw.decode("utf-8")
        except UnicodeDecodeError as e:
            raise GobError(f"invalid UTF-8 in gob string: {e}") from e

    def done(self) -> bool:
        return self.pos >= len(self.data)


# --------------------------------------------------------------------------
# wire type definitions (the meta level)
#
# A type-definition message carries a `wireType` meta-struct value.  Field
# layout of the meta structs, per gob/type.go (ids 16-23 are reserved for
# them but never appear on the wire — the wireType structure is implied):
#
#   wireType   { ArrayT *arrayType; SliceT *sliceType; StructT *structType;
#                MapT *mapType; ... }           (field indices 0,1,2,3)
#   CommonType { Name string; Id int }
#   arrayType  { CommonType; Elem int; Len int }
#   sliceType  { CommonType; Elem int }
#   structType { CommonType; Field []fieldType }
#   fieldType  { Name string; Id int }
#   mapType    { CommonType; Key int; Elem int }


class _WireDef:
    """A decoded type definition: exactly one of array/slice/strct/mapp."""

    __slots__ = ("kind", "name", "elem", "length", "kt", "vt", "fields")

    def __init__(self, kind, name="", elem=None, length=0, kt=None, vt=None,
                 fields=None):
        self.kind = kind        # "array" | "slice" | "struct" | "map"
        self.name = name
        self.elem = elem        # type id (array/slice)
        self.length = length    # array
        self.kt = kt            # map key type id
        self.vt = vt            # map value type id
        self.fields = fields or []  # [(name, type id)] (struct)


def _dec_common(r: _Reader) -> tuple[str, int]:
    name, tid = "", 0
    f = -1
    while True:
        d = r.uint()
        if d == 0:
            return name, tid
        f += d
        if f == 0:
            name = r.string()
        elif f == 1:
            tid = r.int_()
        else:
            raise GobError(f"bad CommonType field {f}")


def _dec_typedef(r: _Reader) -> _WireDef:
    """Parse a wireType meta-struct value into a _WireDef."""
    f = -1
    d = r.uint()
    if d == 0:
        raise GobError("empty wireType")
    f += d
    if f == 0:  # ArrayT
        name, elem, length = "", 0, 0
        g = -1
        while True:
            d = r.uint()
            if d == 0:
                break
            g += d
            if g == 0:
                name, _tid = _dec_common(r)
            elif g == 1:
                elem = r.int_()
            elif g == 2:
                length = r.int_()
            else:
                raise GobError(f"bad arrayType field {g}")
        wd = _WireDef("array", name=name, elem=elem, length=length)
    elif f == 1:  # SliceT
        name, elem = "", 0
        g = -1
        while True:
            d = r.uint()
            if d == 0:
                break
            g += d
            if g == 0:
                name, _tid = _dec_common(r)
            elif g == 1:
                elem = r.int_()
            else:
                raise GobError(f"bad sliceType field {g}")
        wd = _WireDef("slice", name=name, elem=elem)
    elif f == 2:  # StructT
        name, fields = "", []
        g = -1
        while True:
            d = r.uint()
            if d == 0:
                break
            g += d
            if g == 0:
                name, _tid = _dec_common(r)
            elif g == 1:
                for _ in range(r.uint()):
                    fname, ftid = "", 0
                    h = -1
                    while True:
                        d2 = r.uint()
                        if d2 == 0:
                            break
                        h += d2
                        if h == 0:
                            fname = r.string()
                        elif h == 1:
                            ftid = r.int_()
                        else:
                            raise GobError(f"bad fieldType field {h}")
                    fields.append((fname, ftid))
            else:
                raise GobError(f"bad structType field {g}")
        wd = _WireDef("struct", name=name, fields=fields)
    elif f == 3:  # MapT
        name, kt, vt = "", 0, 0
        g = -1
        while True:
            d = r.uint()
            if d == 0:
                break
            g += d
            if g == 0:
                name, _tid = _dec_common(r)
            elif g == 1:
                kt = r.int_()
            elif g == 2:
                vt = r.int_()
            else:
                raise GobError(f"bad mapType field {g}")
        wd = _WireDef("map", name=name, kt=kt, vt=vt)
    else:
        raise GobError(f"unsupported wireType variant (field {f}) — "
                       "GobEncoder/BinaryMarshaler payloads not supported")
    if r.uint() != 0:
        raise GobError("wireType not terminated")
    return wd


# --------------------------------------------------------------------------
# Encoder


class Encoder:
    """One gob stream (one direction of one connection).  Thread-safe;
    type-definition state persists for the stream's lifetime, as in Go."""

    def __init__(self, sink, registry: Registry | None = None):
        """`sink(bytes)` transmits; `registry` resolves interface values."""
        self._sink = sink
        self._registry = registry or Registry()
        self._ids: dict[tuple, int] = {}
        self._next = _FIRST_USER_ID
        self._pending: list[bytes] = []  # framed type-def messages
        self._lock = threading.Lock()

    # -- type ids ----------------------------------------------------------

    # Framed type-definition messages memoized across streams: the body is
    # a pure function of (type, own id, component ids), and the dial-per-
    # call transport (one fresh Encoder per connection, paxos/rpc.go:24-42)
    # otherwise rebuilds identical definitions for every single RPC.
    # Bounded like the decoder's _TYPEDEF_CACHE: dynamically generated
    # Struct schemas must not grow it without limit.
    _DEF_CACHE: dict[tuple, bytes] = {}
    _DEF_CACHE_MAX = 4096

    def _type_id(self, t: GobType) -> int:
        if isinstance(t, _Builtin):
            return t.id
        k = t.key()
        tid = self._ids.get(k)
        if tid is not None:
            return tid
        # Define component types first (Go emits inner defs before outer).
        if isinstance(t, (Slice, Array)):
            elem_id = self._type_id(t.elem)
            comp = (elem_id,)
        elif isinstance(t, Map):
            kt_id = self._type_id(t.kt)
            vt_id = self._type_id(t.vt)
            comp = (kt_id, vt_id)
        elif isinstance(t, Struct):
            field_ids = [self._type_id(ft) for _, ft in t.fields]
            comp = tuple(field_ids)
        else:
            raise GobError(f"cannot assign id to {t!r}")
        ckey = (k, self._next, comp)
        cached = self._DEF_CACHE.get(ckey)
        if cached is not None:
            tid = self._next
            self._next += 1
            self._ids[k] = tid
            self._pending.append(cached)
            return tid
        tid = self._next
        self._next += 1
        self._ids[k] = tid

        body = bytearray()
        enc_int(body, -tid)
        if isinstance(t, Array):
            enc_uint(body, 1)                       # wireType.ArrayT
            self._enc_common(body, "", tid)
            enc_uint(body, 1)                       # .Elem
            enc_int(body, elem_id)
            enc_uint(body, 1)                       # .Len
            enc_int(body, t.length)
            enc_uint(body, 0)
        elif isinstance(t, Slice):
            enc_uint(body, 2)                       # wireType.SliceT
            self._enc_common(body, "", tid)
            enc_uint(body, 1)                       # .Elem
            enc_int(body, elem_id)
            enc_uint(body, 0)
        elif isinstance(t, Struct):
            enc_uint(body, 3)                       # wireType.StructT
            self._enc_common(body, t.name, tid)
            enc_uint(body, 1)                       # .Field
            enc_uint(body, len(t.fields))
            for (fname, _), fid in zip(t.fields, field_ids):
                enc_uint(body, 1)                   # fieldType.Name
                enc_string(body, fname)
                enc_uint(body, 1)                   # fieldType.Id
                enc_int(body, fid)
                enc_uint(body, 0)
            enc_uint(body, 0)
        else:  # Map
            enc_uint(body, 4)                       # wireType.MapT
            self._enc_common(body, "", tid)
            enc_uint(body, 1)                       # .Key
            enc_int(body, kt_id)
            enc_uint(body, 1)                       # .Elem
            enc_int(body, vt_id)
            enc_uint(body, 0)
        enc_uint(body, 0)                           # end wireType
        framed = self._frame(bytes(body))
        if len(self._DEF_CACHE) >= self._DEF_CACHE_MAX:
            self._DEF_CACHE.clear()
        self._DEF_CACHE[ckey] = framed
        self._pending.append(framed)
        return tid

    @staticmethod
    def _enc_common(out: bytearray, name: str, tid: int) -> None:
        """CommonType as the first (embedded) field of a *Type struct:
        field delta 1, then {Name?, Id}, then its terminator."""
        enc_uint(out, 1)
        if name:
            enc_uint(out, 1)                        # CommonType.Name
            enc_string(out, name)
            enc_uint(out, 1)                        # CommonType.Id (delta 1)
        else:
            enc_uint(out, 2)                        # skip zero Name
        enc_int(out, tid)
        enc_uint(out, 0)

    @staticmethod
    def _frame(payload: bytes) -> bytes:
        head = bytearray()
        enc_uint(head, len(payload))
        return bytes(head) + payload

    # -- values ------------------------------------------------------------

    def _enc_value(self, out: bytearray, t: GobType, v, top: bool) -> None:
        if isinstance(t, Struct):
            prev = -1
            for idx, (fname, ft) in enumerate(t.fields):
                fv = _field_of(v, fname, ft)
                if _is_zero(ft, fv):
                    continue
                enc_uint(out, idx - prev)
                prev = idx
                self._enc_value(out, ft, fv, top=False)
            enc_uint(out, 0)
            return
        if top:
            out.append(0)  # singleton zero delta (gob decodeSingle)
        self._enc_nonstruct(out, t, v)

    def _enc_nonstruct(self, out: bytearray, t: GobType, v) -> None:
        if t is BOOL:
            enc_uint(out, 1 if v else 0)
        elif t is INT:
            enc_int(out, int(v))
        elif t is UINT:
            enc_uint(out, int(v))
        elif t is FLOAT:
            enc_float(out, float(v))
        elif t is BYTES:
            enc_string(out, bytes(v))
        elif t is STRING:
            enc_string(out, v)
        elif t is INTERFACE:
            self._enc_interface(out, v)
        elif isinstance(t, (Slice, Array)):
            v = list(v or [])
            if isinstance(t, Array) and len(v) != t.length:
                raise GobError(f"array length {len(v)} != {t.length}")
            enc_uint(out, len(v))
            for e in v:
                self._enc_value(out, t.elem, e, top=False)
        elif isinstance(t, Map):
            v = v or {}
            enc_uint(out, len(v))
            for k, e in v.items():
                self._enc_value(out, t.kt, k, top=False)
                self._enc_value(out, t.vt, e, top=False)
        elif isinstance(t, Struct):
            self._enc_value(out, t, v, top=False)
        else:
            raise GobError(f"cannot encode {t!r}")

    def _enc_interface(self, out: bytearray, v) -> None:
        if v is None:
            enc_uint(out, 0)  # nil interface: empty concrete-type name
            return
        try:
            name, inner = v
        except (TypeError, ValueError):
            raise GobError(
                "interface value must be (registered_name, value) or None")
        t = self._registry.lookup(name)
        enc_string(out, name)
        tid = self._type_id(t)  # defs (if new) go to self._pending
        enc_int(out, tid)
        sub = bytearray()
        self._enc_value(sub, t, inner, top=True)
        enc_uint(out, len(sub))
        out += sub

    def encode(self, t: GobType, v) -> None:
        """Transmit one value, preceded by any new type definitions —
        the equivalent of Go's `Encoder.Encode`."""
        with self._lock:
            body = bytearray()
            tid = self._type_id(t)
            enc_int(body, tid)
            self._enc_value(body, t, v, top=True)
            pending, self._pending = self._pending, []
            self._sink(b"".join(pending) + self._frame(bytes(body)))


# --------------------------------------------------------------------------
# Decoder


# Parsed type-definition cache shared by all Decoder instances (read-only
# _WireDef values), keyed by the raw definition body bytes.  Bounded: a
# hostile peer streaming unique (valid) typedefs must not grow memory
# without limit — on overflow the cache resets (honest peers re-warm it
# with the handful of wire schemas immediately).
_TYPEDEF_CACHE: dict[bytes, "_WireDef"] = {}
_TYPEDEF_CACHE_MAX = 4096


class Decoder:
    """One gob stream, decoding generically from the sender's type
    definitions (field matching by name happens above, in `complete` /
    the net/rpc layer), exactly how Go's decoder is wire-driven."""

    def __init__(self, read):
        """`read(n)` returns exactly n bytes or raises EOFError/GobError.

        No registry: decoding is wire-driven (the sender's type-definition
        messages carry everything), so interface concrete types decode to
        ``(name, value)`` without local registration — matching is the
        caller's concern."""
        self._read = read
        self._wire: dict[int, _WireDef] = {}

    def _read_uint(self) -> int:
        b = self._read(1)[0]
        if b < 128:
            return b
        n = 256 - b
        if n > 8:
            raise GobError(f"bad uint byte count {n}")
        return int.from_bytes(self._read(n), "big")

    def next(self):
        """Decode the next *value* message → (type_id, value).  Type
        definitions are absorbed along the way.  Struct values arrive as
        dicts keyed by the sender's field names (zero fields absent —
        pass through `complete()` to fill them)."""
        while True:
            size = self._read_uint()
            if size > _MAX_MESSAGE:
                raise GobError(f"gob message too large: {size}")
            r = _Reader(self._read(size))
            tid = r.int_()
            if tid < 0:
                # Typedef bodies repeat verbatim on every dial-per-call
                # connection; parse each distinct body once, process-wide.
                body = r.data[r.pos:]
                wd = _TYPEDEF_CACHE.get(body)
                if wd is None:
                    wd = _dec_typedef(r)
                    if not r.done():
                        raise GobError(
                            "trailing bytes after type definition")
                    if len(_TYPEDEF_CACHE) >= _TYPEDEF_CACHE_MAX:
                        _TYPEDEF_CACHE.clear()
                    _TYPEDEF_CACHE[body] = wd
                self._wire[-tid] = wd
                continue
            v = self._dec_value(r, tid, top=True)
            if not r.done():
                raise GobError("trailing bytes after value")
            return tid, v

    # -- value decoding ----------------------------------------------------

    def _dec_value(self, r: _Reader, tid: int, top: bool, depth: int = 0):
        if depth > _MAX_DEPTH:
            raise GobError("gob value nesting too deep "
                           "(self-referential or hostile type definition)")
        wd = self._wire.get(tid)
        if wd is not None and wd.kind == "struct":
            return self._dec_struct(r, wd, depth)
        if top:
            if r.uint() != 0:
                raise GobError("non-zero delta for singleton value")
        return self._dec_nonstruct(r, tid, wd, depth)

    def _dec_struct(self, r: _Reader, wd: _WireDef, depth: int) -> dict:
        out = {}
        f = -1
        while True:
            d = r.uint()
            if d == 0:
                return out
            f += d
            if f >= len(wd.fields):
                raise GobError(
                    f"field index {f} out of range for struct {wd.name!r}")
            fname, ftid = wd.fields[f]
            out[fname] = self._dec_value(r, ftid, top=False, depth=depth + 1)

    def _dec_nonstruct(self, r: _Reader, tid: int, wd: _WireDef | None,
                       depth: int = 0):
        if wd is None:
            if tid == BOOL_ID:
                return r.uint() != 0
            if tid == INT_ID:
                return r.int_()
            if tid == UINT_ID:
                return r.uint()
            if tid == FLOAT_ID:
                return r.float_()
            if tid == BYTES_ID:
                return r.take(r.uint())
            if tid == STRING_ID:
                return r.string()
            if tid == COMPLEX_ID:
                return complex(r.float_(), r.float_())
            if tid == INTERFACE_ID:
                return self._dec_interface(r, depth)
            raise GobError(f"value of undefined type id {tid}")
        remaining = len(r.data) - r.pos
        if wd.kind in ("slice", "array"):
            n = r.uint()
            if wd.kind == "array" and n != wd.length:
                raise GobError(f"array count {n} != declared {wd.length}")
            if n > remaining:  # every element costs >= 1 byte
                raise GobError(f"{wd.kind} count {n} exceeds message size")
            return [self._dec_value(r, wd.elem, top=False, depth=depth + 1)
                    for _ in range(n)]
        if wd.kind == "map":
            n = r.uint()
            if 2 * n > remaining:  # every key+value costs >= 2 bytes
                raise GobError(f"map count {n} exceeds message size")
            out = {}
            for _ in range(n):
                k = self._dec_value(r, wd.kt, top=False, depth=depth + 1)
                out[k] = self._dec_value(r, wd.vt, top=False, depth=depth + 1)
            return out
        raise GobError(f"cannot decode wire kind {wd.kind!r}")

    def _dec_interface(self, r: _Reader, depth: int = 0):
        nlen = r.uint()
        if nlen == 0:
            return None
        try:
            name = r.take(nlen).decode("utf-8")
        except UnicodeDecodeError as e:
            raise GobError(f"invalid UTF-8 interface type name: {e}") from e
        tid = r.int_()
        blen = r.uint()
        sub = _Reader(r.take(blen))
        v = self._dec_value(sub, tid, top=True, depth=depth + 1)
        if not sub.done():
            raise GobError("trailing bytes inside interface value")
        return (name, v)
