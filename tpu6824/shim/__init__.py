"""shim/ — SURVEY §7 layer 5: wire-compatible Go `net/rpc` + `encoding/gob`
endpoints backed by the TPU runtime, so the reference's unmodified Go clerks
(`paxos/rpc.go:24-42` and the `call()` clones in every package) can drive this
framework over the same Unix-domain sockets.

  gob.py      — Go `encoding/gob` stream codec (encode + decode)
  netrpc.py   — Go `net/rpc` connection protocol (Request/Response framing)
  wire.py     — the reference's exact wire structs as gob schemas
  endpoints.py— per-service adapters mapping Go RPC names onto our services
"""
