"""HostPaxosPeer — the reference's decentralized runtime model, on the
reference's exact wire.

The fabric kernel (`core/fabric.py`) is the TPU path: all groups' consensus
advances as one batched tensor step.  This module is the complementary
*decentralized* path — one acceptor per process, a proposer loop per Start,
and real per-message `Paxos.Prepare`/`Paxos.Accept`/`Paxos.Decided` RPCs
over gob Unix sockets (`paxos/rpc.go:52-84` wire structs via `shim/wire.py`)
— so a deployment can mix these peers with the reference's own Go peers,
and the per-message fault machinery (accept-loop drops, socket surgery)
applies at message granularity exactly as in the reference.

Semantics follow `paxos/paxos.go` with the fork's defects fixed:
  - proposal numbers are globally unique: n = round·P + me + 1
    (fixes SURVEY §2.4.6 — the reference's highest-seen+1 can collide);
  - no goroutine leak per accept round (§2.4.5) — one proposer thread per
    undecided instance, exiting on decision;
  - acceptor grants Prepare iff n > prep_n (`paxos.go:244-257`) and Accept
    iff n >= prep_n (`paxos.go:300-313`);
  - Decided broadcasts piggyback the sender's Done sequence
    (`rpc.go:74-80`, `paxos.go:328-341`), driving the Min() window GC
    (`paxos.go:352-425`): state below Min is forgotten everywhere.

Values travel as gob interface values: plain str/int are auto-wrapped with
their Go-registered names; anything else must be a ``(registered_name,
value)`` pair with the name in the peer's registry (the `gob.Register`
contract).
"""

from __future__ import annotations

import os
import pickle
import random
import threading
import time
from collections import deque

from tpu6824.core.peer import Fate
from tpu6824.shim import wire
from tpu6824.shim.gob import Registry
from tpu6824.shim.netrpc import GobRpcServer, gob_call
from tpu6824.utils.errors import OK, RPCError
from tpu6824.utils import crashsink, durafs
from tpu6824.utils.trace import EventLog, dprintf

_REJECTED = "ErrRejected"  # paxos/rpc.go:47

# Participation floor covering every possible instance: an amnesiac boot
# grants nothing until the rejoin protocol lowers the floor (force=True).
FLOOR_ALL = 1 << 62


def _wrap(value):
    if value is None or isinstance(value, tuple):
        return value
    if isinstance(value, str):
        return ("string", value)
    if isinstance(value, bool):
        raise ValueError("bool consensus values are not wire-mapped")
    if isinstance(value, int):
        return ("int", value)
    raise ValueError(
        f"value {value!r} is not (registered_name, value) or str/int")


class _Acc:
    __slots__ = ("prep_n", "acc_n", "acc_v")

    def __init__(self):
        self.prep_n = 0
        self.acc_n = 0
        self.acc_v = None  # wrapped (name, value) or None


class HostPaxosPeer:
    """One peer = one gob endpoint + acceptor state + proposer loops, with
    the reference's public contract: Make/Start/Status/Done/Min/Max."""

    def __init__(self, peers: list[str], me: int,
                 registry: Registry | None = None,
                 seed: int | None = None, backoff: float = 0.02,
                 persist_dir: str | None = None,
                 max_proposers: int = 64,
                 bind_addr: str | None = None,
                 pooled: bool = False,
                 parallel_fanout: bool = False,
                 participation_floor: int | None = None):
        """With `persist_dir`, acceptor promises/acceptances, decisions,
        and Done state are written to disk BEFORE any RPC reply leaves —
        Paxos's durability requirement — and reloaded on construction, so
        this peer survives crash+restart.  The reference's paxos explicitly
        does NOT (`paxos/paxos.go:3-11`: "not crash+restart"); Lab 5 was
        meant to add it and the fork left it empty (SURVEY §2.4.7) — this
        implements what that lab asked for, with the diskv file discipline
        (atomic write-via-rename, `diskv/server.go:92-105`).

        Disk-LOSS restart is NOT safe on this path: an acceptor restarted
        over an empty persist_dir has forgotten its promises and could
        re-grant against them (the amnesia problem — a node cannot detect
        its own disk loss, since the marker would be on the lost disk).
        Operators must treat disk loss as a dead peer and redeploy; the
        diskv service layer handles disk-lossy REJOIN safely instead
        (`services/diskv.py::_snapshot_from_peer` + the Test5RejoinMix
        analogs), because there the RSM state, not the consensus vote
        ledger, is what the lost disk held.

        `bind_addr` separates where this peer LISTENS from how its peers[]
        entry is dialed — required by the link-farm partition harness
        (`rpc.transport.LinkFarm`), where every peer dials through its own
        per-edge alias paths while servers bind their real sockets.

        `pooled=True` reuses net/rpc client connections (Go's long-lived
        rpc.Client model; `shim.netrpc.GobClientPool`) instead of the
        reference's dial-per-call — wire-identical per request and still
        compatible with unmodified Go servers, but the harness's per-
        connection fault injection then fires only at dial time, so keep
        the default for fidelity runs."""
        self.peers = list(peers)
        self.me = me
        self.addr = bind_addr or peers[me]
        self.P = len(peers)
        self.mu = threading.Lock()
        self.acc: dict[int, _Acc] = {}
        self.values: dict[int, tuple | None] = {}  # decided (wrapped)
        self.done_seqs = [-1] * self.P             # paxos.go doneSeqs
        self.max_seq = -1
        # Acceptor amnesia floor (see set_participation_floor): grants are
        # refused at/below it.  -1 = normal participation everywhere.  An
        # amnesiac restart passes `participation_floor=FLOOR_ALL` so the
        # endpoint comes up refusing every grant — there is no window
        # between the accept loop starting and the rejoin protocol
        # computing the real horizon.
        self._floor = -1 if participation_floor is None else participation_floor
        self.dead = False
        self.backoff = backoff
        self._rng = random.Random(seed)
        self._proposing: set[int] = set()
        # Bounded proposer pool: at most `max_proposers` concurrent proposer
        # threads; further Starts queue and run as workers free up (the
        # reference's goroutine-per-Start is fine in Go; a Python deployment
        # with thousands of in-flight instances would thrash on threads).
        self._max_proposers = max_proposers
        self._prop_threads = 0
        self._prop_q: deque[tuple[int, tuple | None]] = deque()
        # Decided re-delivery: ONE daemon thread per unreachable peer (at
        # most P), each draining a per-peer queue of (seq, value) — not one
        # immortal thread per decided instance.
        self._redeliver_q: list[deque] = [deque() for _ in range(self.P)]
        self._redeliver_on = [False] * self.P
        # Same observability surface as the fabric (SURVEY §5 build note):
        # counters + bounded event ring, dprintf under tag "hostpaxos".
        self.events = EventLog()
        self.persist_dir = persist_dir
        if persist_dir:
            os.makedirs(persist_dir, exist_ok=True)
            self._reload()
            if participation_floor is not None:
                # The quarantine must be durable from the very first
                # instant: a crash after a peer's Decided lands a dec-*
                # file but before any meta write would otherwise make the
                # next restart look non-amnesiac and boot unguarded.
                with self.mu:
                    self._persist_meta_locked()
        reg = registry or wire.default_registry()
        self._pool = None
        self._fanout = None
        if pooled:
            from tpu6824.shim.netrpc import GobClientPool

            self._pool = GobClientPool(registry=reg, timeout=5.0,
                                       cap_idle=2 * self.P)
        if parallel_fanout:
            # Phases fan out to the other peers CONCURRENTLY — one RTT per
            # phase instead of the reference's one RTT per peer per phase
            # (sendPrepareToAll loops sequentially, paxos/paxos.go:161-190).
            # Wins when round-trips dominate (multi-core hosts, multi-host
            # DCN links); LOSES on a single shared core, where the peers'
            # server work contends with the fan-out threads — measured
            # 839/s vs 1350/s sequential-pooled on the 1-core CI box —
            # hence opt-in rather than tied to pooling.
            from concurrent.futures import ThreadPoolExecutor

            # Sized for worst-case contention — EVERY proposer slot
            # simultaneously fanning P-1 blocking calls (e.g. a deaf peer
            # holding 5s timeouts).  A smaller shared pool would queue
            # healthy-peer calls behind deaf-peer timeouts, degrading
            # liveness below the sequential mode this exists to beat.
            self._fanout = ThreadPoolExecutor(
                max_workers=max(2, (self.P - 1) * max_proposers),
                thread_name_prefix=f"px{me}-fan")
        self.server = GobRpcServer(self.addr, seed=seed, registry=reg)
        self.server.register_method("Paxos.Prepare", self._rpc_prepare,
                                    wire.PREPARE_ARGS, wire.PREPARE_REPLY)
        self.server.register_method("Paxos.Accept", self._rpc_accept,
                                    wire.ACCEPT_ARGS, wire.ACCEPT_REPLY)
        self.server.register_method("Paxos.Decided", self._rpc_decided,
                                    wire.DECIDED_ARGS, wire.DECIDED_REPLY)
        self._registry = reg
        self.server.start()

    # ------------------------------------------------- public contract

    def start(self, seq: int, value) -> None:
        """Async agreement on instance seq (paxos/paxos.go:99-109)."""
        v = _wrap(value)
        with self.mu:
            if self.dead or seq < self._min_locked():
                return
            self.max_seq = max(self.max_seq, seq)
            if seq in self.values or seq in self._proposing:
                return
            self._proposing.add(seq)
            if self._prop_threads >= self._max_proposers:
                self._prop_q.append((seq, v))
                return
            self._prop_threads += 1
        threading.Thread(
            target=crashsink.guarded(self._proposer_worker, "hostpeer-proposer"),
            args=(seq, v), daemon=True).start()

    def status(self, seq: int):
        """Local-only read (paxos/paxos.go:434-447)."""
        fate, wrapped = self.status_wrapped(seq)
        return fate, _unwrap(wrapped)

    def status_wrapped(self, seq: int):
        """status() keeping the gob interface wrapping: DECIDED values come
        back as the ``(registered_name, value)`` pair, so typed consumers
        (e.g. the kvpaxos Op adapter) can check what's in the log instead
        of assuming."""
        with self.mu:
            if seq < self._min_locked():
                return Fate.FORGOTTEN, None
            if seq in self.values:
                return Fate.DECIDED, self.values[seq]
            return Fate.PENDING, None

    def done(self, seq: int) -> None:
        with self.mu:
            if seq > self.done_seqs[self.me]:
                self.done_seqs[self.me] = seq
                self._persist_meta_locked()

    def min(self) -> int:
        with self.mu:
            return self._min_locked()

    def max(self) -> int:
        with self.mu:
            return self.max_seq

    def kill(self) -> None:
        with self.mu:
            self.dead = True
        if self._fanout is not None:
            self._fanout.shutdown(wait=False, cancel_futures=True)
        if self._pool is not None:
            self._pool.close()
        self.server.kill()

    # fault hooks delegate to the endpoint (the reference's accept loop).
    def set_unreliable(self, flag: bool) -> None:
        self.server.set_unreliable(flag)

    def deafen(self) -> None:
        self.server.deafen()

    def undeafen(self) -> None:
        self.server.undeafen()

    @property
    def rpc_count(self) -> int:
        return self.server.rpc_count

    # ------------------------------------------------- persistence

    def _pfile(self, name: str) -> str:
        return os.path.join(self.persist_dir, name)

    def _persist(self, name: str, obj) -> None:
        """Atomic write-via-rename + fsync — durable before the caller's
        RPC reply leaves the process.  Routed through the one durafs
        seam (tmp fsync + rename + DIR fsync — the old local version
        skipped the dir sync, so the rename itself could be lost), which
        is also where the durafault nemesis injects torn writes and
        fsync lies against the acceptor ledger."""
        try:
            durafs.atomic_write(
                self._pfile(name),
                pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))
        except FileNotFoundError:
            # A rebooted peer's _reload swept OUR in-flight .tmp out
            # from under the rename (same dir, old instance still
            # draining) — the write is moot, we are dead; any live
            # writer losing its file is a real bug (diskv's _apply has
            # the identical tolerance).
            if not self.dead:
                raise

    def _persist_acc_locked(self, seq: int) -> None:
        if not self.persist_dir:
            return
        st = self.acc[seq]
        self._persist(f"acc-{seq}", (st.prep_n, st.acc_n, st.acc_v))

    def _persist_decided_locked(self, seq: int) -> None:
        if not self.persist_dir:
            return
        self._persist(f"dec-{seq}", self.values[seq])

    def _persist_meta_locked(self) -> None:
        if not self.persist_dir:
            return
        # The floor rides the meta record so a post-rejoin crash with an
        # intact disk cannot resurrect grants below it (the pre-disk-loss
        # promises it guards against are STILL forgotten).
        self._persist("meta", (self.done_seqs, self.max_seq, self._floor))

    def _reload(self) -> None:
        """Crash recovery: restore promises, acceptances, decisions, and the
        Done window from disk."""
        for fn in os.listdir(self.persist_dir):
            path = self._pfile(fn)
            if fn.endswith(".tmp"):
                # Torn-write debris (durafs names scratch files
                # `<name>.<pid>.<tid>.tmp`; the injector's torn fault
                # leaves them behind deliberately): swept at reboot like
                # diskv's _load_from_disk sweep, or a fault-heavy soak
                # grows the ledger dir without bound.
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass
                continue
            try:
                if fn.startswith("acc-"):
                    seq = int(fn[4:])
                    st = self.acc.setdefault(seq, _Acc())
                    st.prep_n, st.acc_n, st.acc_v = pickle.load(
                        open(path, "rb"))
                    self.max_seq = max(self.max_seq, seq)
                elif fn.startswith("dec-"):
                    seq = int(fn[4:])
                    self.values[seq] = pickle.load(open(path, "rb"))
                    self.max_seq = max(self.max_seq, seq)
                elif fn == "meta":
                    rec = pickle.load(open(path, "rb"))
                    if len(rec) >= 3:  # floor-carrying format
                        self.done_seqs, saved_max, floor = rec[:3]
                        self._floor = max(self._floor, floor)
                    else:  # pre-floor meta files
                        self.done_seqs, saved_max = rec
                    self.max_seq = max(self.max_seq, saved_max)
            except (OSError, pickle.PickleError, ValueError, EOFError):
                continue  # torn scratch file: the .tmp never replaced it

    def _gc_files_locked(self, below: int) -> None:
        if not self.persist_dir:
            return
        for fn in os.listdir(self.persist_dir):
            if fn.startswith(("acc-", "dec-")):
                try:
                    if int(fn.split("-", 1)[1]) < below:
                        os.unlink(self._pfile(fn))
                except (ValueError, FileNotFoundError):
                    continue

    # ------------------------------------------------- acceptor (RPCs)

    def set_participation_floor(self, seq: int, force: bool = False) -> None:
        """Amnesiac-rejoin guard: refuse ACCEPTOR participation (prepare/
        accept grants) for instances at or below `seq`.

        An acceptor restarted over an empty persist_dir has forgotten its
        promises; re-granting against them can fork an in-flight instance
        (two decided values).  A rejoining replica that lost its disk
        boots with the floor at FLOOR_ALL (ctor kwarg — no grants at all,
        closing the window before the rejoin protocol runs), then lowers
        it with `force=True` to the highest instance ANY live peer has
        seen, so the healthy majority alone finishes everything that
        might have been in flight — this node still PROPOSES (quorum
        forms from the others), still LEARNS decided values, and
        participates normally above the floor, where it can never have
        promised anything."""
        with self.mu:
            self._floor = seq if force else max(self._floor, seq)
            self._persist_meta_locked()

    def participation_floor(self) -> int:
        """Current amnesia floor (-1 = full participation).  The rejoin
        protocol reads this to learn whether the peer booted quarantined
        (FLOOR_ALL) and still needs the group-horizon lowering."""
        with self.mu:
            return self._floor

    def _rpc_prepare(self, a: dict) -> dict:
        """paxos.go:230-257 — grant iff n > prep_n; reply carries the
        highest accepted (n, v) on grant, highest seen n on reject."""
        seq, n = a["Instance"], a["Proposal"]
        with self.mu:
            self.max_seq = max(self.max_seq, seq)
            if seq <= self._floor:
                return {"Err": _REJECTED, "Instance": seq,
                        "Proposal": 0, "Value": None}
            st = self.acc.setdefault(seq, _Acc())
            if n > st.prep_n:
                st.prep_n = n
                self._persist_acc_locked(seq)  # promise durable before reply
                return {"Err": OK, "Instance": seq, "Proposal": st.acc_n,
                        "Value": st.acc_v}
            return {"Err": _REJECTED, "Instance": seq,
                    "Proposal": st.prep_n, "Value": None}

    def _rpc_accept(self, a: dict) -> dict:
        """paxos.go:287-313 — grant iff n >= prep_n."""
        seq, n, v = a["Instance"], a["Proposal"], a["Value"]
        with self.mu:
            self.max_seq = max(self.max_seq, seq)
            if seq <= self._floor:
                return {"Err": _REJECTED}
            st = self.acc.setdefault(seq, _Acc())
            if n >= st.prep_n:
                st.prep_n = st.acc_n = n
                st.acc_v = v
                self._persist_acc_locked(seq)  # acceptance durable first
                return {"Err": OK}
            return {"Err": _REJECTED}

    def _rpc_decided(self, a: dict) -> dict:
        """paxos.go:334-344 — record the decision; absorb the sender's
        piggybacked Done sequence and shrink below the new Min."""
        with self.mu:
            if a["Instance"] not in self.values:
                self.events.bump("decided")
                dprintf("hostpaxos", "peer %d learned seq %d", self.me,
                        a["Instance"])
                self.values[a["Instance"]] = a["Value"]
                self._persist_decided_locked(a["Instance"])
            else:
                self.values[a["Instance"]] = a["Value"]
            self.max_seq = max(self.max_seq, a["Instance"])
            sender = a["Sender"]
            if 0 <= sender < self.P:
                if a["DoneIns"] > self.done_seqs[sender]:
                    self.done_seqs[sender] = a["DoneIns"]
                    self._persist_meta_locked()
            self._shrink_locked()
        return {}

    # ------------------------------------------------- proposer loop

    def _proposer_worker(self, seq: int, v) -> None:
        """Run one proposal to completion, then drain queued Starts until
        the pool has no more work for this thread."""
        while True:
            try:
                self._propose(seq, v)
            except BaseException:
                # Keep the pool's slot accounting honest even if a proposal
                # dies unexpectedly (e.g. disk-full during persist): hand
                # the slot to queued work or free it, then re-raise.
                with self.mu:
                    if self._prop_q and not self.dead:
                        nxt = self._prop_q.popleft()
                    else:
                        self._prop_threads -= 1
                        raise
                threading.Thread(
                    target=crashsink.guarded(self._proposer_worker,
                                             "hostpeer-proposer"),
                    args=nxt, daemon=True).start()
                raise
            with self.mu:
                if self._prop_q and not self.dead:
                    seq, v = self._prop_q.popleft()
                else:
                    self._prop_threads -= 1
                    return

    def _propose(self, seq: int, v) -> None:
        """paxos.go:122-152 — retry rounds until decided, with randomized
        backoff (ties are systematic in lockstep otherwise)."""
        try:
            max_seen = 0
            while True:
                with self.mu:
                    if self.dead or seq in self.values or \
                            seq < self._min_locked():
                        return
                k = max_seen // self.P + 1
                n = k * self.P + self.me + 1  # globally unique
                self.events.bump("rounds")
                ok, max_seen, v1 = self._phase_prepare(seq, n, max_seen, v)
                if ok and self._phase_accept(seq, n, v1):
                    self.events.bump("proposals_won")
                    self._broadcast_decided(seq, v1)
                    return
                time.sleep(self.backoff * (0.5 + self._rng.random()))
        except Exception:
            if not self.dead:
                raise
        finally:
            with self.mu:
                self._proposing.discard(seq)

    def _call(self, peer: int, method, args, args_schema, reply_schema):
        if peer == self.me:  # self-calls bypass RPC (paxos.go:214-228)
            handler = {"Paxos.Prepare": self._rpc_prepare,
                       "Paxos.Accept": self._rpc_accept,
                       "Paxos.Decided": self._rpc_decided}[method]
            return handler(args)
        self.events.bump("rpc_out")
        if self._pool is not None:
            return self._pool.call(self.peers[peer], method, args_schema,
                                   args, reply_schema)
        return gob_call(self.peers[peer], method, args_schema, args,
                        reply_schema, registry=self._registry, timeout=5.0)

    def _fan(self, method, args, args_schema, reply_schema):
        """One phase's peer fan-out: replies (or None) per peer, in peer
        order.  Sequential by default (the reference's sendPrepareToAll
        shape); concurrent when `parallel_fanout` is enabled."""
        def one(p):
            try:
                return self._call(p, method, args, args_schema, reply_schema)
            except RPCError:
                return None

        if self._fanout is None:
            return [one(p) for p in range(self.P)]
        from concurrent.futures import CancelledError

        try:
            futs = [None if p == self.me else self._fanout.submit(one, p)
                    for p in range(self.P)]
        except RuntimeError:  # executor shut down (kill mid-proposal)
            return [None] * self.P
        out = []
        for p, f in enumerate(futs):
            if p == self.me:
                out.append(one(p))
                continue
            try:
                out.append(f.result())
            except CancelledError:  # kill() cancelled queued fan-out work
                out.append(None)
        return out

    def _phase_prepare(self, seq, n, max_seen, v):
        grants, best_n, best_v = 0, 0, None
        for r in self._fan("Paxos.Prepare",
                           {"Instance": seq, "Proposal": n},
                           wire.PREPARE_ARGS, wire.PREPARE_REPLY):
            if r is None:
                continue
            if r["Err"] == OK:
                grants += 1
                # An acceptance exists iff Proposal > 0 (real proposal
                # numbers start at 1) — keying on the VALUE being non-None
                # would let a legitimately accepted None be overridden,
                # breaking agreement.
                if r["Proposal"] > best_n:
                    best_n, best_v = r["Proposal"], r["Value"]
            else:
                max_seen = max(max_seen, r["Proposal"])
        v1 = best_v if best_n > 0 else v
        return grants * 2 > self.P, max(max_seen, n), v1

    def _phase_accept(self, seq, n, v1) -> bool:
        grants = 0
        for r in self._fan("Paxos.Accept",
                           {"Instance": seq, "Proposal": n, "Value": v1},
                           wire.ACCEPT_ARGS, wire.ACCEPT_REPLY):
            if r is not None and r["Err"] == OK:
                grants += 1
        return grants * 2 > self.P

    def _broadcast_decided(self, seq, v1) -> None:
        """Unlike the reference's fire-and-forget `go call` (paxos.go:
        315-320) — which can strand a learner forever when the one Decided
        message is dropped — delivery is retried until the RPC reply acks
        it.  One immediate pass here; failed peers are handed to a per-peer
        re-delivery thread (at most P such threads exist, regardless of how
        many instances are in flight), which retries with backoff until the
        peer heals or the Done window moves past seq.  Costs nothing on a
        reliable net (one acked send, no thread spawned)."""
        with self.mu:
            done = self.done_seqs[self.me]
        for p in range(self.P):
            args = {"Sender": self.me, "DoneIns": done,
                    "Instance": seq, "Value": v1}
            try:
                self._call(p, "Paxos.Decided", args,
                           wire.DECIDED_ARGS, wire.DECIDED_REPLY)
            except RPCError:
                with self.mu:
                    if self.dead:
                        return
                    self._redeliver_q[p].append((seq, v1))
                    if not self._redeliver_on[p]:
                        self._redeliver_on[p] = True
                        threading.Thread(
                            target=crashsink.guarded(self._redeliver_loop,
                                                     "hostpeer-redeliver"),
                            args=(p,), daemon=True).start()

    def _redeliver_loop(self, p: int) -> None:
        """Drain peer p's queue of unacked Decided messages.  Exits when the
        queue is empty (or only holds forgotten instances), so a healthy
        deployment carries zero re-delivery threads."""
        try:
            self._redeliver_drain(p)
        except BaseException:
            # Unexpected death must not leave the started-flag stuck True
            # (that would silence re-delivery to p forever); the next failed
            # broadcast respawns the drainer.
            with self.mu:
                self._redeliver_on[p] = False
            raise

    def _redeliver_drain(self, p: int) -> None:
        sleep = self.backoff
        while True:
            with self.mu:
                q = self._redeliver_q[p]
                mn = self._min_locked()
                while q and q[0][0] < mn:
                    q.popleft()  # window moved past it: nobody needs it
                if self.dead or not q:
                    self._redeliver_on[p] = False
                    return
                seq, v1 = q[0]
                done = self.done_seqs[self.me]
            try:
                self._call(p, "Paxos.Decided",
                           {"Sender": self.me, "DoneIns": done,
                            "Instance": seq, "Value": v1},
                           wire.DECIDED_ARGS, wire.DECIDED_REPLY)
                with self.mu:
                    if self._redeliver_q[p] and \
                            self._redeliver_q[p][0] == (seq, v1):
                        self._redeliver_q[p].popleft()
                sleep = self.backoff
            except RPCError:
                # Peer still unreachable: back off (caps at 1s) and retry —
                # a partition outliving any fixed cap would otherwise
                # re-strand the learner.
                time.sleep(sleep * (0.5 + self._rng.random()))
                sleep = min(sleep * 1.5, 1.0)

    # ------------------------------------------------- window GC

    def _min_locked(self) -> int:
        return min(self.done_seqs) + 1

    def _shrink_locked(self) -> None:
        """doMemShrink (paxos.go:362-378): drop state below Min — memory
        AND the on-disk window."""
        mn = self._min_locked()
        dropped = False
        for seq in [s for s in self.acc if s < mn]:
            del self.acc[seq]
            dropped = True
        for seq in [s for s in self.values if s < mn]:
            del self.values[seq]
            dropped = True
        if dropped:
            self._gc_files_locked(mn)


def _unwrap(v):
    if isinstance(v, tuple) and len(v) == 2:
        return v[1]
    return v


def make_host_cluster(sockdir: str, npeers: int = 3,
                      registry: Registry | None = None,
                      seed: int | None = None,
                      pooled: bool = False,
                      parallel_fanout: bool = False) -> list[HostPaxosPeer]:
    """Boot npeers decentralized peers on real gob sockets — the
    reference's `Make(peers, me, nil)` per process (paxos/paxos.go:488)."""
    addrs = [f"{sockdir}/px-{i}" for i in range(npeers)]
    return [HostPaxosPeer(addrs, i, registry=registry,
                          seed=None if seed is None else seed + i,
                          pooled=pooled, parallel_fanout=parallel_fanout)
            for i in range(npeers)]
