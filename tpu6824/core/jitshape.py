"""jitshape — the shared jit-shape discipline for host→device handoffs.

Every jitted entry point in this tree takes FIXED-shape operands: the
fabric's injection path pads its (rows, cells, vids, seqs) columns to
one of two bucket sizes, and the devapply kernel (ISSUE 16) pads its
per-drain op columns to a geometric bucket ladder.  Variable-length
batches hitting a jit boundary with their natural length would compile
one executable per length — the jitguard zero-steady-state-recompile
contract exists precisely because that failure mode is silent and slow.

This module is that discipline, shared: pick a bucket from a fixed
ladder (`bucket_for`), pad int32 columns into it (`pad_i32`).  The
ladder is finite by construction, so the set of compiled signatures is
finite; callers chunk batches larger than the top rung through repeated
max-size calls (the fabric's chunked-injection pattern).

Kept stdlib+numpy at import; jax is imported lazily so analysis tooling
can import the module without a backend.
"""

from __future__ import annotations

import numpy as np


def bucket_ladder(lo: int, hi: int) -> tuple[int, ...]:
    """The geometric (power-of-two) bucket ladder from `lo` to `hi`
    inclusive — the full set of pad sizes a caller may produce, i.e.
    the full set of jit signatures it can ever compile."""
    lo = max(1, int(lo))
    hi = max(lo, int(hi))
    out = []
    b = 1
    while b < lo:
        b <<= 1
    while b < hi:
        out.append(b)
        b <<= 1
    out.append(b)
    return tuple(out)


def bucket_for(n: int, ladder: tuple[int, ...]) -> int:
    """Smallest rung holding `n` ops; the top rung for anything larger
    (the caller chunks — see the fabric's injection loop)."""
    for b in ladder:
        if n <= b:
            return b
    return ladder[-1]


# The group-axis ladder (meshfab, ISSUE 17): when the fabric's G groups
# shard over a mesh's 'g' axis, every compiled signature carries the
# PER-SHARD group count G/n — so G itself must land on a rung·shards
# product or each distinct service topology would compile its own
# executables.  Capped at 1024 per shard: the paper's north-star shape
# (1024 groups on v5e-8) is 128/shard, well inside.
GROUP_LADDER = bucket_ladder(1, 1024)


def shard_groups(n: int, shards: int,
                 ladder: tuple[int, ...] = GROUP_LADDER) -> int:
    """Total group count to ALLOCATE so `n` live groups shard evenly
    over `shards` mesh slices with a ladder-stable per-shard count:
    ceil(n/shards) rounded up to a rung, times shards.  The padding
    groups are idle lanes (never started, never fed) — the price of a
    finite compiled-signature set on the sharded real path.  With
    shards=1 this is the identity for any n (single-device fabrics
    keep their exact shapes)."""
    shards = max(1, int(shards))
    n = max(1, int(n))
    if shards == 1:
        return n
    per = bucket_for((n + shards - 1) // shards, ladder)
    return per * shards


def pad_i32(arr, fill: int, bucket: int):
    """Pad (or create) an int32 column of exactly `bucket` slots, the
    tail filled with `fill` (a guard row index, a NOP kind — whatever
    the kernel treats as inert).  Returns a device array.

    This is the fabric's `_pad_i32` (PR 4), extracted verbatim so the
    decide-feed → apply-kernel handoff (ISSUE 16) and the injection
    path share one pad implementation and one shape discipline.
    """
    import jax.numpy as jnp

    out = np.full(bucket, fill, np.int32)
    n = 0 if arr is None else len(arr)
    if n:
        out[:n] = arr
    return jnp.asarray(out)
