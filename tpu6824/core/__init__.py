from tpu6824.core.kernel import (  # noqa: F401
    PaxosState, StepIO, apply_starts, init_state, paxos_step,
    paxos_step_reliable,
)
from tpu6824.core.pallas_kernel import (  # noqa: F401
    LaneState, apply_starts_lane, from_lane_state, get_step,
    paxos_step_lanes, paxos_step_pallas, resolve_impl, to_lane_state,
)
from tpu6824.core.hostpeer import HostPaxosPeer, make_host_cluster  # noqa: F401
