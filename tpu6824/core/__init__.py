from tpu6824.core.kernel import PaxosState, init_state, paxos_step, apply_starts  # noqa: F401
