"""The Paxos cell state machine as a single jittable tensor kernel.

Capability parity target: the multi-instance single-decree Paxos library of the
reference (`paxos/paxos.go`) — `Start/Status/Done/Min/Max` semantics, majority
quorums, safety under partitions and message loss, the Done/Min garbage
collection protocol with done-value piggybacking (`paxos/rpc.go:74-80`,
`paxos/paxos.go:328,339-341`).

Architecture (deliberately NOT a translation).  The reference runs one
goroutine per in-flight proposal doing three sequential RPC fan-outs
(`paxos/paxos.go:122-152` propose; `:161-190` sendPrepareToAll; `:259-271`
sendAcceptToAll; `:315-320` sendDecidedToAll).  Here the *entire* universe of
consensus cells — `G` independent Paxos groups × `I` instance slots × `P`
peers — advances in one globally-clocked `paxos_step`:

  - every active proposer runs prepare, accept and decide *phases* within one
    step, as masked exchanges over the peer axis;
  - an acceptor processes all of a phase's incoming messages at once, with the
    per-step serialization rule that makes the lockstep schedule equivalent to
    a legal sequential interleaving (all prepares of the step ordered before
    all accepts; at most one accept wins per acceptor per step);
  - majority checks are integer sums over the peer axis (psum over ICI when P
    is sharded across devices);
  - the lossy/partitioned network of the reference's test harness
    (`paxos/paxos.go:528-544` unreliable accept loop; socket-link partitions
    `paxos/test_test.go:712-751`) becomes per-edge boolean delivery masks and
    per-step Bernoulli drops from a counter PRNG — deterministic under seed.

Proposal numbers are globally unique by construction: n = k·P + p + 1 for peer
p, round k (fixes the reference defect where `chooseProposalNumber` =
highest-seen+1 can collide across peers, `paxos/paxos.go:154-159`).

Values never touch the device: the host interns payloads and the kernel agrees
on int32 value *ids* (-1 = none).
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp

I32 = jnp.int32
NO_VAL = -1  # value-id sentinel: no value

# ---------------------------------------------------------------- kernelscope
# Device-resident protocol telemetry: per-group event counts accumulated
# INSIDE the consensus round (both engines) and read back only on the
# existing once-per-dispatch summary — zero additional host round-trips.
# Field order is the contract between the XLA round, the Pallas packed
# event word (pallas_kernel._unpack_proto), the fabric's host mirror, and
# stats()["protocol"] — append only, never reorder.
PROTO_FIELDS = (
    "prepare_attempts",   # proposer prepare rounds run (1/active proposer/step)
    "prepare_rejects",    # delivered prepares refused (n <= promised)
    "accept_rejects",     # delivered accepts that did not take (refused or
                          # lost the per-step duel serialization)
    "quorum_failures",    # phase majorities missed (prepare + accept)
    "restarts",           # proposers still undecided after a full round
                          # (they re-prepare at a higher n next step)
    "decides",            # decide events — once per decided instance tenancy
                          # (a late proposer re-deciding an already-decided
                          # instance under partitions counts again; monotone)
    "fast_path_decides",  # decides won at the proposer's FIRST proposal
                          # number (n <= 2P): the 1-round fast-path cohort
                          # the flexible-quorum variants target
)
NPROTO = len(PROTO_FIELDS)
# Packed per-cell event word (the Pallas engine's proto output): field k
# occupies PROTO_PACK_BITS[k] bits at PROTO_PACK_SHIFT[k].  Widths bound
# the per-STEP per-cell event counts: reject counts reach P (so P <= 15),
# quorum failures reach 2 (prepare + accept), everything else is 0/1.
# 14 bits total — one int32 word per cell carries the whole step.
PROTO_PACK_BITS = (1, 4, 4, 2, 1, 1, 1)
PROTO_PACK_SHIFT = tuple(
    sum(PROTO_PACK_BITS[:k]) for k in range(NPROTO))
# Kill switch for overhead A/B measurement (TUNING round 11): with
# TPU6824_PROTO=0 the round returns all-zero counters (a trace-time
# constant XLA folds away), the fabric omits them from the summary
# readback, and the Pallas kernel skips the event-word output entirely.
PROTO_ENABLED = os.environ.get("TPU6824_PROTO", "1") not in ("0", "false")


class PaxosState(NamedTuple):
    """Device-resident consensus state.

    Shapes: G = groups, I = instance slots, P = peers.
    """

    # Acceptor state per cell (paxos/paxos.go:75-79 State{prepProposal,
    # accpProposal, accpValue} — here n_promised / n_accepted / value id):
    np_: jnp.ndarray      # (G, I, P) i32  highest proposal promised; 0 = none
    na: jnp.ndarray       # (G, I, P) i32  highest proposal accepted; 0 = none
    va: jnp.ndarray       # (G, I, P) i32  accepted value id; NO_VAL = none
    # Learner state:
    decided: jnp.ndarray  # (G, I, P) i32  decided value id per peer; NO_VAL = undecided
    # Proposer state (the reference's free-running `propose` goroutine,
    # paxos/paxos.go:122-152, flattened into per-cell registers):
    active: jnp.ndarray   # (G, I, P) bool peer is proposing on this instance
    propv: jnp.ndarray    # (G, I, P) i32  value id the proposer wants
    maxseen: jnp.ndarray  # (G, I, P) i32  highest proposal number observed
    # Done/Min GC protocol (paxos/paxos.go:352-425):
    done_view: jnp.ndarray  # (G, P, P) i32 [g, p, q] = p's knowledge of q's done seq


def init_state(G: int, I: int, P: int) -> PaxosState:
    # NB: distinct buffers per field — paxos_step donates its input state, and
    # aliased buffers would be donated twice.
    return PaxosState(
        np_=jnp.zeros((G, I, P), I32),
        na=jnp.zeros((G, I, P), I32),
        va=jnp.full((G, I, P), NO_VAL, I32),
        decided=jnp.full((G, I, P), NO_VAL, I32),
        active=jnp.zeros((G, I, P), bool),
        propv=jnp.full((G, I, P), NO_VAL, I32),
        maxseen=jnp.zeros((G, I, P), I32),
        done_view=jnp.full((G, P, P), -1, I32),
    )


class StepIO(NamedTuple):
    """Per-step observable outputs the host mirrors after each step."""

    decided: jnp.ndarray    # (G, I, P) i32
    done_view: jnp.ndarray  # (G, P, P) i32
    touched: jnp.ndarray    # (G, I, P) bool — peer participated in the slot (for Max())
    msgs: jnp.ndarray       # () i32 — remote messages sent this step (RPC-count analog)
    proto: jnp.ndarray      # (G, NPROTO) i32 — per-group protocol event
                            # counts this step (kernelscope; see PROTO_FIELDS)


def _edge_masks(key, shape, link, drop, eye):
    """One phase's delivery mask: static connectivity AND'd with a per-edge
    Bernoulli keep.  `drop` is (G, P, P) f32 — per-edge drop probability,
    derived host-side from per-server unreliable flags (the reference's
    accept-loop coin flips, paxos/paxos.go:528-544, are per *receiving*
    server).  Self edges always deliver (reference self-calls are plain
    function calls, never RPCs: paxos/paxos.go:214-228)."""
    if len(shape) == 4:
        d = drop[:, None, :, :]
    else:
        d = drop
    keep = jax.random.uniform(key, shape) >= d
    return (keep | eye) & link


@functools.partial(jax.jit, donate_argnums=(0,))
def paxos_step(
    state: PaxosState,
    link: jnp.ndarray,       # (G, P, P) bool — [g, src, dst] connectivity (partitions/deafness)
    done: jnp.ndarray,       # (G, P) i32 — host-owned per-peer Done() high-water marks
    key: jnp.ndarray,        # PRNG key for this step
    drop_req: jnp.ndarray,   # (G, P, P) f32 — request drop prob per edge (unreliable, ~0.10)
    drop_rep: jnp.ndarray,   # (G, P, P) f32 — reply drop prob per edge (executed-but-unacked, ~0.20)
) -> tuple[PaxosState, StepIO]:
    """Advance every consensus cell by one prepare→accept→decide round."""
    G, I, P = state.np_.shape
    eye = jnp.eye(P, dtype=bool)
    shape4 = (G, I, P, P)
    k1, k2, k3, k1r, k2r, k3r, khb = jax.random.split(key, 7)

    L = (link | eye)[:, None, :, :]  # (G, 1, P, P); self always connected
    Mreq1 = _edge_masks(k1, shape4, L, drop_req, eye)
    Mreq2 = _edge_masks(k2, shape4, L, drop_req, eye)
    Mreq3 = _edge_masks(k3, shape4, L, drop_req, eye)
    Mrep1 = _edge_masks(k1r, shape4, L, drop_rep, eye)
    Mrep2 = _edge_masks(k2r, shape4, L, drop_rep, eye)
    hb = _edge_masks(khb, (G, P, P), (link | eye), drop_req, eye)
    return _paxos_round(state, done, eye,
                        Mreq1, Mreq2, Mreq3, Mrep1, Mrep2, hb)


@functools.partial(jax.jit, donate_argnums=(0,))
def paxos_step_reliable(
    state: PaxosState,
    link: jnp.ndarray,       # (G, P, P) bool
    done: jnp.ndarray,       # (G, P) i32
) -> tuple[PaxosState, StepIO]:
    """`paxos_step` specialized to a lossless network: every delivery mask
    is the (static) connectivity itself, so no Bernoulli draws are
    generated or materialized — at bench shape that removes five
    `(G, I, P, P)` uniform draws per step.  Bit-identical to
    `paxos_step(..., drop_req=0, drop_rep=0)` under any key (at zero drop
    the draws never affect a mask)."""
    G, I, P = state.np_.shape
    eye = jnp.eye(P, dtype=bool)
    L = jnp.broadcast_to((link | eye)[:, None, :, :], (G, I, P, P))
    return _paxos_round(state, done, eye, L, L, L, L, L, link | eye)


def _merge_scan_io(state: PaxosState, touched_k, msgs_k, proto_k) -> StepIO:
    """Fold a scan's per-round (touched, msgs, proto) stacks into the one
    merged StepIO a multi-step dispatch reports: decided/done_view are the
    final round's (both monotone within a dispatch — decided is sticky per
    tenancy, done_view max-accumulates), touched is the union (Max() needs
    every slot any round touched), msgs and the protocol event counts are
    dispatch totals."""
    return StepIO(decided=state.decided, done_view=state.done_view,
                  touched=touched_k.any(axis=0),
                  msgs=msgs_k.sum().astype(I32),
                  proto=proto_k.sum(axis=0))


@functools.partial(jax.jit, donate_argnums=(0,))
def paxos_multi_step(
    state: PaxosState,
    link: jnp.ndarray,       # (G, P, P) bool
    done: jnp.ndarray,       # (G, P) i32
    keys: jnp.ndarray,       # (K,) PRNG keys, one per fused micro-step
    drop_req: jnp.ndarray,   # (G, P, P) f32
    drop_rep: jnp.ndarray,   # (G, P, P) f32
) -> tuple[PaxosState, StepIO]:
    """K fused `paxos_step` rounds in ONE device dispatch (lax.scan over
    the per-step keys): bit-identical to K sequential calls under the same
    key sequence, but the host pays one dispatch + one readback per K
    steps — the pipelined-clock amortization (ISSUE 1) on the full-io
    path."""

    def body(st, key):
        st2, io = paxos_step(st, link, done, key, drop_req, drop_rep)
        return st2, (io.touched, io.msgs, io.proto)

    st, (touched_k, msgs_k, proto_k) = jax.lax.scan(body, state, keys)
    return st, _merge_scan_io(st, touched_k, msgs_k, proto_k)


@functools.partial(jax.jit, static_argnums=(3,), donate_argnums=(0,))
def paxos_multi_step_reliable(
    state: PaxosState,
    link: jnp.ndarray,       # (G, P, P) bool
    done: jnp.ndarray,       # (G, P) i32
    nsteps: int,
) -> tuple[PaxosState, StepIO]:
    """`paxos_multi_step` on the lossless fast path: no keys, no Bernoulli
    draws, `nsteps` fused rounds per dispatch."""

    def body(st, _):
        st2, io = paxos_step_reliable(st, link, done)
        return st2, (io.touched, io.msgs, io.proto)

    st, (touched_k, msgs_k, proto_k) = jax.lax.scan(body, state, None,
                                                    length=nsteps)
    return st, _merge_scan_io(st, touched_k, msgs_k, proto_k)


def _paxos_round(state, done, eye, Mreq1, Mreq2, Mreq3, Mrep1, Mrep2, hb):
    """One prepare→accept→decide round given materialized delivery masks
    (Mreq*/Mrep* are (G, I, P, P); hb is the (G, P, P) heartbeat mask)."""
    G, I, P = state.np_.shape
    pid = jnp.arange(P, dtype=I32)
    # Unique, ever-growing proposal number: smallest n ≡ p+1 (mod P) with
    # n > maxseen.  maxseen always includes the proposer's own acceptor promise
    # from its previous round (self reply is never dropped), so n strictly
    # increases every step a proposer stays active — no self-livelock.
    n_prop = (state.maxseen // P + 1) * P + pid + 1  # (G, I, P)

    np_pre, na_pre, va_pre = state.np_, state.na, state.va

    # ---- Phase 1: PREPARE (paxos/paxos.go:161-190 send; :244-257 handler) ----
    send1 = state.active
    D1 = Mreq1 & send1[..., :, None]  # [g,i,p(src),q(dst)] delivered
    grant = D1 & (n_prop[..., :, None] > np_pre[..., None, :])
    np_post1 = jnp.maximum(
        np_pre, jnp.max(jnp.where(D1, n_prop[..., :, None], 0), axis=-2)
    )
    R1 = grant & Mrep1  # promise made it back to the proposer
    cnt1 = R1.sum(-1).astype(I32)
    maj1 = cnt1 * 2 > P
    # Adopt the value of the highest accepted proposal among promisers
    # (paxos/paxos.go:166-189): else keep our own propv.
    na_rep = jnp.where(R1, na_pre[..., None, :], -1)  # (G,I,P,q)
    best_q = jnp.argmax(na_rep, axis=-1)
    best_na = jnp.take_along_axis(na_rep, best_q[..., None], axis=-1)[..., 0]
    va_b = jnp.broadcast_to(va_pre[..., None, :], na_rep.shape)
    va_best = jnp.take_along_axis(va_b, best_q[..., None], axis=-1)[..., 0]
    v1 = jnp.where(best_na > 0, va_best, state.propv)
    # Rejections teach the proposer higher numbers (the reference learns them
    # through its own acceptor state; we return the acceptor's promise).
    rep1 = jnp.where(D1 & Mrep1, np_post1[..., None, :], 0)
    maxseen = jnp.maximum(state.maxseen, rep1.max(-1))

    # ---- Phase 2: ACCEPT (paxos/paxos.go:259-271 send; :300-313 handler) ----
    send2 = send1 & maj1
    D2 = Mreq2 & send2[..., :, None]
    ok2 = D2 & (n_prop[..., :, None] >= np_post1[..., None, :])
    # Per-step serialization: an acceptor accepts at most ONE proposal per
    # step — the highest eligible n (unique per proposer).  This makes the
    # lockstep round equivalent to processing the step's prepares before its
    # accepts in a sequential schedule, preserving Paxos safety.
    win_n = jnp.max(jnp.where(ok2, n_prop[..., :, None], 0), axis=-2)  # (G,I,q)
    win = ok2 & (n_prop[..., :, None] == win_n[..., None, :])
    any_acc = win_n > 0
    np_post2 = jnp.maximum(np_post1, win_n)
    na_new = jnp.where(any_acc, win_n, na_pre)
    va_win = jnp.sum(jnp.where(win, v1[..., :, None], 0), axis=-2)
    va_new = jnp.where(any_acc, va_win, va_pre)
    R2 = win & Mrep2
    cnt2 = R2.sum(-1).astype(I32)
    maj2 = cnt2 * 2 > P
    rep2 = jnp.where(D2 & Mrep2, np_post2[..., None, :], 0)
    maxseen = jnp.maximum(maxseen, rep2.max(-1))

    # ---- Phase 3: DECIDE broadcast + learned-value gossip ----
    # (paxos/paxos.go:315-332 sendDecidedToAll; gossip keeps re-broadcasting
    # until every peer has learned, replacing the reference pattern where a
    # missed Decided is repaired only by a later proposal.)
    decider = send2 & maj2  # at most one per (g, i): accept winners are exclusive
    dv = jnp.where(decider, v1, state.decided)
    all_dec = (state.decided >= 0).all(-1)  # (G, I): stop gossip when everyone knows
    send3 = decider | ((state.decided >= 0) & ~all_dec[..., None])
    D3 = Mreq3 & send3[..., :, None]
    dec_in = jnp.max(jnp.where(D3, dv[..., :, None], NO_VAL), axis=-2)
    decided_new = jnp.where(state.decided >= 0, state.decided, dec_in)

    # ---- Done piggyback + heartbeat (paxos/rpc.go:74-80) ----
    # p learns q's done high-water mark whenever any message q→p lands this
    # step; an additional once-per-step heartbeat over live links replaces the
    # reference's piggyback-on-next-instance pattern.
    anymsg = (D1 | D2 | D3).any(axis=1)  # (G, src, dst)
    gotmsg = jnp.swapaxes(anymsg | hb, -1, -2)  # [g, dst(p), src(q)]
    done_view = jnp.maximum(state.done_view, jnp.where(gotmsg, done[:, None, :], -1))
    # A peer always knows its own done value:
    done_view = jnp.maximum(done_view, jnp.where(eye[None], done[:, None, :], -1))

    # ---- Proposer bookkeeping ----
    active_new = state.active & (decided_new < 0)

    # Remote-message count (self edges excluded) — the RPC-budget analog of
    # paxos/test_test.go:503-573.
    offdiag = ~eye[None, None]
    msgs = (
        (D1 & offdiag).sum() + (D2 & offdiag).sum() + (D3 & offdiag).sum()
    ).astype(I32)

    # kernelscope protocol counters (PROTO_FIELDS order): per-group event
    # sums over booleans the round already materialized — the Pallas
    # kernel packs the identical per-cell events (pallas_kernel
    # _round_kernel proto path), so the two engines report bit-identical
    # totals under the same delivery masks.
    def _gsum(x):
        return x.sum(axis=tuple(range(1, x.ndim))).astype(I32)

    if PROTO_ENABLED:
        proto = jnp.stack([
            _gsum(send1),
            _gsum(D1 & ~grant),
            _gsum(D2 & ~win),
            _gsum(send1 & ~maj1) + _gsum(send2 & ~maj2),
            _gsum(send1 & (decided_new < 0)),
            _gsum(decider),
            _gsum(decider & (n_prop <= 2 * P)),
        ], axis=-1)
    else:
        # Trace-time constant: consumers that don't read it cost nothing,
        # and XLA folds the zeros out of any summary that does.
        proto = jnp.zeros((G, NPROTO), I32)

    new_state = PaxosState(
        np_=np_post2,
        na=na_new,
        va=va_new,
        decided=decided_new,
        active=active_new,
        propv=state.propv,
        maxseen=maxseen,
        done_view=done_view,
    )
    touched = (np_post2 > 0) | (na_new > 0) | (decided_new >= 0) | active_new
    io = StepIO(decided=decided_new, done_view=done_view, touched=touched,
                msgs=msgs, proto=proto)
    return new_state, io


def apply_starts_compact(
    state: PaxosState,
    slot_seq: jnp.ndarray,    # (G, I) i32 — device mirror of the host slot map
    reset_rows: jnp.ndarray,  # (R,) i32 — flat g*I+slot rows to recycle; pad = G*I
    cells: jnp.ndarray,       # (N,) i32 — flat (g*I+slot)*P+p cells to arm; pad = G*I*P
    vids: jnp.ndarray,        # (N,) i32 — proposed value ids, aligned with cells
    seqs: jnp.ndarray,        # (N,) i32 — absolute seq per start, aligned with cells
) -> tuple[PaxosState, jnp.ndarray]:
    """Scatter-based `apply_starts`: O(ops) injection instead of dense
    (G, I) reset + (G, I, P) arm tensors — the host→device half of keeping
    the per-step cost O(active cells), not O(G·I·P) (the compact-IO fix
    for the full-mirror wall; `Status` stays a host-mirror read the way
    the reference's is a local map read, paxos/paxos.go:434-447).

    Padding uses positive out-of-bounds indices with scatter mode='drop'.
    Semantics match `apply_starts` exactly: resets first, then arms, with
    duplicate cells pre-deduplicated by the host (last write wins, the
    dense scatter's behavior).  Also maintains the device-resident
    slot→seq map that the step summary uses for Max() bookkeeping.

    Not jitted here: callers fuse it into their step jit so the
    pre-round `decided` is visible to the newly-decided diff without an
    extra device round trip.
    """
    G, I, P = state.np_.shape
    nrows = G * I

    def wipe(a, fill):
        flat = a.reshape(nrows, P)
        return flat.at[reset_rows].set(fill, mode="drop").reshape(G, I, P)

    np_ = wipe(state.np_, 0)
    na = wipe(state.na, 0)
    va = wipe(state.va, NO_VAL)
    decided = wipe(state.decided, NO_VAL)
    active = wipe(state.active, False)
    propv = wipe(state.propv, NO_VAL)
    maxseen = wipe(state.maxseen, 0)
    slot_flat = slot_seq.reshape(nrows)
    slot_flat = slot_flat.at[reset_rows].set(-1, mode="drop")
    slot_flat = slot_flat.at[cells // P].set(seqs, mode="drop")

    ncells = nrows * P
    safe = jnp.minimum(cells, ncells - 1)  # clamp pads for the gathers
    dec_flat = decided.reshape(ncells)
    act_flat = active.reshape(ncells)
    prop_flat = propv.reshape(ncells)
    # active |= start & undecided; propv first-set (see apply_starts).
    new_act = act_flat[safe] | (dec_flat[safe] < 0)
    new_prop = jnp.where(prop_flat[safe] < 0, vids, prop_flat[safe])
    act_flat = act_flat.at[cells].set(new_act, mode="drop")
    prop_flat = prop_flat.at[cells].set(new_prop, mode="drop")
    return (
        PaxosState(
            np_=np_, na=na, va=va, decided=decided,
            active=act_flat.reshape(G, I, P), propv=prop_flat.reshape(G, I, P),
            maxseen=maxseen, done_view=state.done_view,
        ),
        slot_flat.reshape(G, I),
    )


@jax.jit
def apply_starts(
    state: PaxosState,
    reset: jnp.ndarray,         # (G, I) bool — recycle these slots (window GC)
    start_active: jnp.ndarray,  # (G, I, P) bool — peer begins proposing
    start_val: jnp.ndarray,     # (G, I, P) i32 — proposed value id
) -> PaxosState:
    """Host→device op injection: recycle forgotten slots, then arm proposers.

    The reference's `Start(seq, v)` spawns a goroutine (`paxos/paxos.go:99-109`);
    here it flips the cell's proposer registers.  Slot recycling implements the
    memory reclamation `doMemShrink` performs once Min advances
    (`paxos/paxos.go:362-378`).
    """
    r3 = reset[..., None]

    def rz(a, v):
        return jnp.where(r3, v, a)

    np_ = rz(state.np_, 0)
    na = rz(state.na, 0)
    va = rz(state.va, NO_VAL)
    decided = rz(state.decided, NO_VAL)
    active = jnp.where(r3, False, state.active)
    propv = rz(state.propv, NO_VAL)
    maxseen = rz(state.maxseen, 0)

    active = active | (start_active & (decided < 0))
    # A re-Start on an instance this peer already has a value staged for keeps
    # the original value (semantics only require *some* started value can win;
    # first-set is deterministic).  Post-reset propv is NO_VAL, so recycled
    # slots always take the new value.
    propv = jnp.where(start_active & (propv < 0), start_val, propv)
    return PaxosState(
        np_=np_, na=na, va=va, decided=decided, active=active,
        propv=propv, maxseen=maxseen, done_view=state.done_view,
    )
