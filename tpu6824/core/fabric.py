"""PaxosFabric — host runtime that owns the device state and the step clock.

This replaces the reference's per-process runtime: socket listeners
(`paxos/paxos.go:524-552`), the unreliable accept loop (`:528-544`), and the
test harness's filesystem network surgery (`paxos/test_test.go:712-751`
partitions, `:194-195` deafness) all become host-owned mask/probability arrays
fed into the jitted `paxos_step` kernel.  One fabric hosts G independent Paxos
groups × I instance slots × P peers and advances them all in lockstep.

Host↔device contract (designed to avoid per-op round-trips — SURVEY §7 "Host↔
device chatter"):
  - API calls (`start/status/done/...`) only touch host mirrors and pending-op
    queues under a lock; they never talk to the device.
  - A single clock thread drains queues into `apply_starts`, runs
    `paxos_step`, and refreshes the mirrors — one device round-trip per step
    for the whole universe of cells, regardless of op rate.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from tpu6824.core.intern import Intern
from tpu6824.core.kernel import NO_VAL, apply_starts, init_state
from tpu6824.utils.trace import EventLog, dprintf

# Reference unreliable-network rates: 10% of requests dropped before
# processing, a further ~20% processed but the reply discarded
# (paxos/paxos.go:528-544).
UNRELIABLE_REQ_DROP = 0.10
UNRELIABLE_REP_DROP = 0.20

# How many per-step PRNG subkeys to pre-split at once (see _next_key_locked).
_KEY_BATCH = 256

# Immediate-value tagging: small non-negative ints ride the device arrays
# AS their value id (tagged with bit 30) — no intern store round-trip, no
# refcount, nothing to GC.  The moral analog of tagged immediates in a
# runtime: the device only ever agrees on int32 ids either way (values
# never touch the TPU, kernel.py:33-34); for int payloads the id can BE
# the payload.  Interned ids grow from 0 and are bounded by the live
# window (G·I values at most), so the spaces cannot collide.
IMM_BASE = 1 << 30


class WindowFullError(RuntimeError):
    """No free instance slot: callers are outrunning Done()/Min() GC.

    The reference has no such limit because it leaks memory instead
    (`paxos/paxos.go` keeps every un-GC'd instance in a map); the fixed
    window is what makes the device arrays bounded (SURVEY §5 long-context
    note).

    `index` is set when raised from `start_many`: ops[:index] were fully
    applied, ops[index:] were not.  Resuming from `index` once GC frees a
    slot is the precise retry; re-submitting from 0 is also SAFE (Start is
    idempotent for an undecided seq) but re-queues the prefix — duplicate
    pending entries and intern refs that live until GC."""

    def __init__(self, msg: str, index: int | None = None):
        super().__init__(msg)
        self.index = index


class PaxosFabric:
    def __init__(
        self,
        ngroups: int = 1,
        npeers: int = 3,
        ninstances: int = 64,
        seed: int = 0,
        auto_step: bool = False,
        step_sleep: float = 0.0,
        kernel: str | None = None,
        unreliable_req_drop: float = UNRELIABLE_REQ_DROP,
        unreliable_rep_drop: float = UNRELIABLE_REP_DROP,
    ):
        from tpu6824.core.pallas_kernel import get_step, resolve_impl

        self._step_fn = get_step(kernel)
        self._kernel_req = kernel  # as requested (checkpoint/restore)
        # On the XLA path, steps with no unreliable server skip Bernoulli
        # mask generation entirely (paxos_step_reliable — bit-identical at
        # drop=0, works under partitioned links).  The Pallas path keeps its
        # own mask handling (packed bitplanes / maskless lane fast path).
        self._reliable_ok = resolve_impl(kernel) == "xla"
        self._req_drop = unreliable_req_drop
        self._rep_drop = unreliable_rep_drop
        self.G, self.I, self.P = ngroups, ninstances, npeers
        G, I, P = self.G, self.I, self.P
        self._state = init_state(G, I, P)
        self._key = jax.random.key(seed)
        self._key_buf: list = []

        # Host-owned network condition (device inputs):
        self._link = np.ones((G, P, P), bool)
        self._link_dev = None  # device copy; None = stale (net changed)
        self._unreliable = np.zeros((G, P), bool)  # per receiving server
        self._done = np.full((G, P), -1, np.int32)
        self._pmin_i32 = np.empty((G, P), np.int32)  # scratch for min-reduce

        # Host mirrors of device outputs (device dtype — int32 — so the
        # per-step refresh is a straight copy, no astype pass):
        self.m_decided = np.full((G, I, P), NO_VAL, np.int32)
        self.m_done_view = np.full((G, P, P), -1, np.int32)
        # Min() cache: _peer_min[g, p] = 1 + min_q done_view[g, p, q],
        # refreshed vectorized once per step and on done() — so the hot API
        # calls (start/status, O(ops/sec) of them) read a scalar instead of
        # reducing a row each (the O(G) bookkeeping wall, VERDICT r3 weak #2).
        self._peer_min = np.zeros((G, P), np.int64)
        self._max_seq = np.full((G, P), -1, np.int64)  # Max() running high-water
        # Observability (SURVEY §5 build note): per-step event log + counters.
        # The EventLog counters are the single source of truth for steps/msgs;
        # steps_total/msgs_total below are read-through views.
        self.events = EventLog()
        self._decided_cells = 0  # running count of decided (g, i, p) cells

        # Slot management (host only): which absolute seq lives in each slot.
        self._slot_seq = np.full((G, I), -1, np.int64)
        self._seq2slot: list[dict[int, int]] = [dict() for _ in range(G)]
        # O(1) allocation: per-group LIFO freelist (invariant: slot is listed
        # iff _slot_seq[g, slot] == -1).  A freed slot may carry a pending
        # reset; that is safe to hand out because apply_starts applies resets
        # before starts within the same step.
        self._free: list[list[int]] = [
            list(range(I - 1, -1, -1)) for _ in range(G)
        ]
        self._slot_vids: list[list[list[int]]] = [
            [[] for _ in range(I)] for _ in range(G)
        ]  # interned ids referenced by each slot (for GC decref)

        self.intern = Intern()

        self._lock = threading.RLock()
        self._pending_starts: list[tuple[int, int, int, int, int]] = []  # (g, slot, p, vid, seq)
        self._pending_resets: list[tuple[int, int]] = []  # (g, slot)
        self._dead = np.zeros((G, P), bool)

        self._running = False
        self._thread: threading.Thread | None = None
        self._step_sleep = step_sleep
        self._stepped = threading.Condition(self._lock)
        if auto_step:
            self.start_clock()

    # ------------------------------------------------------------------ clock

    def start_clock(self):
        with self._lock:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(target=self._clock_loop, daemon=True)
        self._thread.start()

    def stop_clock(self):
        with self._lock:
            self._running = False
        if self._thread:
            self._thread.join()
            self._thread = None

    def _clock_loop(self):
        while True:
            with self._lock:
                if not self._running:
                    return
            self.step()
            if self._step_sleep:
                time.sleep(self._step_sleep)

    def step(self, n: int = 1):
        """Advance the whole fabric by n kernel steps (callable from the clock
        thread or directly in deterministic tests)."""
        for _ in range(n):
            self._step_once()

    def _next_key_locked(self):
        # Amortized PRNG: one split call per _KEY_BATCH steps instead of one
        # per step (jax.random.split is a host round-trip).
        if not self._key_buf:
            keys = jax.random.split(self._key, _KEY_BATCH + 1)
            self._key = keys[0]
            self._key_buf = list(keys[1:])
        return self._key_buf.pop()

    def _step_once(self):
        with self._lock:
            starts = self._pending_starts
            resets = self._pending_resets
            self._pending_starts = []
            self._pending_resets = []
            s_arr = r_arr = None
            if starts:
                s_arr = np.asarray(starts, dtype=np.int64)  # (N, 5) cols: g, slot, p, vid, seq
                # Drop starts whose slot was GC-recycled while they were
                # queued (the slot no longer maps to their seq): arming the
                # freed slot would run a ghost round with a value id whose
                # intern ref the GC already dropped.
                keep = (self._slot_seq[s_arr[:, 0], s_arr[:, 1]]
                        == s_arr[:, 4])
                s_arr = s_arr[keep] if not keep.all() else s_arr
            if resets:
                r_arr = np.asarray(resets, dtype=np.int64)  # (N, 2)
            if self._link_dev is None:
                self._link_dev = jnp.asarray(self._link)
            link = self._link_dev
            done = jnp.asarray(self._done)
            any_unrel = bool(self._unreliable.any())
            reliable = self._reliable_ok and not any_unrel
            if not reliable:
                # Per-edge drop probabilities from per-server unreliable
                # flags: the *destination* server's accept loop drops.
                unrel = self._unreliable.astype(np.float32)  # (G, P)
                e = np.broadcast_to(
                    unrel[:, None, :], (self.G, self.P, self.P))
                drop_req = jnp.asarray(e * self._req_drop)
                drop_rep = jnp.asarray(e * self._rep_drop)
                sub = self._next_key_locked()

        state = self._state
        if s_arr is not None or r_arr is not None:
            reset = np.zeros((self.G, self.I), bool)
            sa = np.zeros((self.G, self.I, self.P), bool)
            sv = np.full((self.G, self.I, self.P), NO_VAL, np.int32)
            if r_arr is not None:
                reset[r_arr[:, 0], r_arr[:, 1]] = True
            if s_arr is not None and len(s_arr):
                sa[s_arr[:, 0], s_arr[:, 1], s_arr[:, 2]] = True
                sv[s_arr[:, 0], s_arr[:, 1], s_arr[:, 2]] = s_arr[:, 3]
            state = apply_starts(
                state, jnp.asarray(reset), jnp.asarray(sa), jnp.asarray(sv)
            )

        if reliable:
            from tpu6824.core.kernel import paxos_step_reliable

            state, io = paxos_step_reliable(state, link, done)
        else:
            state, io = self._step_fn(state, link, done, sub, drop_req,
                                      drop_rep)
        self._state = state
        decided, done_view, touched, msgs = jax.device_get(
            (io.decided, io.done_view, io.touched, io.msgs)
        )

        with self._lock:
            # device_get output can be read-only; mirrors must be writable
            # (GC wipes recycled rows, the done() diagonal stays monotone).
            decided = np.array(decided)
            done_view = np.array(done_view)
            self.m_decided = decided
            self.m_done_view = done_view
            # done() calls that landed while the step was in flight are in
            # self._done but not yet in the device output — keep the own-done
            # diagonal monotone so Min() never transiently regresses.
            pidx = np.arange(self.P)
            done_view[:, pidx, pidx] = np.maximum(
                done_view[:, pidx, pidx], self._done)
            np.minimum.reduce(done_view, axis=2, out=self._pmin_i32)
            self._peer_min = self._pmin_i32.astype(np.int64) + 1
            ndec = int((self.m_decided >= 0).sum())
            # _decided_cells was decremented by GC for wiped cells, so this
            # delta counts decisions landing in recycled slots too.
            newly = ndec - self._decided_cells
            self._decided_cells = ndec
            self.events.bump("steps")
            self.events.bump("msgs", int(msgs))
            if newly > 0:
                self.events.bump("decided_cells", newly)
                dprintf("fabric", "step %d: +%d decided cells, %d msgs",
                        self.steps_total, newly, int(msgs))
            # Max() bookkeeping: highest seq this peer has participated in.
            seqs = np.where(touched, self._slot_seq[:, :, None], -1)  # (G,I,P)
            self._max_seq = np.maximum(self._max_seq, seqs.max(axis=1))
            self._gc_locked()
            self._stepped.notify_all()

    @property
    def steps_total(self) -> int:
        return self.events.counters().get("steps", 0)

    @property
    def msgs_total(self) -> int:
        return self.events.counters().get("msgs", 0)

    def wait_steps(self, n: int, timeout: float = 30.0):
        """Block until the fabric has advanced n more steps."""
        with self._lock:
            target = self.steps_total + n
            deadline = time.monotonic() + timeout
            while self.steps_total < target:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._running:
                    break
                self._stepped.wait(remaining)

    # ---------------------------------------------------------------- GC

    def _global_min_locked(self, g: int) -> int:
        # min over peers of Min_p, where Min_p = 1 + min_q done_view[p, q]
        # (paxos/paxos.go:420-425).  Conservative: a slot may be recycled only
        # once *every* peer has forgotten it.
        return int(self._peer_min[g].min())

    def _gc_locked(self):
        # Vectorized staleness scan: one (G, I) compare against the per-group
        # global min, instead of a Python dict walk per group per step.  The
        # common case (nothing to collect) costs one reduce + one any().
        gmin = self._peer_min.min(axis=1)  # (G,)
        stale = (self._slot_seq >= 0) & (self._slot_seq < gmin[:, None])
        if not stale.any():
            return
        gs, slots = np.nonzero(stale)
        seqs = self._slot_seq[gs, slots]
        # Array-side reclamation in bulk; only the dict/freelist/intern
        # bookkeeping stays a (minimal) Python loop.
        # Mirrors must stop reporting the old tenant immediately, and the
        # wiped cells are deducted from the running decided count so
        # decided_cells keeps crediting decisions that land in recycled
        # slots (steady-state windowed throughput).
        self._decided_cells -= int((self.m_decided[gs, slots, :] >= 0).sum())
        self.m_decided[gs, slots, :] = NO_VAL
        self._slot_seq[gs, slots] = -1
        self._pending_resets.extend(zip(gs.tolist(), slots.tolist()))
        decref = self.intern.decref
        for g, slot, seq in zip(gs.tolist(), slots.tolist(), seqs.tolist()):
            del self._seq2slot[g][seq]
            self._free[g].append(slot)
            vids = self._slot_vids[g][slot]
            if vids:
                for vid in vids:
                    decref(vid)
                self._slot_vids[g][slot] = []

    # ---------------------------------------------------------------- API

    def _slot_for_locked(self, g: int, seq: int, create: bool) -> int | None:
        slot = self._seq2slot[g].get(seq)
        if slot is not None:
            return slot
        if not create:
            return None
        if not self._free[g]:
            raise WindowFullError(
                f"group {g}: all {self.I} instance slots live; "
                f"call Done() to advance Min() (global_min={self._global_min_locked(g)})"
            )
        # O(1) LIFO pop; a freed slot's pending reset (if any) is applied
        # before the start lands (apply_starts order), so reuse is safe.
        slot = self._free[g].pop()
        self._slot_seq[g, slot] = seq
        self._seq2slot[g][seq] = slot
        return slot

    def start(self, g: int, p: int, seq: int, value) -> None:
        """paxos.Start(seq, v) for peer p of group g (paxos/paxos.go:99-109):
        asynchronous — agreement proceeds on subsequent clock steps."""
        with self._lock:
            self._start_locked(g, p, seq, value)

    def _start_locked(self, g: int, p: int, seq: int, value) -> None:
        if self._dead[g, p]:
            return
        if seq < self._peer_min[g, p]:
            return  # forgotten; reference ignores such Starts
        slot = self._seq2slot[g].get(seq)
        if slot is not None and self.m_decided[g, slot, p] >= 0:
            return  # already decided locally; nothing to do
        # Allocate the slot BEFORE interning: _slot_for_locked may raise
        # WindowFullError, and an intern ref taken first would never be
        # decref'd (leak under start-retry backpressure loops).
        slot = self._slot_for_locked(g, seq, create=True)
        if type(value) is int and 0 <= value < IMM_BASE:
            vid = IMM_BASE | value  # immediate: no store, no refcount
        else:
            vid = self.intern.put(value)
            self._slot_vids[g][slot].append(vid)
        self._pending_starts.append((g, slot, p, vid, seq))
        if seq > self._max_seq[g, p]:
            self._max_seq[g, p] = seq

    def status(self, g: int, p: int, seq: int):
        """paxos.Status (paxos/paxos.go:434-447) → (Fate, value)."""
        from tpu6824.core.peer import Fate

        with self._lock:
            if seq < self._peer_min[g, p]:
                return Fate.FORGOTTEN, None
            slot = self._seq2slot[g].get(seq)
            if slot is None:
                return Fate.PENDING, None
            vid = int(self.m_decided[g, slot, p])
            if vid < 0:
                return Fate.PENDING, None
            if vid >= IMM_BASE:
                return Fate.DECIDED, vid - IMM_BASE
            return Fate.DECIDED, self.intern.get(vid)

    # ----------------------------------------------------- batched API
    # The fabric is a batched runtime: a driver pumping hundreds of groups
    # per clock step should pay one lock acquisition per batch, not per op.
    # Semantics are exactly N calls of the scalar methods, in order.

    def start_many(self, ops) -> None:
        """Batched Start: `ops` iterates (g, p, seq, value).

        Semantically N scalar start() calls; the body is the same logic with
        the per-op numpy-scalar reads hoisted to plain-int lists (this is
        the service driver's hottest call).

        NOT atomic: on WindowFullError the prefix ops[:e.index] has been
        applied and the rest dropped — resume the batch from `e.index`
        after GC frees slots (retrying from 0 is safe but re-queues the
        prefix).  The same contract holds for the `fabric_service`
        start_many RPC."""
        with self._lock:
            dead = self._dead.tolist()
            pmin = self._peer_min.tolist()
            s2s = self._seq2slot
            item = self.m_decided.item
            free = self._free
            slot_seq = self._slot_seq
            vids = self._slot_vids
            put = self.intern.put
            pend = self._pending_starts.append
            mx = self._max_seq
            for n, (g, p, seq, value) in enumerate(ops):
                if dead[g][p] or seq < pmin[g][p]:
                    continue
                slot = s2s[g].get(seq)
                if slot is not None:
                    if item(g, slot, p) >= 0:
                        continue  # already decided locally
                else:
                    fl = free[g]
                    if not fl:
                        raise WindowFullError(
                            f"group {g}: all {self.I} instance slots live; "
                            f"call Done() to advance Min() "
                            f"(global_min={self._global_min_locked(g)}); "
                            f"batch applied up to index {n}",
                            index=n)
                    slot = fl.pop()
                    slot_seq[g, slot] = seq
                    s2s[g][seq] = slot
                if type(value) is int and 0 <= value < IMM_BASE:
                    vid = IMM_BASE | value  # immediate (see IMM_BASE)
                else:
                    vid = put(value)
                    vids[g][slot].append(vid)
                pend((g, slot, p, vid, seq))
                if seq > mx[g, p]:
                    mx[g, p] = seq

    def status_many(self, queries) -> list:
        """Batched Status: `queries` iterates (g, p, seq); returns a
        (Fate, value) list in query order."""
        from tpu6824.core.peer import Fate

        out = []
        append = out.append
        forgotten = (Fate.FORGOTTEN, None)
        pending = (Fate.PENDING, None)
        decided = Fate.DECIDED
        with self._lock:
            # Hot loop: everything hoisted; pmin as a plain nested list so
            # the per-query compare is int-vs-int, not a numpy scalar.
            pmin = self._peer_min.tolist()
            dec = self.m_decided
            item = dec.item
            s2s = self._seq2slot
            get = self.intern.get
            for g, p, seq in queries:
                if seq < pmin[g][p]:
                    append(forgotten)
                    continue
                slot = s2s[g].get(seq)
                vid = -1 if slot is None else item(g, slot, p)
                if vid < 0:
                    append(pending)
                elif vid >= IMM_BASE:
                    append((decided, vid - IMM_BASE))
                else:
                    append((decided, get(vid)))
        return out

    def done_many(self, items) -> None:
        """Batched Done: `items` iterates (g, p, seq) — one vectorized
        update + one row-min recompute per affected group, instead of a
        per-call row reduction (the RSM drain calls Done once per applied
        op per peer; this is the fabric's hottest write path)."""
        items = items if isinstance(items, list) else list(items)
        if not items:
            return
        arr = np.asarray(items, dtype=np.int64)
        if (arr[:, 2] >= np.int64(2) ** 31).any():
            raise OverflowError("done seq exceeds int32 (matches scalar "
                                "done()'s loud failure)")
        gs, ps, seqs = arr[:, 0], arr[:, 1], arr[:, 2].astype(np.int32)
        with self._lock:
            np.maximum.at(self._done, (gs, ps), seqs)
            # Own view updates without needing a message to self.
            np.maximum.at(self.m_done_view, (gs, ps, ps), seqs)
            gu = np.unique(gs)
            self._peer_min[gu] = (
                self.m_done_view[gu].min(axis=2).astype(np.int64) + 1)

    def done(self, g: int, p: int, seq: int) -> None:
        """paxos.Done (paxos/paxos.go:352-359)."""
        with self._lock:
            self._done_locked(g, p, seq)

    def _done_locked(self, g: int, p: int, seq: int) -> None:
        if seq > self._done[g, p]:
            self._done[g, p] = seq
            # Own view updates without needing a message to self.
            if seq > self.m_done_view[g, p, p]:
                self.m_done_view[g, p, p] = seq
                self._peer_min[g, p] = int(self.m_done_view[g, p].min()) + 1

    def peer_min(self, g: int, p: int) -> int:
        """paxos.Min (paxos/paxos.go:420-425): 1 + min over peers of done as
        known to p via piggybacked/heartbeat traffic."""
        with self._lock:
            return int(self._peer_min[g, p])

    def peer_max(self, g: int, p: int) -> int:
        """paxos.Max (paxos/paxos.go:385-390)."""
        with self._lock:
            return int(self._max_seq[g, p])

    # ------------------------------------------------------- network control

    def set_unreliable(self, flag: bool, g: int | None = None, p: int | None = None):
        """Per-receiving-server message loss (the accept-loop coin flips,
        paxos/paxos.go:528-544)."""
        with self._lock:
            gs = slice(None) if g is None else g
            ps = slice(None) if p is None else p
            self._unreliable[gs, ps] = flag

    def partition(self, g: int, *parts: list[int]):
        """Split group g's peers into disjoint partitions; traffic flows only
        within a partition (the socket hard-link farm,
        paxos/test_test.go:712-751).  Peers not listed are fully isolated."""
        with self._lock:
            self._link_dev = None
            self._link[g] = False
            for part in parts:
                for a in part:
                    for b in part:
                        self._link[g, a, b] = True
            # Socket surgery must not resurrect a crashed peer (heal() has
            # the same guard): dead lanes stay cut whatever the partition.
            self._apply_dead_locked(g)

    def heal(self, g: int | None = None):
        with self._lock:
            self._link_dev = None
            if g is None:
                self._link[:] = True
            else:
                self._link[g] = True
            for gg in range(self.G) if g is None else [g]:
                self._apply_dead_locked(gg)

    def deafen(self, g: int, p: int):
        """Nothing can be delivered TO peer p (socket file removed,
        paxos/test_test.go:194-195); p can still send."""
        with self._lock:
            self._link_dev = None
            self._link[g, :, p] = False

    def set_link(self, g: int, src: int, dst: int, up: bool):
        with self._lock:
            self._link_dev = None
            self._link[g, src, dst] = up

    def _apply_dead_locked(self, g: int):
        for p in range(self.P):
            if self._dead[g, p]:
                self._link[g, :, p] = False
                self._link[g, p, :] = False

    def kill(self, g: int, p: int):
        """Crash peer p of group g (paxos.Kill, paxos/paxos.go:456-461): no
        more sends or receives; its state is NOT recovered (the reference
        Paxos has no persistence)."""
        with self._lock:
            self._link_dev = None
            self._dead[g, p] = True
            self._apply_dead_locked(g)

    def revive(self, g: int, p: int):
        """Reboot a crashed peer (diskv's restart path): clears the dead flag
        and restores its links, leaving other peers' crash state intact."""
        with self._lock:
            self._link_dev = None
            self._dead[g, p] = False
            self._link[g, p, :] = True
            self._link[g, :, p] = True
            self._apply_dead_locked(g)

    def is_dead(self, g: int, p: int) -> bool:
        with self._lock:
            return bool(self._dead[g, p])

    # ------------------------------------------------------- checkpoint

    @staticmethod
    def _start_is_live(slot_seq, t, known_vids=None) -> bool:
        """Keep predicate for a queued (g, slot, p, vid, seq) start: its
        slot still maps to its seq (the vectorized form of this same test
        gates the live drain in _step_once).  With `known_vids`, also
        require the vid to have a payload (restore-side defense against
        pre-fix blobs).  One definition, three users — do not fork it."""
        g, s, _p, v, seq = t
        if slot_seq[g, s] != seq:
            return False
        return known_vids is None or v >= IMM_BASE or v in known_vids

    def checkpoint(self, path: str) -> None:
        """Snapshot the ENTIRE consensus universe — device state, host
        mirrors, slot/window bookkeeping, network condition, queued ops,
        and every live value payload — to one file, atomically
        (write-tmp + fsync + rename, the diskv file discipline,
        diskv/server.go:92-105).

        The reference's paxos is explicitly not crash-safe
        (paxos/paxos.go:3-11); its persistence story lives in diskv and in
        `HostPaxosPeer(persist_dir=...)`.  This is the batched-runtime
        analog: checkpoint/resume for all G groups at once, the way an ML
        framework checkpoints a training state pytree.

        Must be called with the clock stopped (deterministic snapshot —
        a step in flight would leave device state and mirrors torn).
        """
        import pickle

        with self._lock:
            if self._running:
                raise RuntimeError("stop_clock() before checkpoint()")
            state_np = {f: np.array(x)
                        for f, x in zip(self._state._fields, self._state)}
            # Pending window-GC resets are applied INTO the snapshot (their
            # effect is deterministic): the device arrays may still carry
            # value ids whose intern refs the GC already dropped — those
            # cells must not reach restore()'s vid remap.
            if self._pending_resets:
                r = np.asarray(self._pending_resets)
                gs, ss = r[:, 0], r[:, 1]
                for f, fill in (("np_", 0), ("na", 0), ("va", NO_VAL),
                                ("decided", NO_VAL), ("active", False),
                                ("propv", NO_VAL), ("maxseen", 0)):
                    state_np[f][gs, ss, :] = fill
            # Live payloads: every vid referenced by any slot or queued op
            # (immediate-tagged ids carry their own payload; see IMM_BASE).
            vids = sorted({v for g in range(self.G)
                           for slot in self._slot_vids[g]
                           for v in slot})
            # Everything below is COPIED under the lock: the blob must not
            # alias mutable fabric state (serialization happens outside
            # the lock, and other API threads stay free to run).
            blob = {
                "dims": (self.G, self.I, self.P),
                "kernel": self._kernel_req,
                "drops": (self._req_drop, self._rep_drop),
                "state": state_np,
                "link": self._link.copy(),
                "unreliable": self._unreliable.copy(),
                "done": self._done.copy(), "dead": self._dead.copy(),
                "m_decided": self.m_decided.copy(),
                "m_done_view": self.m_done_view.copy(),
                "max_seq": self._max_seq.copy(),
                "slot_seq": self._slot_seq.copy(),
                "seq2slot": [dict(d) for d in self._seq2slot],
                "free": [list(s) for s in self._free],
                "slot_vids": [[list(v) for v in grp]
                              for grp in self._slot_vids],
                "values": {v: self.intern.get(v) for v in vids},
                # _start_is_live: a start queued mid-step whose slot the
                # end-of-step GC recycled still sits in the queue with a
                # decref'd vid — snapshotting it verbatim would make the
                # file unrestorable (restore()'s vid remap lacks it).
                "pending_starts": [
                    t for t in self._pending_starts
                    if self._start_is_live(self._slot_seq, t)],
                "pending_resets": [],  # applied into the snapshot above
                "key_data": np.array(jax.random.key_data(self._key)),
            }
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            f.write(pickle.dumps(blob, protocol=pickle.HIGHEST_PROTOCOL))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    @classmethod
    def restore(cls, path: str, **kw) -> "PaxosFabric":
        """Resume a checkpointed fabric.  Interned value ids are REMAPPED
        through a fresh intern store (so either intern backend restores
        into either), with the device arrays rewritten through the same
        old→new lookup; immediate-tagged ids pass through unchanged.
        PRNG subkey batching restarts at the saved base key, so post-
        restore lossy draws differ from an uninterrupted run (determinism
        holds per process lifetime, not across the boundary)."""
        import pickle

        with open(path, "rb") as f:
            blob = pickle.loads(f.read())
        G, I, P = blob["dims"]
        kw.setdefault("kernel", blob["kernel"])
        kw.setdefault("unreliable_req_drop", blob["drops"][0])
        kw.setdefault("unreliable_rep_drop", blob["drops"][1])
        # The clock must not run while state is being swapped in.
        auto_step = kw.pop("auto_step", False)
        fab = cls(ngroups=G, npeers=P, ninstances=I, **kw)
        with fab._lock:
            # Rebuild the intern with exactly one ref per _slot_vids entry
            # (the GC decrefs one per entry), building the old->new map —
            # any device vid absent from it fails LOUDLY in remap (the
            # checkpoint invariant is that no such vid exists).
            old2new = {}
            new_vids = [[[] for _ in range(I)] for _ in range(G)]
            for g in range(G):
                for slot in range(I):
                    for old_vid in blob["slot_vids"][g][slot]:
                        nv = fab.intern.put(blob["values"][old_vid])
                        old2new[old_vid] = nv
                        new_vids[g][slot].append(nv)
            fab._slot_vids = new_vids

            def remap(a):
                a = np.array(a)
                m = (a >= 0) & (a < IMM_BASE)
                if m.any():
                    a[m] = np.vectorize(
                        lambda v: old2new[v], otypes=[np.int64])(a[m])
                return a

            st = {f: np.array(v) for f, v in blob["state"].items()}
            for f in ("va", "decided", "propv"):
                st[f] = remap(st[f]).astype(st[f].dtype)
            fab._state = type(fab._state)(**{
                f: jnp.asarray(v) for f, v in st.items()})
            fab._link = np.array(blob["link"])
            fab._link_dev = None
            fab._unreliable = np.array(blob["unreliable"])
            fab._done = np.array(blob["done"])
            fab._dead = np.array(blob["dead"])
            fab.m_decided = remap(blob["m_decided"]).astype(np.int32)
            fab.m_done_view = np.array(blob["m_done_view"])
            np.minimum.reduce(fab.m_done_view, axis=2, out=fab._pmin_i32)
            fab._peer_min = fab._pmin_i32.astype(np.int64) + 1
            fab._max_seq = np.array(blob["max_seq"])
            fab._slot_seq = np.array(blob["slot_seq"])
            fab._seq2slot = [dict(d) for d in blob["seq2slot"]]
            fab._free = [list(s) for s in blob["free"]]
            fab._decided_cells = int((fab.m_decided >= 0).sum())
            # Defensive twin of checkpoint()'s keep-filter (pre-fix blobs
            # may carry GC-orphaned entries): same _start_is_live test,
            # plus the vid-has-a-payload check.
            fab._pending_starts = [
                (g, s, p, v if v >= IMM_BASE else old2new[v], seq)
                for g, s, p, v, seq in blob["pending_starts"]
                if cls._start_is_live(fab._slot_seq, (g, s, p, v, seq),
                                      old2new)]
            fab._pending_resets = list(blob["pending_resets"])
            fab._key = jax.random.wrap_key_data(jnp.asarray(blob["key_data"]))
            fab._key_buf = []
        if auto_step:
            fab.start_clock()
        return fab

    # ------------------------------------------------------------- stats

    def stats(self) -> dict:
        """Live counters: steps, remote messages, decided cells, and their
        per-second rates — the decided/sec counter SURVEY §5 asks for."""
        counters = self.events.counters()
        with self._lock:
            out = {
                "steps": counters.get("steps", 0),
                "msgs": counters.get("msgs", 0),
                "decided_cells": self._decided_cells,
                "groups": self.G,
                "instances": self.I,
                "peers": self.P,
            }
        out["rates"] = self.events.rates()
        return out

    def ndecided(self, g: int, seq: int) -> int:
        """Test helper mirroring paxos/test_test.go:32-49: asserts agreement
        and returns how many peers have decided `seq`."""
        with self._lock:
            slot = self._seq2slot[g].get(seq)
            if slot is None:
                return 0
            d = self.m_decided[g, slot]
        vals = d[d >= 0]
        if len(vals):
            assert (vals == vals[0]).all(), f"seq {seq}: peers disagree: {d}"
        return int((d >= 0).sum())
